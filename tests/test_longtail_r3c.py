"""Round-3 long-tail tranche C: hermitian FFTs, LKJCholesky /
StackTransform / ExponentialFamily, geometric heter-graph ops,
PSRoIPool, Bilinear init, incubate fused layers, static save/load +
static.nn legacy layers, dist.split / shard_optimizer / PS datasets,
Tensor inplace long tail."""

import numpy as np
import pytest
import scipy.fft as sfft

import paddle_tpu as paddle
from paddle_tpu import static


class TestHermitianFFT:
    def test_hfft2_matches_scipy(self):
        rng = np.random.RandomState(0)
        a = (rng.randn(4, 6) + 1j * rng.randn(4, 6)).astype(np.complex64)
        for norm in ("backward", "ortho", "forward"):
            out = paddle.fft.hfft2(paddle.to_tensor(a), norm=norm)
            np.testing.assert_allclose(
                np.asarray(out.numpy()), sfft.hfft2(a, norm=norm),
                rtol=2e-3, atol=2e-3)

    def test_ihfft2_matches_scipy(self):
        r = np.random.RandomState(1).randn(4, 6).astype(np.float32)
        for norm in ("backward", "ortho", "forward"):
            out = paddle.fft.ihfft2(paddle.to_tensor(r), norm=norm)
            np.testing.assert_allclose(
                np.asarray(out.numpy()), sfft.ihfft2(r, norm=norm),
                rtol=1e-4, atol=1e-5)

    def test_hfftn_ihfftn_roundtrip_shapes(self):
        rng = np.random.RandomState(2)
        a = (rng.randn(3, 4, 5) + 1j * rng.randn(3, 4, 5)).astype(
            np.complex64)
        out = paddle.fft.hfftn(paddle.to_tensor(a))
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   sfft.hfftn(a), rtol=2e-3, atol=2e-3)
        back = paddle.fft.ihfftn(out)
        assert back.shape == list(sfft.ihfftn(np.asarray(out.numpy())).shape)


class TestDistributionLongTail:
    def test_lkj_cholesky_samples_valid(self):
        paddle.seed(0)
        d = paddle.distribution.LKJCholesky(4, concentration=2.0)
        L = np.asarray(d.sample((8,)).numpy())
        corr = L @ np.swapaxes(L, -1, -2)
        np.testing.assert_allclose(
            np.diagonal(corr, axis1=-2, axis2=-1), 1.0, atol=1e-5)
        # lower-triangular with positive diagonal
        assert np.allclose(np.triu(L, 1), 0.0, atol=1e-6)
        assert (np.diagonal(L, axis1=-2, axis2=-1) > 0).all()

    def test_lkj_log_prob_uniform_at_concentration_one(self):
        # at concentration 1 the density over correlation matrices is
        # uniform → log_prob depends only on the jacobian diag terms
        d = paddle.distribution.LKJCholesky(3, concentration=1.0)
        paddle.seed(1)
        s = d.sample((2,))
        lp = np.asarray(d.log_prob(s).numpy())
        assert lp.shape == (2,) and np.isfinite(lp).all()

    @pytest.mark.slow
    def test_lkj_dim2_concentration1_marginal_uniform(self):
        # at dim=2, c=1 the correlation r is uniform on [-1, 1]:
        # r = L[1,0], and r² ~ Beta(1/2, 1)  →  E[r²] = 1/3
        paddle.seed(7)
        d = paddle.distribution.LKJCholesky(2, concentration=1.0)
        L = np.asarray(d.sample((1500,)).numpy())
        r = L[:, 1, 0]
        assert abs(r.mean()) < 0.05
        np.testing.assert_allclose((r ** 2).mean(), 1.0 / 3.0, atol=0.04)

    def test_stack_transform(self):
        st = paddle.distribution.StackTransform(
            [paddle.distribution.ExpTransform(),
             paddle.distribution.AffineTransform(0.0, 3.0)], axis=0)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        y = np.asarray(st.forward(x).numpy())
        np.testing.assert_allclose(y[0], np.e, rtol=1e-5)
        np.testing.assert_allclose(y[1], 3.0, rtol=1e-6)
        back = np.asarray(st.inverse(st.forward(x)).numpy())
        np.testing.assert_allclose(back, 1.0, rtol=1e-5)

    def test_exponential_family_entropy_via_bregman(self):
        import jax.numpy as jnp

        class _NormalEF(paddle.distribution.ExponentialFamily):
            # N(μ, σ²) with η = (μ/σ², −1/(2σ²)), t(x) = (x, x²),
            # h(x) = 1/√(2π) so E[log h] is a constant
            def __init__(self, loc, scale):
                self.loc = paddle.to_tensor(loc)
                self.scale = paddle.to_tensor(scale)
                self._mean_carrier_measure = -0.5 * np.log(2 * np.pi)

            @property
            def _natural_parameters(self):
                var = self.scale * self.scale
                return (self.loc / var, -0.5 / var)

            def _log_normalizer(self, e1, e2):
                return (-e1 * e1 / (4 * e2)
                        + 0.5 * jnp.log(jnp.pi / (-e2))
                        - 0.5 * jnp.log(2 * jnp.pi))

        ent = np.asarray(
            _NormalEF(np.float32(1.7), np.float32(1.3)).entropy().numpy())
        expect = 0.5 * np.log(2 * np.pi * np.e * 1.3 ** 2)
        np.testing.assert_allclose(ent, expect, rtol=1e-5)


class TestGeometricLongTail:
    def _csc(self):
        # graph: 0<-1, 0<-2, 1<-2 (rows = sources per dst column)
        row = paddle.to_tensor(np.array([1, 2, 2], np.int64))
        colptr = paddle.to_tensor(np.array([0, 2, 3, 3], np.int64))
        return row, colptr

    def test_weighted_sample_neighbors(self):
        row, colptr = self._csc()
        w = paddle.to_tensor(np.array([1.0, 100.0, 1.0], np.float32))
        paddle.seed(0)
        neigh, cnt = paddle.geometric.weighted_sample_neighbors(
            row, colptr, w, paddle.to_tensor(np.array([0], np.int64)),
            sample_size=1)
        assert int(cnt.numpy()[0]) == 1
        # heavily-weighted neighbor 2 dominates
        assert int(neigh.numpy()[0]) in (1, 2)

    def test_weighted_sample_zero_weight_edges_skipped(self):
        # node 0 has neighbors [0, 1, 2] but only neighbor 1 has positive
        # weight — sampling 2 must return just that one, not crash on
        # 'fewer non-zero entries in p than size'
        row = paddle.to_tensor(np.array([0, 1, 2], np.int64))
        colptr = paddle.to_tensor(np.array([0, 3, 3, 3], np.int64))
        w = paddle.to_tensor(np.array([0.0, 5.0, 0.0], np.float32))
        paddle.seed(3)
        neigh, cnt = paddle.geometric.weighted_sample_neighbors(
            row, colptr, w, paddle.to_tensor(np.array([0], np.int64)),
            sample_size=2)
        assert int(cnt.numpy()[0]) == 1
        assert int(neigh.numpy()[0]) == 1

    def test_reindex_heter_graph(self):
        x = paddle.to_tensor(np.array([10, 11], np.int64))
        n1 = paddle.to_tensor(np.array([20, 10], np.int64))
        c1 = paddle.to_tensor(np.array([1, 1], np.int32))
        n2 = paddle.to_tensor(np.array([30], np.int64))
        c2 = paddle.to_tensor(np.array([1, 0], np.int32))
        src, dst, nodes = paddle.geometric.reindex_heter_graph(
            x, [n1, n2], [c1, c2])
        assert list(nodes.numpy()) == [10, 11, 20, 30]
        assert list(src.numpy()) == [2, 0, 3]
        assert list(dst.numpy()) == [0, 1, 0]


class TestVisionInitIncubate:
    def test_psroi_pool_layer(self):
        layer = paddle.vision.ops.PSRoIPool(2, 1.0)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(1, 4 * 3, 6, 6).astype(
                np.float32))
        boxes = paddle.to_tensor(np.array([[0, 0, 5, 5]], np.float32))
        num = paddle.to_tensor(np.array([1], np.int32))
        out = layer(x, boxes, num)
        assert list(out.shape) == [1, 3, 2, 2]

    def test_bilinear_initializer(self):
        w = np.asarray(paddle.nn.initializer.Bilinear()((1, 1, 4, 4),
                                                        "float32"))
        # separable triangle kernel, symmetric, peak in the middle
        np.testing.assert_allclose(w[0, 0], w[0, 0].T, rtol=1e-6)
        assert w[0, 0, 1:3, 1:3].min() > w[0, 0, 0, 0]

    def test_fused_ec_moe_layer_gate_logits(self):
        paddle.seed(0)
        m = paddle.incubate.nn.FusedEcMoe(8, 16, 4, act_type="gelu")
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 3, 8).astype(np.float32))
        gate = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 3, 4).astype(np.float32))
        out = m(x, gate)
        assert list(out.shape) == [2, 3, 8]
        assert np.isfinite(np.asarray(out.numpy())).all()

    def test_fused_ec_moe_square_x_prefers_logits(self):
        # x has as many tokens as hidden dims: the per-token logits
        # reading (documented signature) must win over the weight one
        paddle.seed(0)
        E, d = 4, 6
        m = paddle.incubate.nn.FusedEcMoe(d, 8, E)
        x = paddle.to_tensor(
            np.random.RandomState(5).randn(d, d).astype(np.float32))
        one_hot = np.full((d, E), -1e9, np.float32)
        one_hot[:, 1] = 0.0  # route everything to expert 1
        out = np.asarray(m(x, paddle.to_tensor(one_hot)).numpy())
        w0 = np.asarray(m.bmm0_weight.numpy())[1]
        b0 = np.asarray(m.bmm0_bias.numpy())[1].reshape(-1)
        w1 = np.asarray(m.bmm1_weight.numpy())[1]
        b1 = np.asarray(m.bmm1_bias.numpy())[1].reshape(-1)
        from scipy.special import erf
        h = np.asarray(x.numpy()) @ w0 + b0
        h = 0.5 * h * (1 + erf(h / np.sqrt(2)))
        expect = h @ w1 + b1
        np.testing.assert_allclose(out, expect, rtol=2e-4, atol=2e-5)

    def test_fused_dropout_add_eval_identity(self):
        m = paddle.incubate.nn.FusedDropoutAdd(p=0.9)
        m.eval()
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        np.testing.assert_allclose(np.asarray(m(x, x).numpy()), 2.0)

    def test_fused_matmul_bias_transposes(self):
        rng = np.random.RandomState(2)
        a = rng.randn(3, 4).astype(np.float32)
        b = rng.randn(5, 4).astype(np.float32)
        bias = rng.randn(5).astype(np.float32)
        out = paddle.incubate.nn.functional.fused_matmul_bias(
            paddle.to_tensor(a), paddle.to_tensor(b),
            paddle.to_tensor(bias), transpose_y=True)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   a @ b.T + bias, rtol=1e-5, atol=1e-5)


class TestStaticLongTail:
    def test_places_and_weightnorm_attr(self):
        assert static.ipu_places() == []
        assert static.npu_places() == []
        assert static.xpu_places() == []
        attr = static.WeightNormParamAttr(dim=0, name="w")
        assert attr.dim == 0 and attr.name == "w"

    def test_weight_norm_param_attr_applied(self):
        prog = static.Program()

        @prog.capture
        def build(feed):
            return {"o": static.nn.fc(
                feed["x"], 4,
                weight_attr=static.WeightNormParamAttr(dim=1))}

        exe = static.Executor()
        exe.run(prog, feed={"x": np.ones((2, 3), np.float32)},
                fetch_list=["o"])
        layer = prog._layer_slots[0]
        names = [n for n, _ in layer.named_parameters()]
        assert any("weight_g" in n for n in names), names

    def test_save_load_roundtrip(self, tmp_path):
        prog = static.Program()

        @prog.capture
        def build(feed):
            return {"out": static.nn.fc(feed["x"], 3)}

        exe = static.Executor()
        x = np.ones((2, 4), np.float32)
        out0 = exe.run(prog, feed={"x": x}, fetch_list=["out"])[0]
        path = str(tmp_path / "ckpt")
        static.save(prog, path)
        state = static.load_program_state(path)
        # perturb, then restore
        static.set_program_state(
            prog, {k: np.zeros_like(v) for k, v in state.items()})
        zeroed = exe.run(prog, feed={"x": x}, fetch_list=["out"])[0]
        np.testing.assert_allclose(zeroed, 0.0)
        static.load(prog, path)
        out1 = exe.run(prog, feed={"x": x}, fetch_list=["out"])[0]
        np.testing.assert_allclose(out1, out0, rtol=1e-6)

    def test_static_nn_norm_layers(self):
        prog = static.Program()

        @prog.capture
        def build(feed):
            h = static.nn.group_norm(feed["x"], 2)
            h = static.nn.instance_norm(h)
            h = static.nn.data_norm(h.reshape([2, -1]))
            return {"out": h}

        exe = static.Executor()
        x = np.random.RandomState(0).randn(2, 4, 5, 5).astype(np.float32)
        out = exe.run(prog, feed={"x": x}, fetch_list=["out"])[0]
        assert out.shape == (2, 100) and np.isfinite(out).all()

    def test_static_nn_nce_and_row_conv(self):
        prog = static.Program()

        @prog.capture
        def build(feed):
            loss = static.nn.nce(feed["h"], feed["y"], 12,
                                 num_neg_samples=3)
            rc = static.nn.row_conv(feed["t"], 2)
            return {"loss": loss, "rc": rc}

        exe = static.Executor()
        h = np.random.RandomState(1).randn(4, 6).astype(np.float32)
        y = np.random.RandomState(2).randint(0, 12, (4, 1)).astype(
            np.int64)
        t = np.ones((2, 5, 3), np.float32)
        loss, rc = exe.run(prog, feed={"h": h, "y": y, "t": t},
                           fetch_list=["loss", "rc"])
        assert loss.shape == (4, 1) and np.isfinite(loss).all()
        np.testing.assert_allclose(rc, 0.0)  # zero-init lookahead weight

    def test_static_nn_spectral_norm_unit_sigma(self):
        prog = static.Program()

        @prog.capture
        def build(feed):
            return {"o": static.nn.spectral_norm(feed["w"], dim=0,
                                                 power_iters=20)}

        exe = static.Executor()
        w = np.random.RandomState(3).randn(6, 4).astype(np.float32) * 5
        o = exe.run(prog, feed={"w": w}, fetch_list=["o"])[0]
        sigma = np.linalg.svd(o, compute_uv=False)[0]
        np.testing.assert_allclose(sigma, 1.0, atol=0.1)

    def test_static_pylayer_custom_backward(self):
        x = paddle.to_tensor(np.ones(3, np.float32))
        x.stop_gradient = False
        out = static.nn.static_pylayer(
            lambda a: a * 2, [x], backward_fn=lambda g: g * 10)
        out.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.numpy()), 10.0)

    def test_sparse_embedding_desugars(self):
        prog = static.Program()

        @prog.capture
        def build(feed):
            return {"e": static.nn.sparse_embedding(feed["ids"], [16, 4])}

        exe = static.Executor()
        ids = np.array([[1, 2]], np.int64)
        e = exe.run(prog, feed={"ids": ids}, fetch_list=["e"])[0]
        assert e.shape == (1, 2, 4)


class TestDistributedLongTail:
    def test_split_linear_and_embedding_eager(self):
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        out = paddle.distributed.split(x, (4, 6), operation="linear",
                                       axis=1)
        assert list(out.shape) == [2, 6]
        ids = paddle.to_tensor(np.array([[0, 3]], np.int64))
        emb = paddle.distributed.split(ids, (10, 3),
                                       operation="embedding")
        assert list(emb.shape) == [1, 2, 3]

    def test_split_reuses_weights_inside_program(self):
        prog = static.Program()

        @prog.capture
        def build(feed):
            return {"o": paddle.distributed.split(
                feed["x"], (4, 5), operation="linear", axis=1)}

        exe = static.Executor()
        x = np.ones((2, 4), np.float32)
        a = exe.run(prog, feed={"x": x}, fetch_list=["o"])[0]
        b = exe.run(prog, feed={"x": x}, fetch_list=["o"])[0]
        np.testing.assert_allclose(a, b)

    def test_shard_optimizer_wraps_and_steps(self):
        m = paddle.nn.Linear(4, 4)
        calls = []

        def shard_fn(name, param, acc):
            calls.append(name)
            return acc

        opt = paddle.distributed.shard_optimizer(
            paddle.optimizer.AdamW(1e-3, parameters=m.parameters()),
            shard_fn)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        m(x).sum().backward()
        opt.step()
        opt.clear_grad()
        assert calls, "shard_fn never invoked on new accumulators"

    def test_shard_optimizer_replaces_after_state_restore(self):
        m = paddle.nn.Linear(3, 3)
        placed = []
        opt = paddle.distributed.shard_optimizer(
            paddle.optimizer.AdamW(1e-3, parameters=m.parameters()),
            lambda name, p, acc: placed.append(name) or acc)
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        m(x).sum().backward()
        opt.step()
        n_first = len(placed)
        assert n_first > 0
        # restoring state overwrites accumulator tensors in place — the
        # wrapper must re-place them all, not skip via the stale cache
        opt.set_state_dict(opt.state_dict())
        assert len(placed) >= 2 * n_first

    def test_split_validates_num_partitions(self):
        from paddle_tpu.distributed import fleet
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": -1, "mp_degree": 2,
                                   "pp_degree": 1, "sharding_degree": 1,
                                   "sep_degree": 1, "ep_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        try:
            x = paddle.to_tensor(np.ones((2, 4), np.float32))
            with pytest.raises(ValueError):
                paddle.distributed.split(x, (4, 6), operation="linear",
                                         axis=1, num_partitions=3)
            out = paddle.distributed.split(x, (4, 6), operation="linear",
                                           axis=1, num_partitions=2)
            assert list(out.shape) == [2, 6]
        finally:
            fleet.fleet._hcg = None
            fleet.fleet._topology = None
            fleet.fleet._is_initialized = False

    def test_placement_export(self):
        assert issubclass(paddle.distributed.Shard,
                          paddle.distributed.Placement)

    def test_inmemory_dataset(self, tmp_path):
        f = tmp_path / "slots.txt"
        f.write_text("1 2.5 3\n4 5 6\n7 8 9\n")
        ds = paddle.distributed.InMemoryDataset()
        ds.init(batch_size=2, use_var=["a", "b", "c"])
        ds.set_filelist([str(f)])
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 3
        ds.local_shuffle()
        total = sum(len(b) for b in ds)
        assert total == 3
        ds.release_memory()

    def test_queue_dataset_pipe_command(self, tmp_path):
        f = tmp_path / "slots.txt"
        f.write_text("1 2\n3 4\n5 6\n")
        ds = paddle.distributed.QueueDataset()
        ds.init(batch_size=2, pipe_command="head -2")
        ds.set_filelist([str(f)])
        assert sum(len(b) for b in ds) == 2

    def test_gloo_barrier_single_process(self):
        paddle.distributed.gloo_barrier()  # no-op at world size 1


class TestTensorInplaceLongTail:
    def test_index_add_(self):
        t = paddle.to_tensor(np.zeros((3, 4), np.float32))
        t.index_add_(paddle.to_tensor(np.array([0, 2])), 0,
                     paddle.to_tensor(np.ones((2, 4), np.float32)))
        assert np.asarray(t.numpy()).sum() == 8

    def test_index_put_(self):
        t = paddle.to_tensor(np.zeros(5, np.float32))
        t.index_put_([paddle.to_tensor(np.array([1, 3]))],
                     paddle.to_tensor(np.array([7.0, 8.0], np.float32)))
        assert np.asarray(t.numpy())[3] == 8

    def test_scatter_(self):
        t = paddle.to_tensor(np.zeros((4, 2), np.float32))
        t.scatter_(paddle.to_tensor(np.array([2, 1])),
                   paddle.to_tensor(np.ones((2, 2), np.float32)))
        got = np.asarray(t.numpy())
        assert got[2, 0] == 1 and got[1, 1] == 1 and got[0, 0] == 0

    def test_gradient_legacy(self):
        x = paddle.to_tensor(np.ones(3, np.float32))
        x.stop_gradient = False
        (x * x).sum().backward()
        np.testing.assert_allclose(x.gradient(), 2.0)
        y = paddle.to_tensor(np.ones(2, np.float32))
        assert y.gradient() is None
