"""Tests for paddle.nn.utils (weight_norm, spectral_norm,
parameters_to_vector, grad clipping) — SURVEY.md §2.2 `paddle.nn` row."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class TestVectorize:
    def test_roundtrip(self):
        paddle.seed(0)
        lin = nn.Linear(3, 4)
        vec = nn.utils.parameters_to_vector(lin.parameters())
        assert vec.shape == [16]
        w0 = lin.weight.numpy().copy()
        nn.utils.vector_to_parameters(vec * 2.0, lin.parameters())
        np.testing.assert_allclose(lin.weight.numpy(), w0 * 2.0, rtol=1e-6)

    def test_size_mismatch_raises(self):
        lin = nn.Linear(2, 2)
        with pytest.raises(ValueError, match="elements"):
            nn.utils.vector_to_parameters(
                paddle.to_tensor(np.zeros(99, "float32")),
                lin.parameters())

    def test_vector_grad_flows(self):
        paddle.seed(0)
        lin = nn.Linear(2, 2)
        v = nn.utils.parameters_to_vector(lin.parameters())
        (v * v).sum().backward()
        assert lin.weight.grad is not None
        np.testing.assert_allclose(lin.weight.grad.numpy(),
                                   2 * lin.weight.numpy(), rtol=1e-5)


class TestClipValue:
    def test_clips_in_place(self):
        lin = nn.Linear(2, 2)
        (lin(paddle.to_tensor(np.full((1, 2), 100.0, "float32")))
         .sum() * 100.0).backward()
        nn.utils.clip_grad_value_(lin.parameters(), 1.0)
        for p in lin.parameters():
            assert np.abs(p.grad.numpy()).max() <= 1.0


class TestWeightNorm:
    def test_preserves_function_and_splits_params(self):
        paddle.seed(0)
        lin = nn.Linear(4, 3)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 4).astype("float32"))
        ref = lin(x).numpy()
        nn.utils.weight_norm(lin, "weight", dim=0)
        names = [n for n, _ in lin.named_parameters()]
        assert "weight_g" in names and "weight_v" in names
        assert "weight" not in names
        np.testing.assert_allclose(lin(x).numpy(), ref, atol=1e-5)

    def test_grad_flows_to_g_and_v(self):
        paddle.seed(0)
        lin = nn.Linear(4, 3)
        nn.utils.weight_norm(lin)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 4).astype("float32"))
        lin(x).sum().backward()
        g = dict(lin.named_parameters())
        assert g["weight_g"].grad is not None
        assert g["weight_v"].grad is not None
        assert np.isfinite(g["weight_v"].grad.numpy()).all()

    def test_training_with_weight_norm(self):
        paddle.seed(0)
        lin = nn.Linear(4, 1)
        nn.utils.weight_norm(lin)
        opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(16, 4).astype("float32"))
        y = paddle.to_tensor(rng.randn(16, 1).astype("float32"))
        losses = []
        for _ in range(20):
            loss = nn.functional.mse_loss(lin(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.item()))
        assert losses[-1] < losses[0] * 0.7

    def test_remove_weight_norm(self):
        paddle.seed(0)
        lin = nn.Linear(4, 3)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 4).astype("float32"))
        nn.utils.weight_norm(lin)
        ref = lin(x).numpy()
        nn.utils.remove_weight_norm(lin)
        names = [n for n, _ in lin.named_parameters()]
        assert "weight" in names and "weight_g" not in names
        np.testing.assert_allclose(lin(x).numpy(), ref, atol=1e-5)

    def test_double_apply_raises(self):
        lin = nn.Linear(2, 2)
        nn.utils.weight_norm(lin)
        with pytest.raises(RuntimeError, match="already"):
            nn.utils.weight_norm(lin)


class TestSpectralNorm:
    def test_unit_spectral_radius(self):
        paddle.seed(0)
        lin = nn.Linear(6, 8)
        nn.utils.spectral_norm(lin, n_power_iterations=20)
        x = paddle.to_tensor(np.eye(6, dtype="float32"))
        lin(x)  # recompute via hook
        w = lin.weight.numpy()
        smax = np.linalg.svd(w, compute_uv=False).max()
        np.testing.assert_allclose(smax, 1.0, atol=1e-2)

    def test_grad_flows(self):
        paddle.seed(0)
        lin = nn.Linear(3, 3)
        nn.utils.spectral_norm(lin)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 3).astype("float32"))
        lin(x).sum().backward()
        g = dict(lin.named_parameters())
        assert g["weight_orig"].grad is not None

    @pytest.mark.xfail(
        reason="pre-existing: seed-3's 6x8 matrix has a slow eigengap — "
               "30 single-iteration power steps converge sigma only to "
               "~3%, outside the 1e-2 bar (u-persistence itself is "
               "covered by the 20-iteration test above)", strict=False)
    def test_default_iterations_converge_across_forwards(self):
        # u must persist between calls: with n_power_iterations=1, sigma
        # converges over repeated forwards (torch/paddle semantics)
        paddle.seed(3)
        lin = nn.Linear(6, 8)
        nn.utils.spectral_norm(lin)  # default: 1 iteration
        x = paddle.to_tensor(np.eye(6, dtype="float32"))
        for _ in range(30):
            lin(x)
        smax = np.linalg.svd(lin.weight.numpy(), compute_uv=False).max()
        np.testing.assert_allclose(smax, 1.0, atol=1e-2)
