"""Op tests: math/reduction ops vs NumPy oracle + grad checks
(reference pattern: test/legacy_test/test_*_op.py, SURVEY.md §4)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_forward, check_grad

rng = np.random.RandomState(0)


UNARY_CASES = [
    ("exp", paddle.exp, np.exp, (3, 4), (-2, 2)),
    ("log", paddle.log, np.log, (3, 4), (0.1, 3)),
    ("sqrt", paddle.sqrt, np.sqrt, (3, 4), (0.1, 3)),
    ("abs", paddle.abs, np.abs, (3, 4), (-2, 2)),
    ("sin", paddle.sin, np.sin, (3, 4), (-3, 3)),
    ("cos", paddle.cos, np.cos, (3, 4), (-3, 3)),
    ("tanh", paddle.tanh, np.tanh, (3, 4), (-2, 2)),
    ("sigmoid", paddle.sigmoid, lambda x: 1 / (1 + np.exp(-x)), (3, 4),
     (-2, 2)),
    ("square", paddle.square, np.square, (3, 4), (-2, 2)),
    ("rsqrt", paddle.rsqrt, lambda x: 1 / np.sqrt(x), (3, 4), (0.5, 2)),
    ("log1p", paddle.log1p, np.log1p, (3, 4), (-0.5, 2)),
    ("expm1", paddle.expm1, np.expm1, (3, 4), (-1, 1)),
    ("floor", paddle.floor, np.floor, (3, 4), (-2, 2)),
    ("ceil", paddle.ceil, np.ceil, (3, 4), (-2, 2)),
    ("reciprocal", paddle.reciprocal, lambda x: 1.0 / x, (3, 4), (0.5, 2)),
    ("erf", paddle.erf, None, (3, 4), (-2, 2)),
]


@pytest.mark.parametrize("name,op,ref,shape,rng_range", UNARY_CASES,
                         ids=[c[0] for c in UNARY_CASES])
def test_unary_forward(name, op, ref, shape, rng_range):
    lo, hi = rng_range
    x = rng.uniform(lo, hi, shape).astype(np.float32)
    if ref is None:
        import scipy.special
        ref = getattr(scipy.special, name, None)
        if ref is None:
            pytest.skip("no oracle")
    # fp32 transcendentals: XLA:CPU's vectorized approximations differ from
    # libm in the last few ulps
    check_forward(lambda x: op(x), lambda x: ref(x), {"x": x}, rtol=5e-4,
                  atol=1e-5)


@pytest.mark.parametrize("name", ["exp", "log", "sqrt", "sin", "tanh",
                                  "sigmoid", "square"])
def test_unary_grad(name):
    op = getattr(paddle, name)
    lo, hi = (0.5, 2) if name in ("log", "sqrt") else (-1.5, 1.5)
    x = rng.uniform(lo, hi, (3, 4)).astype(np.float32)
    check_grad(lambda x: op(x), {"x": x})


BINARY_CASES = [
    ("add", paddle.add, np.add),
    ("subtract", paddle.subtract, np.subtract),
    ("multiply", paddle.multiply, np.multiply),
    ("divide", paddle.divide, np.divide),
    ("maximum", paddle.maximum, np.maximum),
    ("minimum", paddle.minimum, np.minimum),
    ("pow", paddle.pow, np.power),
    ("atan2", paddle.atan2, np.arctan2),
]


@pytest.mark.parametrize("name,op,ref", BINARY_CASES,
                         ids=[c[0] for c in BINARY_CASES])
def test_binary_forward(name, op, ref):
    x = rng.uniform(0.5, 2, (3, 4)).astype(np.float32)
    y = rng.uniform(0.5, 2, (3, 4)).astype(np.float32)
    check_forward(lambda x, y: op(x, y), lambda x, y: ref(x, y),
                  {"x": x, "y": y})


def test_binary_broadcast():
    x = rng.rand(3, 1, 4).astype(np.float32)
    y = rng.rand(2, 4).astype(np.float32)
    check_forward(lambda x, y: paddle.add(x, y), lambda x, y: x + y,
                  {"x": x, "y": y})


def test_binary_grad():
    x = rng.uniform(0.5, 2, (3, 4)).astype(np.float32)
    y = rng.uniform(0.5, 2, (4,)).astype(np.float32)  # broadcast grad
    check_grad(lambda x, y: paddle.multiply(x, y), {"x": x, "y": y})
    check_grad(lambda x, y: paddle.divide(x, y), {"x": x, "y": y})


REDUCE_CASES = [
    ("sum", paddle.sum, np.sum),
    ("mean", paddle.mean, np.mean),
    ("max", paddle.max, np.max),
    ("min", paddle.min, np.min),
    ("prod", paddle.prod, np.prod),
]


@pytest.mark.parametrize("name,op,ref", REDUCE_CASES,
                         ids=[c[0] for c in REDUCE_CASES])
@pytest.mark.parametrize("axis,keepdim", [(None, False), (0, False),
                                          (1, True), ([0, 1], False)])
def test_reduce_forward(name, op, ref, axis, keepdim):
    x = rng.rand(3, 4, 5).astype(np.float32)
    np_axis = tuple(axis) if isinstance(axis, list) else axis
    check_forward(
        lambda x: op(x, axis=axis, keepdim=keepdim),
        lambda x: ref(x, axis=np_axis, keepdims=keepdim),
        {"x": x})


def test_reduce_grad():
    x = rng.rand(3, 4).astype(np.float32)
    check_grad(lambda x: paddle.sum(x, axis=1), {"x": x})
    check_grad(lambda x: paddle.mean(x), {"x": x})
    check_grad(lambda x: paddle.max(x, axis=0), {"x": x})


def test_cumsum():
    x = rng.rand(3, 4).astype(np.float32)
    check_forward(lambda x: paddle.cumsum(x, axis=1),
                  lambda x: np.cumsum(x, axis=1), {"x": x})
    check_grad(lambda x: paddle.cumsum(x, axis=0), {"x": x})


def test_logsumexp():
    import scipy.special
    x = rng.rand(3, 4).astype(np.float32)
    check_forward(lambda x: paddle.logsumexp(x, axis=1),
                  lambda x: scipy.special.logsumexp(x, axis=1), {"x": x},
                  rtol=1e-5, atol=1e-5)


def test_clip():
    x = rng.uniform(-2, 2, (3, 4)).astype(np.float32)
    check_forward(lambda x: paddle.clip(x, -1.0, 1.0),
                  lambda x: np.clip(x, -1.0, 1.0), {"x": x})


def test_scale():
    x = rng.rand(3, 4).astype(np.float32)
    check_forward(lambda x: paddle.scale(x, scale=2.0, bias=1.0),
                  lambda x: 2.0 * x + 1.0, {"x": x})


def test_operators_and_scalars():
    x = paddle.to_tensor([1.0, 2.0, 3.0])
    np.testing.assert_allclose((x + 1).numpy(), [2, 3, 4])
    np.testing.assert_allclose((2 * x).numpy(), [2, 4, 6])
    np.testing.assert_allclose((1 - x).numpy(), [0, -1, -2])
    np.testing.assert_allclose((x / 2).numpy(), [0.5, 1, 1.5])
    np.testing.assert_allclose((x ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((-x).numpy(), [-1, -2, -3])
    assert bool((x > 1.5).numpy()[1])


def test_dtype_of_int_ops():
    x = paddle.to_tensor([1, 2, 3], dtype="int64")
    assert paddle.sum(x).dtype == paddle.int64
    y = paddle.to_tensor([True, False, True])
    assert int(paddle.sum(y.astype("int32")).item()) == 2
