"""distributed namespace long tail: spawn, gather, object scatter,
destroy_process_group, sharding/utils namespaces."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def _spawn_worker(tag):
    # runs in a fresh spawned process
    import os
    import pathlib
    rank = os.environ["PADDLE_TRAINER_ID"]
    pathlib.Path(f"{tag}.rank{rank}").write_text("ok")


class TestDistMisc:
    def test_gather_single(self):
        out = dist.gather(paddle.to_tensor(np.ones(3, "float32")))
        assert len(out) == 1
        np.testing.assert_allclose(out[0].numpy(), 1.0)

    def test_scatter_object_list_single(self):
        ol = []
        dist.scatter_object_list(ol, [{"k": 7}])
        assert ol == [{"k": 7}]

    def test_backend_and_available(self):
        assert dist.get_backend() == "xla"
        assert dist.is_available()

    def test_destroy_process_group_resets_fleet(self):
        strategy = dist.fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": -1, "mp_degree": 1,
                                   "pp_degree": 1, "sharding_degree": 1,
                                   "sep_degree": 1, "ep_degree": 1}
        dist.fleet.init(strategy=strategy)
        assert dist.fleet.fleet._hcg is not None
        dist.destroy_process_group()
        assert dist.fleet.fleet._hcg is None

    @pytest.mark.slow
    def test_spawn_runs_ranked_processes(self, tmp_path):
        tag = str(tmp_path / "w")
        dist.spawn(_spawn_worker, args=(tag,), nprocs=2)
        assert (tmp_path / "w.rank0").exists()
        assert (tmp_path / "w.rank1").exists()

    def test_sharding_namespace(self):
        from paddle_tpu.distributed.sharding import (
            group_sharded_parallel, save_group_sharded_model)
        assert callable(group_sharded_parallel)
        assert callable(save_group_sharded_model)

    def test_utils_namespace(self):
        devs = dist.utils.get_available_device()
        assert len(devs) >= 1
        with pytest.raises(NotImplementedError, match="moe"):
            dist.utils.global_scatter(None, None, None)


class TestParallelize:
    def _reset(self):
        dist.fleet.fleet._hcg = None
        dist.fleet.fleet._topology = None
        dist.fleet.fleet._is_initialized = False

    def test_plan_shards_and_loss_parity(self):
        """ColWise/RowWise plan on an MLP: weights land sharded over the
        'model' axis and a compiled train step matches the unsharded
        single-device run (the §4 oracle)."""
        from paddle_tpu import nn

        def build():
            paddle.seed(5)
            return nn.Sequential(
                ("up", nn.Linear(8, 16)),
                ("act", nn.GELU()),
                ("down", nn.Linear(16, 8)),
            )

        x = np.random.RandomState(0).randn(4, 8).astype("float32")
        y = np.random.RandomState(1).randn(4, 8).astype("float32")

        def run(parallel):
            self._reset()
            model = build()
            opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
            if parallel:
                model, opt = dist.parallelize(
                    model, opt,
                    config={"mp_config": {"parallelize_plan": {
                        "up": dist.ColWiseParallel(),
                        "down": dist.RowWiseParallel(),
                    }}})
            loss_fn = paddle.nn.MSELoss()

            @paddle.jit.to_static
            def step(xt, yt):
                loss = loss_fn(model(xt), yt)
                loss.backward()
                opt.step()
                opt.clear_grad()
                return loss

            xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
            out = [float(step(xt, yt).item()) for _ in range(3)]
            if parallel:
                spec = str(model[0].weight._data.sharding.spec)
                assert "model" in spec, spec
            return out

        try:
            np.testing.assert_allclose(run(True), run(False),
                                       rtol=1e-4, atol=1e-6)
        finally:
            self._reset()

    def test_unmatched_pattern_warns(self):
        from paddle_tpu import nn
        self._reset()
        try:
            with pytest.warns(UserWarning, match="matched no sublayer"):
                dist.parallelize(
                    nn.Linear(2, 2), None,
                    config={"mp_config": {"parallelize_plan": {
                        "nonexistent_layer": dist.ColWiseParallel()}}})
        finally:
            self._reset()


class TestFleetAmpMetaOptimizer:
    def test_distributed_model_wraps_forward_in_auto_cast(self):
        import numpy as np
        from paddle_tpu import nn
        from paddle_tpu.distributed import fleet
        strategy = fleet.DistributedStrategy()
        strategy.amp = True
        strategy.amp_configs = {"level": "O1", "dtype": "bfloat16"}
        fleet.init(strategy=strategy)
        try:
            paddle.seed(0)
            m = nn.Linear(8, 8)
            dm = fleet.fleet.distributed_model(m)
            x = paddle.to_tensor(np.ones((2, 8), np.float32))
            y = dm(x)
            assert "bfloat16" in str(y._data.dtype)
            # outside the wrapper the model still computes fp32
            y2 = m(x)
            assert "float32" in str(y2._data.dtype)
            assert dm.parameters() == m.parameters()
        finally:
            fleet.fleet._hcg = None
            fleet.fleet._topology = None
            fleet.fleet._is_initialized = False
