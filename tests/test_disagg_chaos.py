"""Disaggregation chaos (ISSUE 17) — the ``disagg_chaos`` gate's slow
half, against REAL worker processes.

The two acceptance kills, each with the full correctness bar
(exactly-once, greedy token identity vs the colocated in-process
oracle, page audits green over the wire on every surviving worker):

- **prefill worker SIGKILLed mid-transfer** — died with KV pages
  parked for pickup. The payload is lost; the requests are NOT: they
  stayed in the parent shadow via the step reply's ``migrating``
  re-statement, so the respawn replays them from their prompts and
  they migrate again.
- **decode worker SIGKILLed mid-decode** — killed at every step until
  its respawn budget is spent and the breaker opens. Emitted tokens
  salvage through the shadow; with no decode-capable replica left the
  fleet pins ``no_migrate`` and the streams complete COLOCATED on the
  prefill replica (cross-role failover, never a migrate/replay
  livelock).
"""

import os
import signal

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (ContinuousBatchingEngine,
                                  DisaggServingFleet, ProcReplica)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.testing import FaultInjector

pytestmark = [pytest.mark.disagg, pytest.mark.fault, pytest.mark.slow]

_ENG_KW = dict(num_slots=2, page_size=8, max_len=48, decode_chunk=4,
               prompt_buckets=(8, 16), greedy=True)
_SPEC = {"factory": "paddle_tpu.inference.worker:llama_engine",
         "kwargs": dict(model="tiny", num_hidden_layers=1, seed=0,
                        **_ENG_KW)}

_REF = None
_REF_TOKENS = {}


def _reference(prompt, n_new):
    """Colocated greedy oracle: the same tiny model the workers build
    (seed 0), run uncontended in-process."""
    global _REF
    key = (prompt.tobytes(), int(n_new))
    if key not in _REF_TOKENS:
        if _REF is None:
            cfg = LlamaConfig.tiny()
            cfg.tensor_parallel = False
            cfg.scan_layers = False
            cfg.num_hidden_layers = 1
            paddle.seed(0)
            m = LlamaForCausalLM(cfg)
            m.eval()
            _REF = ContinuousBatchingEngine(m, **_ENG_KW)
        _REF.add_request(prompt, n_new)
        _REF_TOKENS[key] = _REF.run()[-1].tokens
    return _REF_TOKENS[key]


def _specs(seed, n):
    cfg = LlamaConfig.tiny()
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, cfg.vocab_size,
                         (int(rng.randint(9, 16)),)).astype(np.int32),
             int(rng.randint(2, 7))) for _ in range(n)]


def _fleet(num_prefill, num_decode, **kw):
    return DisaggServingFleet(
        _SPEC, num_prefill=num_prefill, num_decode=num_decode,
        replica_cls=ProcReplica,
        replica_kwargs=dict(hb_timeout_s=5.0,
                            respawn_backoff_s=0.01),
        max_restarts=1, retry_backoff_s=0.01, **kw)


def _assert_exactly_once_and_identical(done, fids, specs):
    assert len(done) == len(fids), "lost or duplicated completions"
    by = {r.request_id: r for r in done}
    assert sorted(by) == sorted(fids)
    for fid, (prompt, n_new) in zip(fids, specs):
        r = by[fid]
        assert r.finished
        assert r.error is None, (fid, r.error)
        assert r.tokens == _reference(prompt, n_new), fid


def test_kill_prefill_worker_mid_transfer(monkeypatch):
    """SIGKILL the prefill worker at the exact pickup window: KV
    pages are parked worker-side, the take_migrations RPC is about to
    fire. The payload dies with the process; every request replays
    from the shadow after the respawn and the streams stay
    token-identical, exactly-once, with clean audits on both sides."""
    specs = _specs(23, 6)
    fleet = _fleet(1, 1)
    killed = {"n": 0}
    orig = ProcReplica.take_migrations

    def kill_at_pickup(rep):
        if rep.id == 0 and killed["n"] < 1 \
                and getattr(rep, "_migrating", None) and rep.worker_pid:
            killed["n"] += 1
            os.kill(rep.worker_pid, signal.SIGKILL)
        return orig(rep)

    monkeypatch.setattr(ProcReplica, "take_migrations", kill_at_pickup)
    try:
        fids = [fleet.submit(p, n) for p, n in specs]
        done = fleet.run()
        assert killed["n"] == 1, "the mid-transfer window never opened"
        _assert_exactly_once_and_identical(done, fids, specs)
        assert fleet.replicas[0].respawns >= 1
        assert fleet.metrics.counter("disagg/migrations").value >= 1
        g = fleet.gauges()
        assert g["completed"] == len(fids)
        for rep in fleet.replicas.values():
            if rep.live():
                verdict = rep.audit()
                assert verdict["clean"], (rep.id, verdict)
    finally:
        fleet.close()


def test_kill_decode_worker_mid_decode():
    """SIGKILL the decode worker at every step until its respawn
    budget is spent: the breaker opens, emitted tokens salvage off
    the shadow, and with zero decode capacity left the requests pin
    ``no_migrate`` and finish colocated on the prefill replica —
    exactly-once, token-identical, prefill audit clean."""
    specs = _specs(29, 6)
    fleet = _fleet(1, 1)
    try:
        fids = [fleet.submit(p, n) for p, n in specs]
        with FaultInjector() as fi:
            fi.kill_worker(1, times=10_000, after_steps=2)
            done = fleet.run()
            assert fi.fires() >= 2      # respawn + budget exhaustion
        _assert_exactly_once_and_identical(done, fids, specs)
        g = fleet.gauges()
        assert g["completed"] == len(fids)
        assert g["breaker_open"] == 1
        assert fleet.replicas[1].state == "ejected"
        # migrations that raced the kill may have failed over; either
        # way the prefill replica carried the fleet alone afterwards
        rep0 = fleet.replicas[0]
        assert rep0.live()
        verdict = rep0.audit()
        assert verdict["clean"], verdict
    finally:
        fleet.close()
