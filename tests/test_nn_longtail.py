"""Round-2 nn breadth: losses (incl. RNN-T vs brute-force DP oracle),
unpooling round-trips, sequence utilities, beam-search decoding."""

import numpy as np
import pytest
import scipy.special

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F

R = np.random.RandomState(0)


class TestLossLongTail:
    def test_soft_margin_loss(self):
        x = R.randn(4, 5).astype("float32")
        y = ((R.rand(4, 5) > 0.5) * 2.0 - 1.0).astype("float32")
        out = F.soft_margin_loss(paddle.to_tensor(x), paddle.to_tensor(y))
        np.testing.assert_allclose(float(out),
                                   np.log1p(np.exp(-y * x)).mean(),
                                   rtol=1e-5)
        layer = nn.SoftMarginLoss(reduction="sum")
        np.testing.assert_allclose(
            float(layer(paddle.to_tensor(x), paddle.to_tensor(y))),
            np.log1p(np.exp(-y * x)).sum(), rtol=1e-5)

    def test_multi_margin_loss(self):
        x = R.randn(6, 4).astype("float32")
        y = R.randint(0, 4, (6,)).astype("int64")
        out = nn.MultiMarginLoss()(paddle.to_tensor(x), paddle.to_tensor(y))
        per = np.maximum(1.0 - x[np.arange(6), y][:, None] + x, 0)
        per[np.arange(6), y] = 0
        np.testing.assert_allclose(float(out), (per.sum(1) / 4).mean(),
                                   rtol=1e-5)

    def test_triplet_with_distance_matches_plain(self):
        a, p, n = (R.randn(5, 8).astype("float32") for _ in range(3))
        t1 = F.triplet_margin_with_distance_loss(
            paddle.to_tensor(a), paddle.to_tensor(p), paddle.to_tensor(n))
        t2 = F.triplet_margin_loss(
            paddle.to_tensor(a), paddle.to_tensor(p), paddle.to_tensor(n))
        np.testing.assert_allclose(float(t1), float(t2), rtol=1e-4)

    def test_hsigmoid_loss_trains(self):
        """The hierarchical path probabilities must be trainable: loss on
        a fixed batch decreases under SGD."""
        paddle.seed(0)
        layer = nn.HSigmoidLoss(8, 10)
        opt = paddle.optimizer.SGD(0.5, parameters=layer.parameters())
        x = paddle.to_tensor(R.randn(16, 8).astype("float32"))
        y = paddle.to_tensor(R.randint(0, 10, (16,)).astype("int64"))
        losses = []
        for _ in range(10):
            loss = layer(x, y)
            assert list(loss.shape) == [16, 1]   # un-reduced, paddle shape
            loss = loss.mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, losses

    def test_margin_cross_entropy_reduces_to_plain_ce(self):
        """With all margins off and scale 1, margin CE == cross entropy on
        cosine logits."""
        x = (R.rand(5, 7).astype("float32") - 0.5) * 1.6
        y = R.randint(0, 7, (5,)).astype("int64")
        out = F.margin_cross_entropy(paddle.to_tensor(x),
                                     paddle.to_tensor(y), margin1=1.0,
                                     margin2=0.0, margin3=0.0, scale=1.0)
        lp = scipy.special.log_softmax(x, axis=-1)
        np.testing.assert_allclose(float(out),
                                   -lp[np.arange(5), y].mean(), rtol=1e-4)

    def test_rnnt_loss_vs_bruteforce(self):
        def np_rnnt(logits, ys, tlen, ulen, blank=0):
            lp = scipy.special.log_softmax(logits, axis=-1)
            out = []
            for b in range(logits.shape[0]):
                Tb, Ub = tlen[b], ulen[b]
                alpha = np.full((Tb, Ub + 1), -np.inf)
                alpha[0, 0] = 0
                for t in range(Tb):
                    for u in range(Ub + 1):
                        if t == 0 and u == 0:
                            continue
                        c = []
                        if t > 0:
                            c.append(alpha[t - 1, u] + lp[b, t - 1, u, blank])
                        if u > 0:
                            c.append(alpha[t, u - 1]
                                     + lp[b, t, u - 1, ys[b, u - 1]])
                        alpha[t, u] = np.logaddexp.reduce(c)
                out.append(-(alpha[Tb - 1, Ub] + lp[b, Tb - 1, Ub, blank]))
            return np.asarray(out)

        B, T, U, V = 3, 6, 4, 5
        logits = R.randn(B, T, U + 1, V).astype("float32")
        ys = R.randint(1, V, (B, U)).astype("int64")
        tlen = np.array([6, 5, 4])
        ulen = np.array([4, 3, 2])
        out = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(ys),
                          paddle.to_tensor(tlen), paddle.to_tensor(ulen),
                          reduction="none")
        np.testing.assert_allclose(out.numpy(),
                                   np_rnnt(logits, ys, tlen, ulen),
                                   rtol=1e-4, atol=1e-4)

    def test_adaptive_log_softmax_normalizes(self):
        """exp(log-prob) over every class must sum to 1 per sample."""
        D = 8
        x = R.randn(3, D).astype("float32")
        hw = (R.randn(D, 6).astype("float32") * 0.3)   # cutoff0=4 + 2
        tails = [
            (paddle.to_tensor(R.randn(D, 4).astype("float32") * 0.3),
             paddle.to_tensor(R.randn(4, 4).astype("float32") * 0.3)),
            (paddle.to_tensor(R.randn(D, 2).astype("float32") * 0.3),
             paddle.to_tensor(R.randn(2, 4).astype("float32") * 0.3))]
        cutoffs = [4, 8]   # head 0-3, cluster0 4-7, cluster1 8-11
        total = np.zeros(3)
        for c in range(12):
            lab = paddle.to_tensor(np.full((3,), c, "int64"))
            lp, _ = F.adaptive_log_softmax_with_loss(
                paddle.to_tensor(x), lab, paddle.to_tensor(hw), tails,
                cutoffs)
            total += np.exp(lp.numpy())
        np.testing.assert_allclose(total, 1.0, rtol=1e-4)


class TestUnpool:
    @pytest.mark.parametrize("nd", [1, 2, 3])
    def test_unpool_places_maxima_back(self, nd):
        shape = {1: (2, 3, 8), 2: (2, 3, 8, 8), 3: (1, 2, 4, 4, 4)}[nd]
        x = R.randn(*shape).astype("float32") + 5.0   # positive maxima
        pool = getattr(F, f"max_pool{nd}d")
        unpool = getattr(F, f"max_unpool{nd}d")
        out, mask = pool(paddle.to_tensor(x), 2, 2, return_mask=True)
        rec = unpool(out, mask, 2, 2)
        assert list(rec.shape) == list(shape)
        # pooling the reconstruction recovers the same maxima
        np.testing.assert_allclose(pool(rec, 2, 2).numpy(), out.numpy())

    def test_unpool_layers(self):
        x = paddle.to_tensor(R.randn(2, 3, 8, 8).astype("float32"))
        out, mask = F.max_pool2d(x, 2, 2, return_mask=True)
        rec = nn.MaxUnPool2D(2, 2)(out, mask)
        assert list(rec.shape) == [2, 3, 8, 8]


class TestSequenceUtils:
    def test_sequence_mask(self):
        out = F.sequence_mask(paddle.to_tensor(np.array([2, 0, 3])),
                              maxlen=4)
        np.testing.assert_array_equal(
            out.numpy(), [[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 0]])

    def test_temporal_shift_moves_channels(self):
        nt, c, h, w = 4, 8, 2, 2
        x = np.arange(nt * c * h * w, dtype="float32").reshape(nt, c, h, w)
        out = F.temporal_shift(paddle.to_tensor(x), seg_num=2,
                               shift_ratio=0.25).numpy()
        v = x.reshape(2, 2, c, h, w)
        # first quarter shifted backward: segment t takes t+1's channels
        np.testing.assert_allclose(out.reshape(2, 2, c, h, w)[:, 0, :2],
                                   v[:, 1, :2])
        # last half untouched
        np.testing.assert_allclose(out.reshape(2, 2, c, h, w)[..., 4:, :, :],
                                   v[..., 4:, :, :])

    def test_zeropad2d(self):
        out = F.zeropad2d(paddle.to_tensor(np.ones((1, 1, 2, 2),
                                                   "float32")),
                          [1, 2, 0, 1])
        assert list(out.shape) == [1, 1, 3, 5]
        np.testing.assert_allclose(out.numpy().sum(), 4.0)

    def test_gather_tree(self):
        # the documented paddle example
        ids = paddle.to_tensor(np.array(
            [[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]],
            "int64"))
        parents = paddle.to_tensor(np.array(
            [[[0, 0], [1, 1]], [[1, 0], [1, 0]], [[0, 0], [0, 1]]],
            "int64"))
        out = F.gather_tree(ids, parents).numpy()
        ref = np.array([[[2, 2], [1, 6]], [[3, 3], [6, 1]],
                        [[0, 1], [9, 0]]])
        np.testing.assert_array_equal(out, ref)

    def test_class_center_sample(self):
        lab = paddle.to_tensor(np.array([3, 9, 3, 15], "int64"))
        remapped, sampled = F.class_center_sample(lab, 20, 8)
        s = sampled.numpy()
        assert set([3, 9, 15]) <= set(s.tolist())
        assert len(s) == 8 and (np.diff(s) > 0).all()
        np.testing.assert_array_equal(
            s[remapped.numpy()], lab.numpy())


class TestBeamSearch:
    def test_beam_search_greedy_consistency(self):
        """With beam_size=1, beam search equals greedy argmax decoding."""
        paddle.seed(7)
        cell = nn.GRUCell(8, 16)
        emb = nn.Embedding(10, 8)
        proj = nn.Linear(16, 10)
        h0 = paddle.to_tensor(R.randn(2, 16).astype("float32"))

        dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=0,
                                   beam_size=1, embedding_fn=emb,
                                   output_fn=proj)
        ids, lp = nn.dynamic_decode(dec, inits=h0, max_step_num=3)

        # greedy reference
        import jax.numpy as jnp
        tok = paddle.to_tensor(np.array([1, 1], "int64"))
        h = h0
        ref = []
        for _ in range(3):
            out, h = cell(emb(tok), h)
            logits = proj(out)
            tok = paddle.to_tensor(
                np.argmax(logits.numpy(), -1).astype("int64"))
            ref.append(tok.numpy())
        ref = np.stack(ref, -1)
        np.testing.assert_array_equal(ids.numpy()[:, 0, :], ref)

    def test_beam_scores_monotonic(self):
        paddle.seed(3)
        cell = nn.LSTMCell(8, 16)
        emb = nn.Embedding(12, 8)
        proj = nn.Linear(16, 12)
        h0 = (paddle.to_tensor(R.randn(3, 16).astype("float32")),
              paddle.to_tensor(R.randn(3, 16).astype("float32")))
        dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=0,
                                   beam_size=4, embedding_fn=emb,
                                   output_fn=proj)
        ids, lp = nn.dynamic_decode(dec, inits=h0, max_step_num=4)
        assert list(ids.shape)[:2] == [3, 4]
        assert (np.diff(lp.numpy(), axis=1) <= 1e-5).all()


class TestReviewRound2Regressions:
    def test_ceil_mode_pool_and_mask_agree(self):
        x = R.randn(1, 1, 5, 5).astype("float32")
        out, mask = F.max_pool2d(paddle.to_tensor(x), 2, 2,
                                 ceil_mode=True, return_mask=True)
        assert list(out.shape) == [1, 1, 3, 3]       # ceil(5/2) = 3
        assert list(mask.shape) == [1, 1, 3, 3]
        # last window is the partial tail: its argmax is a real element
        assert int(mask.numpy()[0, 0, 2, 2]) == 24   # element (4,4)
        rec = F.max_unpool2d(out, mask, 2, 2, output_size=[5, 5])
        assert list(rec.shape) == [1, 1, 5, 5]

    def test_pool_mask_string_padding_rejected(self):
        with pytest.raises(NotImplementedError, match="padding"):
            F.max_pool2d(paddle.to_tensor(R.randn(1, 1, 4, 4)
                                          .astype("float32")),
                         2, 2, padding="SAME", return_mask=True)

    def test_fill_diagonal_hyper(self):
        t = paddle.to_tensor(np.zeros((3, 3, 3), "float32"))
        paddle.Tensor.fill_diagonal_(t, 1.0)
        out = t.numpy()
        assert out.sum() == 3.0
        for i in range(3):
            assert out[i, i, i] == 1.0
        bad = paddle.to_tensor(np.zeros((2, 3, 3), "float32"))
        with pytest.raises(ValueError, match="equal"):
            paddle.Tensor.fill_diagonal_(bad, 1.0)

    def test_beam_search_backtracks_parents(self):
        """Beam rows must be FULL hypotheses (parent-pointer backtracked),
        verified against exhaustive search over all token sequences on a
        deterministic cell whose scores force beam reordering."""
        import itertools
        import jax.numpy as jnp

        V, W, T = 4, 3, 3
        rng = np.random.RandomState(9)
        trans = rng.randn(V, V).astype("float32") * 2.0  # score[prev, next]

        class Cell2:
            """Cell whose logits depend only on the current token (the
            state), via a fixed score table — exhaustively searchable."""

            def __call__(self, ids, states):
                logits = paddle.Tensor(jnp.take(jnp.asarray(trans),
                                                ids._data.astype(jnp.int32),
                                                axis=0))
                return logits, ids

        dec = paddle.nn.BeamSearchDecoder(Cell2(), start_token=0,
                                          end_token=-1, beam_size=W,
                                          embedding_fn=None,
                                          output_fn=None)
        h0 = paddle.to_tensor(np.zeros((1,), "int64"))   # state: last token
        ids, lp = paddle.nn.dynamic_decode(dec, inits=h0, max_step_num=T)

        # exhaustive best sequences by summed log-softmax score
        import scipy.special
        logp = scipy.special.log_softmax(trans, axis=-1)
        scored = []
        for seq in itertools.product(range(V), repeat=T):
            s, prev = 0.0, 0
            for t in seq:
                s += logp[prev, t]
                prev = t
            scored.append((s, seq))
        scored.sort(reverse=True)
        best = [list(seq) for _, seq in scored[:W]]
        got = [ids.numpy()[0, w].tolist() for w in range(W)]
        assert got == best, (got, best)
        np.testing.assert_allclose(
            sorted(lp.numpy()[0], reverse=True),
            [s for s, _ in scored[:W]], rtol=1e-4)


def test_beam_search_freezes_finished_hypotheses():
    """A hypothesis that hits end_token must keep its score (emitting only
    end_token at zero cost) instead of decaying and dropping out."""
    import jax.numpy as jnp

    V, W = 4, 2
    # token 3 = eos; from state 0, token 3 is by far the best move
    trans = np.full((V, V), -5.0, "float32")
    trans[0, 3] = 5.0      # finish immediately (best)
    trans[0, 1] = 2.0      # or continue via 1
    trans[1, 2] = 4.0
    trans[3, :] = -10.0    # post-eos moves are terrible: a non-frozen
    trans[3, 0] = -9.0     # finished beam would decay fast

    class Cell:
        def __call__(self, ids, states):
            logits = paddle.Tensor(jnp.take(jnp.asarray(trans),
                                            ids._data.astype(jnp.int32),
                                            axis=0))
            return logits, ids

    dec = paddle.nn.BeamSearchDecoder(Cell(), start_token=0, end_token=3,
                                      beam_size=W)
    h0 = paddle.to_tensor(np.zeros((1,), "int64"))
    ids, lp = paddle.nn.dynamic_decode(dec, inits=h0, max_step_num=4)
    # best hypothesis: [3, 3, 3, 3] (finished at step 1, then frozen)
    assert ids.numpy()[0, 0].tolist() == [3, 3, 3, 3]
    # its score must be exactly the single-step logprob of emitting eos
    import scipy.special
    expect = scipy.special.log_softmax(trans[0])[3]
    np.testing.assert_allclose(lp.numpy()[0, 0], expect, rtol=1e-5)
