"""Tests for the paddle.linalg / utils / regularizer / hub / sysconfig /
onnx / iinfo-finfo namespaces (SURVEY.md §2.2 rows: tensor linalg APIs,
``python/paddle/utils/``, ``python/paddle/regularizer.py`` — UNVERIFIED
reference paths; provenance warning in SURVEY.md)."""

import numpy as np
import pytest

import paddle_tpu as paddle


class TestLinalgNamespace:
    def test_reexports(self):
        for name in ("svd", "qr", "inv", "det", "norm", "matmul", "pinv",
                     "cholesky", "eigh", "solve", "lstsq", "matrix_rank"):
            assert callable(getattr(paddle.linalg, name)), name

    def test_vector_matrix_norm(self):
        x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
        v = paddle.linalg.vector_norm(x)
        np.testing.assert_allclose(
            float(v.item()), np.linalg.norm(np.arange(6)), rtol=1e-5)
        m = paddle.linalg.matrix_norm(x, p="fro")
        np.testing.assert_allclose(
            float(m.item()), np.linalg.norm(np.arange(6)), rtol=1e-5)

    def test_matrix_exp(self):
        a = np.diag([1.0, 2.0]).astype("float32")
        out = paddle.linalg.matrix_exp(paddle.to_tensor(a)).numpy()
        np.testing.assert_allclose(out, np.diag(np.exp([1.0, 2.0])),
                                   rtol=1e-5)

    def test_lu_unpack_reconstructs(self):
        rng = np.random.RandomState(0)
        a = rng.randn(5, 5).astype("float32")
        lu, piv = paddle.linalg.lu(paddle.to_tensor(a))
        P, L, U = paddle.linalg.lu_unpack(lu, piv)
        rec = P.numpy() @ L.numpy() @ U.numpy()
        np.testing.assert_allclose(rec, a, atol=1e-4)

    def test_cdist_matches_numpy(self):
        rng = np.random.RandomState(1)
        x, y = rng.randn(4, 3).astype("float32"), rng.randn(5, 3).astype(
            "float32")
        out = paddle.linalg.cdist(
            paddle.to_tensor(x), paddle.to_tensor(y)).numpy()
        ref = np.sqrt(((x[:, None, :] - y[None, :, :]) ** 2).sum(-1))
        np.testing.assert_allclose(out, ref, atol=1e-5)
        out1 = paddle.linalg.cdist(
            paddle.to_tensor(x), paddle.to_tensor(y), p=1.0).numpy()
        ref1 = np.abs(x[:, None, :] - y[None, :, :]).sum(-1)
        np.testing.assert_allclose(out1, ref1, atol=1e-5)

    def test_svd_lowrank_rank_revealing(self):
        rng = np.random.RandomState(2)
        base = rng.randn(20, 3).astype("float32") @ rng.randn(3, 15).astype(
            "float32")
        u, s, v = paddle.linalg.svd_lowrank(paddle.to_tensor(base), q=6)
        s = s.numpy()
        assert s[0] > 1e-2 and s[3] < 1e-3 * s[0]  # true rank is 3

    def test_cdist_grad_flows(self):
        x = paddle.to_tensor(np.random.RandomState(3).randn(3, 4).astype(
            "float32"))
        x.stop_gradient = False
        d = paddle.linalg.cdist(x, x * 0.5).sum()
        d.backward()
        assert x.grad is not None and np.isfinite(x.grad.numpy()).all()


class TestUtils:
    def test_unique_name(self):
        from paddle_tpu.utils import unique_name
        a, b = unique_name.generate("w"), unique_name.generate("w")
        assert a != b and a.startswith("w_")
        with unique_name.guard("block/"):
            c = unique_name.generate("w")
        assert c.startswith("block/w")

    def test_deprecated_warns(self):
        @paddle.utils.deprecated(update_to="paddle.new_api", since="0.1")
        def old():
            return 7

        with pytest.warns(DeprecationWarning):
            assert old() == 7

    def test_dlpack_roundtrip(self):
        x = paddle.to_tensor(np.arange(12, dtype="float32").reshape(3, 4))
        cap = paddle.utils.dlpack.to_dlpack(x)
        y = paddle.utils.dlpack.from_dlpack(cap)
        np.testing.assert_array_equal(y.numpy(), x.numpy())
        # numpy consumes the protocol object directly
        np.testing.assert_array_equal(np.from_dlpack(
            paddle.utils.dlpack.to_dlpack(x)), x.numpy())

    def test_flatten_pack(self):
        nest = {"a": [1, 2], "b": (3,)}
        flat = paddle.utils.flatten(nest)
        assert flat == [1, 2, 3]
        back = paddle.utils.pack_sequence_as(nest, flat)
        assert back == {"a": [1, 2], "b": (3,)}

    def test_run_check(self, capsys):
        paddle.utils.run_check()
        assert "successfully" in capsys.readouterr().out

    def test_download_is_cache_only(self):
        with pytest.raises(RuntimeError, match="no network access"):
            paddle.utils.download.get_weights_path_from_url(
                "https://example.com/nonexistent_weights.pdparams")


class TestRegularizer:
    def test_l2_decay_changes_update(self):
        rng = np.random.RandomState(0)
        w0 = rng.randn(4, 4).astype("float32")

        def run(reg):
            paddle.seed(0)
            lin = paddle.nn.Linear(4, 4)
            lin.weight.set_value(paddle.to_tensor(w0.copy()))
            opt = paddle.optimizer.Momentum(
                learning_rate=0.1, parameters=lin.parameters(),
                weight_decay=reg)
            x = paddle.to_tensor(rng.randn(2, 4).astype("float32"))
            loss = lin(x).sum()
            loss.backward()
            opt.step()
            return lin.weight.numpy()

        none_w = run(None)
        l2_w = run(paddle.regularizer.L2Decay(0.5))
        l1_w = run(paddle.regularizer.L1Decay(0.5))
        assert not np.allclose(none_w, l2_w)
        assert not np.allclose(l2_w, l1_w)

    def test_regularizer_object_on_every_optimizer(self):
        rng = np.random.RandomState(0)
        for cls, kw in [(paddle.optimizer.SGD, {}),
                        (paddle.optimizer.Momentum, {}),
                        (paddle.optimizer.Adam, {}),
                        (paddle.optimizer.Adamax, {}),
                        (paddle.optimizer.Adagrad, {}),
                        (paddle.optimizer.Adadelta, {}),
                        (paddle.optimizer.RMSProp, {})]:
            paddle.seed(0)
            lin = paddle.nn.Linear(3, 3)
            opt = cls(learning_rate=0.1, parameters=lin.parameters(),
                      weight_decay=paddle.regularizer.L2Decay(0.1), **kw)
            x = paddle.to_tensor(rng.randn(2, 3).astype("float32"))
            lin(x).sum().backward()
            opt.step()  # must not raise on regularizer-object weight_decay
            assert np.isfinite(lin.weight.numpy()).all(), cls.__name__

    def test_param_attr_regularizer_takes_effect(self):
        rng = np.random.RandomState(0)
        x_np = rng.randn(2, 4).astype("float32")

        def run(attr):
            paddle.seed(0)
            lin = paddle.nn.Linear(
                4, 4, weight_attr=paddle.ParamAttr(regularizer=attr))
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=lin.parameters())
            lin(paddle.to_tensor(x_np)).sum().backward()
            opt.step()
            return lin.weight.numpy()

        plain = run(None)
        reg = run(paddle.regularizer.L2Decay(0.5))
        assert not np.allclose(plain, reg)

    def test_l2_matches_scalar_weight_decay(self):
        p = np.array([[2.0, -3.0]], dtype="float32")
        g = np.array([[0.1, 0.1]], dtype="float32")
        out = paddle.regularizer.L2Decay(0.01)(p, g)
        np.testing.assert_allclose(np.asarray(out), g + 0.01 * p, rtol=1e-6)


class TestMiscNamespaces:
    def test_iinfo_finfo(self):
        assert paddle.iinfo(paddle.int8).max == 127
        assert paddle.finfo(paddle.float32).eps > 0
        assert paddle.finfo(paddle.bfloat16).bits == 16

    def test_sysconfig_paths_exist(self):
        import os
        assert os.path.isdir(paddle.sysconfig.get_include())
        assert os.path.isdir(paddle.sysconfig.get_lib())

    def test_hub_local(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny_model(scale=1):\n"
            "    'a tiny model entrypoint'\n"
            "    import paddle_tpu as paddle\n"
            "    return paddle.nn.Linear(2 * scale, 2 * scale)\n")
        names = paddle.hub.list(str(tmp_path))
        assert "tiny_model" in names
        assert "tiny" in paddle.hub.help(str(tmp_path), "tiny_model")
        m = paddle.hub.load(str(tmp_path), "tiny_model", scale=2)
        assert m.weight.shape == [4, 4]

    def test_hub_remote_raises(self):
        with pytest.raises(RuntimeError, match="network"):
            paddle.hub.list("someorg/somerepo", source="github")

    def test_onnx_export_stablehlo(self, tmp_path):
        lin = paddle.nn.Linear(3, 2)
        spec = [paddle.static.InputSpec([1, 3], "float32", "x")]
        out = paddle.onnx.export(lin, str(tmp_path / "m"), input_spec=spec)
        import os
        assert os.path.exists(out)
        assert "stablehlo" in open(out).read() or "module" in open(out).read()
        with pytest.raises(RuntimeError, match="paddle2onnx"):
            paddle.onnx.export(lin, str(tmp_path / "m2"), input_spec=spec,
                               format="onnx")

    def test_callbacks_namespace(self):
        assert hasattr(paddle.callbacks, "Callback") or hasattr(
            paddle.callbacks, "EarlyStopping")
