"""ServingFleet reliability contracts (ISSUE 11).

The multi-replica router's pinned semantics, one scenario per test:

- **failover token-identity** — killing a replica mid-stream loses
  zero requests and every affected greedy stream is token-identical
  to an uncontended single-engine run (the supervisor salvage /
  recompute-replay contract, end to end through the fleet);
- **hedged dispatch** — a straggler replica's request is duplicated
  to a sibling after the hedge delay; the first completion wins and
  the loser is cancelled, exactly one completion per fleet id;
- **circuit breaking** — a replica that burns its supervisor restart
  budget is ejected and its queue requeued to siblings;
- **no-progress ejection** — a wedged replica (heartbeats, no
  progress) is ejected by the health check, not the liveness check,
  without tripping the engine's true-deadlock stall diagnostic;
- **graceful draining** — scale-down stops admission, lets in-flight
  finish under the deadline, and deadline-evicts stragglers for
  recompute on siblings;
- **fleet-wide shed** — all breakers open raises ``Overloaded``; a
  partial shed propagates the MAX computed retry-after across the
  replicas that shed (the ISSUE-11 ``retry_after_s`` fix), and the
  retry backoff honors such a value as its floor.

The 4-replica randomized kill/wedge/slow sweep lives in
``tests/test_fleet_chaos.py`` (the ``fleet_chaos`` gate).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (ContinuousBatchingEngine, Overloaded,
                                  ReplicaFailed, RequestCancelled,
                                  ServingFleet)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.testing import FaultInjector

_MODEL = None
_REF_ENG = None
_REF_TOKENS = {}


def _model():
    global _MODEL
    if _MODEL is None:
        cfg = LlamaConfig.tiny()
        cfg.tensor_parallel = False
        cfg.scan_layers = False
        cfg.num_hidden_layers = 1
        paddle.seed(0)
        m = LlamaForCausalLM(cfg)
        m.eval()
        _MODEL = (m, cfg)
    return _MODEL


def _factory(**kw):
    m, _ = _model()
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_len", 48)
    kw.setdefault("decode_chunk", 4)
    kw.setdefault("prompt_buckets", (8, 16))
    kw.setdefault("greedy", True)
    return lambda: ContinuousBatchingEngine(m, **kw)


def _reference(prompt, n_new):
    """Uncontended single-engine greedy tokens for one request (one
    shared reference engine: each request runs ALONE, and its compiled
    program is reused across every test in this module)."""
    global _REF_ENG
    key = (prompt.tobytes(), int(n_new))
    if key not in _REF_TOKENS:
        if _REF_ENG is None:
            _REF_ENG = _factory()()
        _REF_ENG.add_request(prompt, n_new)
        _REF_TOKENS[key] = _REF_ENG.run()[-1].tokens
    return _REF_TOKENS[key]


def _prompts(seed, n, lo=3, hi=10):
    _, cfg = _model()
    rng = np.random.RandomState(seed)
    return [rng.randint(0, cfg.vocab_size,
                        (int(rng.randint(lo, hi)),)).astype(np.int32)
            for _ in range(n)]


def _assert_identity(fleet, done, fids, specs):
    """Every fid delivered exactly once, error-free, token-identical
    to its uncontended single-engine stream."""
    assert len(done) == len(fids), "lost or duplicated completions"
    by = {r.request_id: r for r in done}
    assert sorted(by) == sorted(fids)
    for fid, (prompt, n_new) in zip(fids, specs):
        r = by[fid]
        assert r.error is None, (fid, r.error)
        assert r.tokens == _reference(prompt, n_new), fid


# ---- failover --------------------------------------------------------------

@pytest.mark.fault
def test_failover_token_identity_supervisor_restart():
    """ACCEPTANCE PIN: a replica dying mid-stream loses zero requests
    and every affected greedy stream is token-identical to an
    uncontended single-engine run — the in-replica supervisor restart
    path (death absorbed below the fleet's breaker)."""
    prompts = _prompts(1, 6)
    specs = [(p, 5) for p in prompts]
    fleet = ServingFleet(_factory(), num_replicas=2, max_restarts=2,
                         retry_backoff_s=0.01)
    fids = [fleet.submit(p, n) for p, n in specs]
    with FaultInjector() as fi:
        fi.kill_replica(0, times=1, after_steps=2)
        done = fleet.run()
        assert fi.fires() == 1
    _assert_identity(fleet, done, fids, specs)
    g = fleet.gauges()
    assert fleet.replicas[0].supervisor.restarts == 1
    assert g["breaker_open"] == 0        # absorbed in-replica
    assert fleet.replicas[0].state == "ready"


@pytest.mark.fault
@pytest.mark.slow
def test_breaker_ejection_requeues_to_siblings():
    """A replica that keeps dying past its supervisor budget trips the
    circuit breaker: it is ejected, its queue + in-flight requeue to
    the sibling with bounded backoff-retries, streams stay
    token-identical."""
    prompts = _prompts(2, 4, lo=6, hi=7)
    specs = [(p, 5) for p in prompts]
    fleet = ServingFleet(_factory(), num_replicas=2, max_restarts=1,
                         retry_backoff_s=0.01)
    fids = [fleet.submit(p, n) for p, n in specs]
    with FaultInjector() as fi:
        fi.kill_replica(0, times=10_000)
        done = fleet.run()
    _assert_identity(fleet, done, fids, specs)
    g = fleet.gauges()
    assert fleet.replicas[0].state == "ejected"
    assert g["breaker_open"] == 1
    assert g["requeued"] >= 1 and g["retries"] >= 1
    assert g["failover_ms_p99"] > 0.0


# ---- health model ----------------------------------------------------------

@pytest.mark.fault
def test_wedged_replica_ejected_by_no_progress():
    """ACCEPTANCE PIN: a wedged replica — heartbeats arriving (its
    step() returns promptly), zero progress — is ejected by the
    NO-PROGRESS health check (not the liveness check, not the
    breaker), and its queue drains to the sibling without tripping the
    engine's true-deadlock stall RuntimeError (run() returns
    normally)."""
    prompts = _prompts(3, 4, lo=6, hi=7)
    specs = [(p, 5) for p in prompts]
    # hedging OFF (hedge_delay_s huge): in a warm process the p99-
    # derived hedge fires first and RESCUES the wedged replica's
    # requests before the no-progress clock reaches 5 — fine behavior,
    # but this test pins the EJECTION path specifically
    fleet = ServingFleet(_factory(), num_replicas=2,
                         no_progress_turns=5, retry_backoff_s=0.01,
                         hedge_delay_s=1e9)
    fids = [fleet.submit(p, n) for p, n in specs]
    with FaultInjector() as fi:
        fi.wedge_replica(0, times=10_000)
        done = fleet.run()            # no RuntimeError
        assert fi.fires() >= 5
    _assert_identity(fleet, done, fids, specs)
    g = fleet.gauges()
    assert g["wedge_ejections"] == 1
    assert g["breaker_open"] == 0     # the wedge is NOT a crash
    assert fleet.replicas[0].state == "ejected"


# ---- hedging ---------------------------------------------------------------

@pytest.mark.fault
@pytest.mark.slow
def test_hedge_winner_cancels_loser():
    """A straggler replica's request is duplicated to the sibling
    after the hedge delay; the duplicate wins, the loser is cancelled
    via the PR-10 cancel path, and exactly ONE completion is delivered
    — token-identical to the uncontended stream."""
    prompts = _prompts(4, 1, lo=6, hi=7)
    spec = (prompts[0], 5)
    fleet = ServingFleet(_factory(), num_replicas=2,
                         hedge_delay_s=0.03, retry_backoff_s=0.01)
    with FaultInjector() as fi:
        # replica 0 straggles: every step burns 50 ms and only every
        # 6th advances — both replicas idle at submit, so the router
        # deterministically picks replica 0 first
        fi.slow_replica(0, delay_s=0.05, stride=6)
        fid = fleet.submit(*spec)
        done = fleet.run()
    _assert_identity(fleet, done, [fid], [spec])
    g = fleet.gauges()
    assert g["hedges"] == 1
    assert g["hedge_wins"] == 1       # the duplicate beat the straggler
    assert g["hedge_cancels"] >= 1    # and the loser was cancelled
    assert g["completed"] == 1        # never delivered twice


# ---- draining / elasticity -------------------------------------------------

@pytest.mark.slow
def test_drain_clean_under_generous_deadline():
    """scale_down with headroom: admission stops, in-flight requests
    FINISH on the draining replica (zero evictions), then it
    retires."""
    prompts = _prompts(5, 4, lo=6, hi=7)
    specs = [(p, 5) for p in prompts]
    fleet = ServingFleet(_factory(), num_replicas=2)
    fids = [fleet.submit(p, n) for p, n in specs]
    rid = fleet.scale_down(0, deadline_s=60.0)
    done = fleet.run()
    _assert_identity(fleet, done, fids, specs)
    g = fleet.gauges()
    assert fleet.replicas[rid].state == "retired"
    assert g["drains"] == 1
    assert g["requeued"] == 0         # nothing was evicted


@pytest.mark.slow
def test_drain_deadline_evicts_stragglers_to_sibling():
    """scale_down with an already-expired deadline: the stragglers are
    evicted through the engine's handoff() hook and recomputed on the
    sibling — still token-identical, still zero loss."""
    prompts = _prompts(6, 4, lo=6, hi=7)
    specs = [(p, 5) for p in prompts]
    fleet = ServingFleet(_factory(), num_replicas=2)
    fids = [fleet.submit(p, n) for p, n in specs]
    rid = fleet.scale_down(0, deadline_s=0.0)
    done = fleet.run()
    _assert_identity(fleet, done, fids, specs)
    g = fleet.gauges()
    assert fleet.replicas[rid].state == "retired"
    assert g["drains"] == 1
    assert g["requeued"] >= 1         # stragglers moved over


@pytest.mark.slow
def test_scale_up_warms_before_taking_weight():
    """A scaled-up replica is warmed (programs compiled on a
    sacrificial request) and its gauges reset before it takes router
    weight — warmup latencies never pollute the routing signal."""
    fleet = ServingFleet(_factory(), num_replicas=1)
    rid = fleet.scale_up()
    rep = fleet.replicas[rid]
    assert rep.state == "ready"
    assert rep.engine._compiled         # warmed: programs exist
    assert rep.engine._stats["tokens_emitted"] == 0   # gauges reset
    assert fleet.gauges()["scale_ups"] == 1
    prompts = _prompts(7, 2, lo=6, hi=7)
    specs = [(p, 4) for p in prompts]
    fids = [fleet.submit(p, n) for p, n in specs]
    done = fleet.run()
    _assert_identity(fleet, done, fids, specs)


# ---- shedding / retry-after ------------------------------------------------

@pytest.mark.fault
@pytest.mark.slow
def test_all_breakers_open_sheds_fleet_wide():
    """Every replica dead: outstanding requests complete with the
    typed ReplicaFailed (never silent loss), and a new submission
    raises Overloaded with the configured fleet-wide retry-after."""
    prompts = _prompts(8, 2, lo=6, hi=7)
    fleet = ServingFleet(_factory(), num_replicas=2, max_restarts=0,
                         retry_backoff_s=0.01, max_retries=2,
                         all_open_retry_after_s=0.7)
    fids = [fleet.submit(p, 5) for p in prompts]
    with FaultInjector() as fi:
        fi.kill_replica(0, times=10_000)
        fi.kill_replica(1, times=10_000)
        done = fleet.run()
    by = {r.request_id: r for r in done}
    assert sorted(by) == sorted(fids)
    for fid in fids:
        assert isinstance(by[fid].error, ReplicaFailed), by[fid].error
        assert by[fid].finish_reason == "failed"
    assert fleet.gauges()["breaker_open"] == 2
    with pytest.raises(Overloaded) as exc:
        fleet.submit(prompts[0], 5)
    assert exc.value.retry_after_s == pytest.approx(0.7)


def test_overloaded_retry_after_is_max_across_replicas():
    """THE ISSUE-11 propagation fix: when every ready replica sheds,
    the fleet's Overloaded carries the MAX of the admission
    controllers' computed retry-afters — not a constant."""
    prompts = _prompts(9, 3, lo=6, hi=7)
    fleet = ServingFleet(_factory(), num_replicas=2, max_queue=1)
    fleet.replicas[0].admission.min_retry_after_s = 0.3
    fleet.replicas[1].admission.min_retry_after_s = 0.7
    fleet.submit(prompts[0], 4)       # fills replica 0's queue bound
    fleet.submit(prompts[1], 4)       # fills replica 1's
    with pytest.raises(Overloaded) as exc:
        fleet.submit(prompts[2], 4)
    assert exc.value.retry_after_s == pytest.approx(0.7)
    assert fleet.gauges()["shed_rejections"] == 1
    assert fleet.gauges()["submitted"] == 2     # sheds never counted


def test_retry_backoff_floor_growth_and_jitter():
    """The fleet's retry schedule: exponential in the attempt number,
    jitter-bounded, capped — and FLOORED by a computed retry-after
    (the Overloaded.retry_after_s backoff-floor contract)."""
    fleet = ServingFleet(_factory(), num_replicas=1,
                         retry_backoff_s=0.05, retry_backoff_cap_s=2.0,
                         retry_jitter=0.25, seed=7)
    for attempt in (1, 2, 3, 4):
        base = 0.05 * 2 ** (attempt - 1)
        for _ in range(20):
            b = fleet._backoff_s(attempt)
            assert base * 0.75 - 1e-9 <= b <= min(2.0, base * 1.25) \
                + 1e-9
    # a computed retry-after outranks the blind schedule entirely
    assert fleet._backoff_s(1, floor_s=5.0) == 5.0
    # the cap bounds the schedule (2^11 * base >> cap), not the floor
    assert fleet._backoff_s(12) == 2.0


@pytest.mark.fault
def test_cancel_while_carried_is_not_resurrected():
    """A request cancelled while waiting out its failover backoff
    (its replica died, it is CARRIED between assignments) completes
    with RequestCancelled — it must never be re-admitted on a sibling
    and delivered as a success (the reap runs before the retry
    firing)."""
    prompts = _prompts(11, 1, lo=6, hi=7)
    fleet = ServingFleet(_factory(), num_replicas=2, max_restarts=0,
                         retry_backoff_s=30.0)   # carry parks for 30s
    with FaultInjector() as fi:
        fi.kill_replica(0, times=10_000)
        fid = fleet.submit(prompts[0], 5)        # routed to replica 0
        out = fleet.step()                       # breaker -> carried
        assert not out and fleet.request(fid) is not None
        assert fleet.cancel(fid)
        done = fleet.step()                      # reap, not reassign
    assert len(done) == 1
    assert isinstance(done[0].error, RequestCancelled), done[0].error
    assert fleet.gauges()["completed"] == 1


@pytest.mark.slow
def test_operator_eject_is_not_a_breaker_trip():
    """fleet.eject() (an operator action, not a failure) fails the
    replica's work over immediately WITHOUT counting a breaker trip or
    burning the salvaged requests' bounded retry budget."""
    prompts = _prompts(12, 3, lo=6, hi=7)
    specs = [(p, 5) for p in prompts]
    fleet = ServingFleet(_factory(), num_replicas=2, max_retries=0)
    fids = [fleet.submit(p, n) for p, n in specs]
    fleet.eject(0)
    done = fleet.run()
    _assert_identity(fleet, done, fids, specs)   # max_retries=0 yet
    g = fleet.gauges()                           # nothing failed
    assert fleet.replicas[0].state == "ejected"
    assert g["breaker_open"] == 0 and g["retries"] == 0
    assert g["requeued"] >= 1


def test_fleet_cancel_and_request_surface():
    """fleet.cancel(fid) completes the request with the typed
    RequestCancelled at the next turn; fleet.request(fid) tracks the
    live handle and then the completion."""
    prompts = _prompts(10, 1, lo=6, hi=7)
    fleet = ServingFleet(_factory(), num_replicas=1)
    fid = fleet.submit(prompts[0], 5)
    assert fleet.request(fid) is not None
    assert fleet.cancel(fid)
    done = fleet.run()
    assert len(done) == 1
    assert isinstance(done[0].error, RequestCancelled)
    assert fleet.request(fid) is done[0]
    assert not fleet.cancel(fid)      # already finished
