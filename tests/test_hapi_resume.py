"""hapi auto-resume: Model.fit(resume=...) + ModelCheckpoint restart
training from the newest *committed* checkpoint, skipping torn saves
(the crash-restart contract of docs/checkpoint_fault_tolerance.md)."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.hapi import Model


def _data():
    x = np.random.RandomState(0).randn(8, 4).astype("float32")
    y = np.random.RandomState(1).randn(8, 1).astype("float32")
    return paddle.io.TensorDataset([paddle.to_tensor(x),
                                    paddle.to_tensor(y)])


def _model(seed):
    paddle.seed(seed)
    net = nn.Linear(4, 1)
    m = Model(net)
    m.prepare(paddle.optimizer.Adam(0.05, parameters=net.parameters()),
              nn.MSELoss())
    return m


def test_fit_writes_committed_step_checkpoints(tmp_path):
    m = _model(0)
    m.fit(_data(), batch_size=4, epochs=2, verbose=0,
          save_dir=str(tmp_path))
    for e in (0, 1):
        assert ckpt.is_committed(str(tmp_path / f"step_{e}"))
        assert os.path.exists(tmp_path / f"epoch_{e}.pdparams")
    best = ckpt.latest_valid_checkpoint(str(tmp_path))
    assert os.path.basename(best) == "step_1"
    assert ckpt.load_values(best)["epoch"] == 1


def test_fit_resume_restores_state_and_skips_done_epochs(tmp_path):
    m1 = _model(0)
    m1.fit(_data(), batch_size=4, epochs=2, verbose=0,
           save_dir=str(tmp_path))
    w1 = m1.network.state_dict()["weight"].numpy()
    step1 = m1._optimizer._step_count

    # crash leaves a torn step_2 behind: resume must skip it
    ckpt.save_state_dict({"model": m1.network.state_dict()},
                         str(tmp_path / "step_2"))
    os.remove(tmp_path / "step_2" / "COMMITTED")

    m2 = _model(123)  # different init — must be overwritten by resume
    assert not np.allclose(m2.network.state_dict()["weight"].numpy(), w1)
    m2.fit(_data(), batch_size=4, epochs=2, verbose=0,
           save_dir=str(tmp_path), resume=True)
    # epochs 0..1 already done at the committed step_1: no retraining,
    # weights + optimizer step land exactly where the crash left them
    np.testing.assert_array_equal(
        m2.network.state_dict()["weight"].numpy(), w1)
    assert m2._optimizer._step_count == step1


def test_fit_resume_continues_training(tmp_path):
    m1 = _model(0)
    m1.fit(_data(), batch_size=4, epochs=1, verbose=0,
           save_dir=str(tmp_path))
    m2 = _model(123)
    m2.fit(_data(), batch_size=4, epochs=3, verbose=0,
           save_dir=str(tmp_path), resume=True, keep_last_n=2)
    # epochs 1..2 trained on top of the restored epoch-0 state
    assert ckpt.is_committed(str(tmp_path / "step_2"))
    # retention kept only the newest 2 step checkpoints
    steps = sorted(n for n in os.listdir(tmp_path)
                   if n.startswith("step_"))
    assert steps == ["step_1", "step_2"]


def test_fit_resume_explicit_path_and_env(tmp_path, monkeypatch):
    m1 = _model(0)
    m1.fit(_data(), batch_size=4, epochs=1, verbose=0,
           save_dir=str(tmp_path / "a"))
    w1 = m1.network.state_dict()["weight"].numpy()

    m2 = _model(7)
    m2.fit(_data(), batch_size=4, epochs=1, verbose=0,
           resume=str(tmp_path / "a" / "step_0"))
    np.testing.assert_array_equal(
        m2.network.state_dict()["weight"].numpy(), w1)

    # the elastic launcher exports PADDLE_RESUME_CHECKPOINT
    monkeypatch.setenv("PADDLE_RESUME_CHECKPOINT",
                       str(tmp_path / "a" / "step_0"))
    m3 = _model(8)
    m3.fit(_data(), batch_size=4, epochs=1, verbose=0, resume=True)
    np.testing.assert_array_equal(
        m3.network.state_dict()["weight"].numpy(), w1)


def test_fit_resume_corrupt_checkpoint_raises(tmp_path):
    m1 = _model(0)
    m1.fit(_data(), batch_size=4, epochs=1, verbose=0,
           save_dir=str(tmp_path))
    shard = next(p for p in (tmp_path / "step_0").iterdir()
                 if p.name.endswith(".npy") and "weight" in p.name)
    blob = bytearray(shard.read_bytes())
    blob[-1] ^= 0xFF
    shard.write_bytes(bytes(blob))
    m2 = _model(1)
    with pytest.raises(ckpt.CheckpointCorruptError):
        m2.fit(_data(), batch_size=4, epochs=1, verbose=0,
               resume=str(tmp_path / "step_0"))


def test_model_checkpoint_callback_atomic(tmp_path):
    m = _model(0)
    from paddle_tpu.hapi.callbacks import ModelCheckpoint
    cb = ModelCheckpoint(save_dir=str(tmp_path), keep_last_n=2)
    cb.set_model(m)
    for epoch in range(4):
        cb.on_epoch_end(epoch)
    steps = sorted(n for n in os.listdir(tmp_path)
                   if n.startswith("step_"))
    assert steps == ["step_2", "step_3"]
    assert all(ckpt.is_committed(str(tmp_path / s)) for s in steps)
    # legacy mode keeps the old model.save contract
    legacy = ModelCheckpoint(save_dir=str(tmp_path / "legacy"),
                             atomic=False)
    legacy.set_model(m)
    os.makedirs(tmp_path / "legacy")
    legacy.on_epoch_end(0)
    assert os.path.exists(tmp_path / "legacy" / "0.pdparams")
