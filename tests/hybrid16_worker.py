"""16-virtual-device hybrid-parallelism worker (SURVEY.md §2.3 hybrid
row): run by ``test_hybrid16.py`` in a fresh subprocess so the device
count can exceed the suite's 8-device mesh.

Families (argv[1]):
  4d — dp2 x sharding2 x mp2 x pp2, NON-degenerate data parallelism,
       loss parity vs the single-device eager oracle under the compiled
       scan schedules (FThenB / interleaved-V2). This is the
       interaction an 8-device mesh cannot express with mp>1: the dp
       gradient MEAN composed with microbatch accumulation.
  5d — pp2 x mp2 x sep2 x sharding2: ring context parallelism crossing
       pipeline-stage boundaries WITH ZeRO-sharded optimizer state and
       a live batch-sharding axis, under both compiled scan schedules.

The explicit 1F1B/ZB-H1 tick engines are NOT in the 16-device families:
this jaxlib's XLA:CPU hard-codes a 40s collective-rendezvous
kill-switch (the newer warn_stuck/terminate_timeout debug flags are
not registered), and 16 single-core-time-sliced device threads cannot
reliably clear it through the tick machine's per-tick permute pairs.
The dp-mean x microbatch-accumulation interaction under 1F1B/ZB-H1 is
instead certified on the suite's 8-device mesh at dp2 x sharding2 x
pp2 (``test_pipeline_parallel.py::test_hybrid_dp2_explicit_schedules``
— exact parity), where the same engines run comfortably.
"""

import os
import re
import sys

N_DEV = 16

flags = os.environ.get("XLA_FLAGS", "")
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
os.environ["XLA_FLAGS"] = (
    flags + f" --xla_force_host_platform_device_count={N_DEV}"
    # 16 device threads time-slice this box's single core: XLA:CPU's
    # default 40s collective-rendezvous kill-switch fires spuriously
    " --xla_cpu_collective_timeout_seconds=1200").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_disable_most_optimizations", True)
# Serialize program dispatch: with 16 virtual devices on few cores,
# XLA:CPU's async dispatch can interleave two in-flight programs'
# collectives across the shared thread pool — half the devices enter
# program A's ppermute while the rest sit in program B's, and the 40s
# rendezvous kill-switch aborts the process. One program at a time
# cannot deadlock.
jax.config.update("jax_cpu_enable_async_dispatch", False)

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                               LlamaForCausalLMPipe)


def _cfg(par, sep=False):
    return LlamaConfig(vocab_size=256, hidden_size=64, num_hidden_layers=4,
                       num_attention_heads=4, num_key_value_heads=2,
                       intermediate_size=128, max_position_embeddings=32,
                       rope_theta=10000.0, tensor_parallel=par,
                       sequence_parallel=par,
                       sep_parallel="ring" if (par and sep) else None)


def _ref_losses(cfg, ids_np, steps=2):
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    ids = paddle.to_tensor(ids_np)
    out = []
    for _ in range(steps):
        _, loss = model(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        out.append(float(loss.item()))
    return out


def _reset():
    fleet.fleet._hcg = None
    fleet.fleet._topology = None
    fleet.fleet._is_initialized = False


def _run_hybrid(hybrid, schedule, ids_np, sep=False, num_virtual=None,
                steps=2):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = hybrid
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "schedule_mode": schedule}
    if num_virtual is not None:
        strategy.pipeline_configs["num_virtual_pipeline_stages"] = \
            num_virtual
    fleet.init(is_collective=True, strategy=strategy)
    try:
        hcg = fleet.get_hybrid_communicate_group()
        paddle.seed(0)
        model = LlamaForCausalLMPipe(_cfg(True, sep=sep))
        engine = fleet.fleet.distributed_model(model)
        opt = fleet.fleet.distributed_optimizer(
            paddle.optimizer.AdamW(1e-3, parameters=model.parameters()))
        batch_spec = PartitionSpec(("data", "sharding"),
                                   "sep" if sep else None)
        ids = jax.device_put(jnp.asarray(ids_np),
                             NamedSharding(hcg.global_mesh, batch_spec))
        ids_p = paddle.Tensor(ids)
        return [float(engine.train_batch((ids_p, ids_p), opt).item())
                for _ in range(steps)]
    finally:
        _reset()


def family_4d():
    """dp2 x sharding2 x mp2 x pp2 — dp is LIVE (the 8-device mesh forces
    dp=1 whenever mp>1), so the dp gradient mean is exercised against
    microbatch accumulation with every axis >1."""
    hybrid = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
              "sharding_degree": 2, "sep_degree": 1, "ep_degree": 1}
    # batch divisible by dp*sharding=4 and accumulate_steps=2
    ids_np = np.random.RandomState(0).randint(
        0, 256, (8, 16)).astype(np.int64)
    ref = _ref_losses(_cfg(False), ids_np)
    for schedule, nv in (("FThenB", None), ("interleaved", 2)):
        losses = _run_hybrid(hybrid, schedule, ids_np, num_virtual=nv)
        np.testing.assert_allclose(losses, ref, rtol=1e-3, atol=1e-5,
                                   err_msg=f"4d {schedule}")
        print(f"4d dp2xsharding2xmp2xpp2 {schedule}: "
              f"losses={losses[0]:.4f},{losses[1]:.4f} == ref OK",
              flush=True)


def family_5d():
    """pp2 x mp2 x sep2 x sharding2 — ring-CP activations cross stage
    boundaries while optimizer state is ZeRO-sharded and the batch is
    sharded over a live axis."""
    hybrid = {"dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
              "sharding_degree": 2, "sep_degree": 2, "ep_degree": 1}
    ids_np = np.random.RandomState(0).randint(
        0, 256, (4, 32)).astype(np.int64)
    ref = _ref_losses(_cfg(False), ids_np)
    for schedule, nv in (("FThenB", None), ("interleaved", 2)):
        losses = _run_hybrid(hybrid, schedule, ids_np, sep=True,
                             num_virtual=nv)
        np.testing.assert_allclose(losses, ref, rtol=1e-3, atol=1e-5,
                                   err_msg=f"5d {schedule}")
        print(f"5d pp2xmp2xsep2xsharding2 {schedule}: "
              f"losses={losses[0]:.4f},{losses[1]:.4f} == ref OK",
              flush=True)


if __name__ == "__main__":
    assert jax.device_count() >= N_DEV, jax.device_count()
    fam = sys.argv[1] if len(sys.argv) > 1 else "4d"
    {"4d": family_4d, "5d": family_5d}[fam]()
    print(f"hybrid16 {fam} OK", flush=True)
