"""Dropless grouped-matmul MoE (MegaBlocks formulation, SURVEY.md §2.3
EP row "Megablocks-style Pallas grouped matmul"): numeric + gradient
parity of the Pallas kernels (interpret mode on CPU) and of the dropless
forward against the capacity path with generous capacity (same routing,
no drops on either side => identical math)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops import moe as moe_ops
from paddle_tpu.ops.pallas.grouped_matmul import (grouped_dw,
                                                  grouped_matmul,
                                                  grouped_matmul_t)


def _layout(gate_idx, E, bm):
    perm, tile_gid, P = moe_ops.sort_rows_by_expert(gate_idx, E, bm=bm)
    return np.asarray(perm), np.asarray(tile_gid), P


def test_sort_rows_layout():
    """Every row lands in a tile owned by its expert; tiles are
    bm-aligned, non-decreasing, and every expert owns >= 1 tile."""
    rng = np.random.RandomState(0)
    E, bm, T, k = 5, 8, 33, 2
    gate_idx = jnp.asarray(rng.randint(0, E, (T, k)).astype(np.int32))
    perm, tile_gid, P = _layout(gate_idx, E, bm)
    assert P % bm == 0 and P >= T * k and len(tile_gid) == P // bm
    assert (np.diff(tile_gid) >= 0).all()
    assert set(range(E)) <= set(tile_gid.tolist())
    e_flat = np.asarray(gate_idx).reshape(-1)
    assert len(set(perm.tolist())) == len(perm)  # injective
    for r, p in enumerate(perm):
        assert tile_gid[p // bm] == e_flat[r], (r, p)


def test_grouped_matmul_numeric_and_grad():
    rng = np.random.RandomState(1)
    E, bm, d, h = 4, 8, 16, 24
    T, k = 20, 2
    gate_idx = jnp.asarray(rng.randint(0, E, (T, k)).astype(np.int32))
    perm, tile_gid, P = _layout(gate_idx, E, bm)
    x = jnp.asarray(rng.randn(P, d).astype(np.float32))
    w = jnp.asarray(rng.randn(E, d, h).astype(np.float32))
    gid = jnp.asarray(tile_gid)

    y = grouped_matmul(x, w, gid, bn=8)
    # reference: per-row dense matmul with that row's expert
    row_e = np.repeat(tile_gid, bm)
    ref = np.einsum("td,tdh->th", np.asarray(x),
                    np.asarray(w)[row_e])
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-4)

    # transpose form
    dy = jnp.asarray(rng.randn(P, h).astype(np.float32))
    dx = grouped_matmul_t(dy, w, gid, bn=8)
    ref_dx = np.einsum("th,tdh->td", np.asarray(dy), np.asarray(w)[row_e])
    np.testing.assert_allclose(np.asarray(dx), ref_dx, rtol=1e-5,
                               atol=1e-4)

    # dw kernel (incl. an expert with zero rows: E index 3 may be empty)
    dw = grouped_dw(x, dy, gid, E, bd=8, bh=8)
    ref_dw = np.zeros((E, d, h), np.float32)
    for t in range(P):
        ref_dw[row_e[t]] += np.outer(np.asarray(x)[t], np.asarray(dy)[t])
    np.testing.assert_allclose(np.asarray(dw), ref_dw, rtol=1e-5,
                               atol=1e-3)

    # custom-vjp wiring end to end
    def loss(x, w):
        return jnp.sum(grouped_matmul(x, w, gid, bn=8) * dy)

    gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), ref_dx, rtol=1e-5,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), ref_dw, rtol=1e-5,
                               atol=1e-3)


@pytest.mark.slow
def test_dropless_matches_capacity_path():
    """With capacity high enough that nothing drops, the capacity path
    and the dropless grouped path compute the same function — outputs
    AND router/weight grads."""
    rng = np.random.RandomState(2)
    T, d, h, E, k = 32, 16, 24, 4, 2
    x = jnp.asarray(rng.randn(T, d).astype(np.float32))
    rw = jnp.asarray(rng.randn(d, E).astype(np.float32) * 0.1)
    wg = jnp.asarray(rng.randn(E, d, h).astype(np.float32) * 0.1)
    wu = jnp.asarray(rng.randn(E, d, h).astype(np.float32) * 0.1)
    wd = jnp.asarray(rng.randn(E, h, d).astype(np.float32) * 0.1)

    def f_cap(x, rw, wg, wu, wd):
        y, aux, z = moe_ops.moe_forward(
            x, rw, lambda t: moe_ops.moe_ffn_grouped(t, wg, wu, wd),
            k=k, capacity_factor=float(E), norm_topk_prob=True)
        return y, aux, z

    def f_drop(x, rw, wg, wu, wd):
        return moe_ops.moe_forward_dropless(
            x, rw, wg, wu, wd, k=k, norm_topk_prob=True, bm=8)

    y1, aux1, z1 = f_cap(x, rw, wg, wu, wd)
    y2, aux2, z2 = f_drop(x, rw, wg, wu, wd)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(float(aux1), float(aux2), rtol=1e-5)
    np.testing.assert_allclose(float(z1), float(z2), rtol=1e-5)

    def loss(fn, *args):
        y, aux, z = fn(*args)
        return jnp.sum(y * y) + aux + 0.1 * z

    g1 = jax.grad(lambda *a: loss(f_cap, *a), argnums=(0, 1, 2, 3, 4))(
        x, rw, wg, wu, wd)
    g2 = jax.grad(lambda *a: loss(f_drop, *a), argnums=(0, 1, 2, 3, 4))(
        x, rw, wg, wu, wd)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_dropless_under_tensor_parallel(reset_fleet):
    """Dropless grouped dispatch inside a GSPMD program with
    'model'-sharded attention around it (mp2, ep1): exact loss parity
    with the single-device dropless run — the Pallas grouped calls see
    replicated token rows while TP shards the dense linears."""
    import dataclasses
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import Qwen2MoeConfig, Qwen2MoeForCausalLM

    cfg_d = dataclasses.replace(Qwen2MoeConfig.tiny(), moe_dropless=True,
                                scan_layers=False)
    ids = paddle.to_tensor(np.random.RandomState(0).randint(
        0, cfg_d.vocab_size, (4, 16)).astype(np.int64))

    def train(cfg, steps=2):
        paddle.seed(0)
        m = Qwen2MoeForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())

        @paddle.jit.to_static
        def step(t):
            _, l = m(t, labels=t)
            l.backward()
            opt.step()
            opt.clear_grad()
            return l

        return [float(step(ids).item()) for _ in range(steps)]

    ref = train(cfg_d)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": -1, "mp_degree": 2,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 1, "ep_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    losses = train(dataclasses.replace(cfg_d, tensor_parallel=True))
    np.testing.assert_allclose(losses, ref, rtol=1e-3, atol=1e-4)


def test_dropless_no_drops_vs_tight_capacity():
    """The point of dropless: a skewed routing that drops tokens under
    cf=1 keeps them all under the grouped path (outputs differ from the
    capacity path exactly on the dropped assignments)."""
    rng = np.random.RandomState(3)
    T, d, h, E, k = 16, 8, 12, 4, 1
    x = jnp.asarray(rng.randn(T, d).astype(np.float32))
    rw = jnp.asarray(rng.randn(d, E).astype(np.float32))  # skewed enough
    wg = jnp.asarray(rng.randn(E, d, h).astype(np.float32) * 0.1)
    wu = jnp.asarray(rng.randn(E, d, h).astype(np.float32) * 0.1)
    wd = jnp.asarray(rng.randn(E, h, d).astype(np.float32) * 0.1)

    y_cap, _, _ = moe_ops.moe_forward(
        x, rw, lambda t: moe_ops.moe_ffn_grouped(t, wg, wu, wd),
        k=k, capacity_factor=1.0, norm_topk_prob=False)
    y_drop, _, _ = moe_ops.moe_forward_dropless(
        x, rw, wg, wu, wd, k=k, norm_topk_prob=False, bm=8)
    # expected drops from the actual routing: per-expert overflow past
    # the cf=1 capacity (queue order = token order at k=1)
    cap = max(int(1.0 * k * T / E), 1)
    e_of = np.argmax(np.asarray(x @ rw), axis=1)
    seen = {e: 0 for e in range(E)}
    dropped = np.zeros(T, bool)
    for t in range(T):
        dropped[t] = seen[e_of[t]] >= cap
        seen[e_of[t]] += 1
    assert dropped.any(), "fixture not skewed enough to drop"
    # capacity path zeroed the overflow tokens; dropless kept them
    np.testing.assert_array_equal(
        np.abs(np.asarray(y_cap)).sum(-1) == 0, dropped)
    kept_out = np.abs(np.asarray(y_drop)).sum(-1)
    assert (kept_out[dropped] > 0).all()
