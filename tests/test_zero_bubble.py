"""Explicit-schedule pipeline (1F1B / ZB-H1 zero-bubble / FThenB).

Oracles (SURVEY.md §4): schedule-table validity by construction rules,
and loss+gradient parity vs a sequential single-device reference for
every schedule kind.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from paddle_tpu.distributed.zero_bubble import (
    NOP, F, B, W, make_schedule, run_pipeline_train)

KINDS = ("fthenb", "1f1b", "zb_h1")


# --------------------------------------------------------------------------
# schedule-table properties
# --------------------------------------------------------------------------

@pytest.mark.parametrize("S,M", [(2, 2), (4, 8), (4, 5), (8, 16)])
@pytest.mark.parametrize("kind", KINDS)
def test_schedule_valid(S, M, kind):
    op, mb = make_schedule(S, M, kind)
    assert op.shape == mb.shape and op.shape[0] == S
    T = op.shape[1]
    f_done = {}
    b_done = {}
    w_done = {}
    for t in range(T):
        for d in range(S):
            o, m = int(op[d, t]), int(mb[d, t])
            if o == NOP:
                continue
            if o == F:
                if d > 0:
                    assert f_done[(d - 1, m)] <= t - 1, (d, t, m)
                f_done[(d, m)] = t
            elif o == B:
                if d == S - 1:
                    assert f_done[(d, m)] <= t - 1, (d, t, m)
                else:
                    assert b_done[(d + 1, m)] <= t - 1, (d, t, m)
                b_done[(d, m)] = t
            elif o == W:
                assert b_done[(d, m)] < t, (d, t, m)
                w_done[(d, m)] = t
    # completeness
    assert len(f_done) == S * M
    assert len(b_done) == S * M
    if kind == "zb_h1":
        assert len(w_done) == S * M
    else:
        assert not w_done


def test_zb_h1_fills_bubbles():
    """ZB-H1's W units occupy ticks 1F1B leaves idle: within the span
    where B work exists, stage 0's idle ticks must shrink."""
    S, M = 4, 8
    op1, _ = make_schedule(S, M, "1f1b")
    opz, _ = make_schedule(S, M, "zb_h1")
    # per-stage busy fraction between first and last non-NOP tick
    def idle_frac(op, d):
        row = op[d]
        nz = np.nonzero(row)[0]
        span = row[nz[0]:nz[-1] + 1]
        return float((span == NOP).mean())
    # zb_h1 does 3 unit types so it is busier inside its span
    assert idle_frac(opz, 0) < idle_frac(op1, 0) + 1e-9
    assert (opz == W).sum() == S * M


def test_1f1b_inflight_cap():
    """In-flight microbatches on stage d never exceed S - d (the memory
    bound that distinguishes 1F1B from FThenB)."""
    S, M = 4, 12
    op, mb = make_schedule(S, M, "1f1b")
    T = op.shape[1]
    for d in range(S):
        inflight = 0
        peak = 0
        for t in range(T):
            if op[d, t] == F:
                inflight += 1
            elif op[d, t] == B:
                inflight -= 1
            peak = max(peak, inflight)
        assert peak <= S - d, (d, peak)


# --------------------------------------------------------------------------
# numeric parity vs sequential reference
# --------------------------------------------------------------------------

def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _loss_fn(y, tgt):
    return jnp.mean((y - tgt) ** 2)


def _reference(params, x_micro, tgt_micro):
    """Sequential single-device execution of the same stacked stages."""
    S = params["w"].shape[0]

    def total_loss(ps):
        acc = 0.0
        for m in range(x_micro.shape[0]):
            h = x_micro[m]
            for s in range(S):
                h = _stage_fn(
                    {"w": ps["w"][s], "b": ps["b"][s]}, h)
            acc = acc + _loss_fn(h, tgt_micro[m])
        return acc

    loss, grads = jax.value_and_grad(total_loss)(params)
    return loss, grads


@pytest.mark.parametrize(
    "kind", [k if k == "fthenb" else pytest.param(
        k, marks=pytest.mark.slow) for k in KINDS])
def test_train_step_parity(kind):
    # fthenb stays in the fast gate; the explicit-table schedules are
    # certified by the slow tier AND the driver's dryrun_multichip
    S, M, mb, dim = 4, 6, 2, 8
    rng = np.random.RandomState(0)
    params = {
        "w": jnp.asarray(rng.randn(S, dim, dim) * 0.3, jnp.float32),
        "b": jnp.asarray(rng.randn(S, dim) * 0.1, jnp.float32),
    }
    x = jnp.asarray(rng.randn(M, mb, dim), jnp.float32)
    tgt = jnp.asarray(rng.randn(M, mb, dim), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:S]), ("pipe",))

    loss, dp, y_micro = run_pipeline_train(
        _stage_fn, _loss_fn, params, x, tgt, mesh, "pipe", kind)

    ref_loss, ref_grads = _reference(params, x, tgt)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref_loss),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dp["w"]),
                               np.asarray(ref_grads["w"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dp["b"]),
                               np.asarray(ref_grads["b"]),
                               rtol=1e-4, atol=1e-5)
    # forward outputs banked on the last stage
    h = x
    for s in range(S):
        h = jax.vmap(lambda xm: _stage_fn(
            {"w": params["w"][s], "b": params["b"][s]}, xm))(h)
    np.testing.assert_allclose(np.asarray(y_micro), np.asarray(h),
                               rtol=1e-5, atol=1e-5)


def test_jit_wrapped():
    """The whole schedule compiles into one jitted program."""
    S, M, mb, dim = 4, 4, 2, 4
    rng = np.random.RandomState(1)
    params = {
        "w": jnp.asarray(rng.randn(S, dim, dim) * 0.3, jnp.float32),
        "b": jnp.asarray(rng.randn(S, dim) * 0.1, jnp.float32),
    }
    x = jnp.asarray(rng.randn(M, mb, dim), jnp.float32)
    tgt = jnp.asarray(rng.randn(M, mb, dim), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:S]), ("pipe",))

    @jax.jit
    def step(p, x, t):
        return run_pipeline_train(_stage_fn, _loss_fn, p, x, t,
                                  mesh, "pipe", "zb_h1")

    loss, dp, _ = step(params, x, tgt)
    ref_loss, ref_grads = _reference(params, x, tgt)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref_loss),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dp["w"]),
                               np.asarray(ref_grads["w"]),
                               rtol=1e-4, atol=1e-5)
