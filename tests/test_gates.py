"""ISSUE-5 satellite: tools/run_gates.py — the single hygiene-gate
entry point. Fast tier: the gate RUNNER itself is covered, so the gate
list cannot silently drift out of the builder workflow (each
individual gate has its own deeper tests —
test_checkpoint_hygiene.py, test_tuner.py)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUN_GATES = os.path.join(REPO, "tools", "run_gates.py")


def _run(*args):
    return subprocess.run([sys.executable, RUN_GATES, *args],
                          capture_output=True, text=True, cwd=REPO)


def test_known_gates_are_registered():
    """The authoritative gate list must contain every hygiene gate the
    repo ships — dropping one here is exactly the drift this driver
    exists to prevent."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import run_gates
        names = [n for n, _ in run_gates.gate_commands("x.log", 300.0,
                                                       False)]
    finally:
        sys.path.pop(0)
    assert names == ["atomic_writes", "metric_names",
                     "fast_tier_budget", "elastic_chaos",
                     "serving_chaos", "fleet_chaos", "prefix_cache",
                     "proc_fleet_chaos", "disagg_chaos",
                     "serving_parity", "spec_decode",
                     "autoscale_scenarios", "quant_serving",
                     "fused_parity", "observability", "http_api"]
    assert len(names) == 16    # ISSUE-20 pin: 16 gates, none dropped


def test_all_gates_pass_on_healthy_log(tmp_path):
    # --no-chaos/--no-serving/--no-fused: the heavyweight gates run
    # ONCE in the fast tier through their own test modules (+ the slow
    # full-driver test below); re-spawning them here would double
    # their cost for no coverage
    log = tmp_path / "t1.log"
    log.write_text("606 passed, 2 failed in 115.60s (0:01:55)\n")
    p = _run("--log", str(log), "--no-chaos", "--no-serving",
             "--no-fused", "--no-observability", "--no-http")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "atomic_writes: PASS" in p.stdout
    assert "metric_names: PASS" in p.stdout
    assert "fast_tier_budget: PASS" in p.stdout
    assert "elastic_chaos" not in p.stdout
    assert "serving_chaos" not in p.stdout
    assert "fleet_chaos" not in p.stdout
    assert "prefix_cache" not in p.stdout
    assert "proc_fleet_chaos" not in p.stdout
    assert "disagg_chaos" not in p.stdout
    assert "serving_parity" not in p.stdout
    assert "spec_decode" not in p.stdout
    assert "autoscale_scenarios" not in p.stdout
    assert "quant_serving" not in p.stdout
    assert "fused_parity" not in p.stdout
    assert "observability" not in p.stdout
    assert "http_api" not in p.stdout
    assert "all gates passed" in p.stdout


@pytest.mark.slow
@pytest.mark.fault
def test_full_driver_including_chaos_gate(tmp_path):
    """The whole gate list end to end, elastic chaos smoke included —
    what the builder workflow actually runs after tier-1."""
    log = tmp_path / "t1.log"
    log.write_text("606 passed, 2 failed in 115.60s (0:01:55)\n")
    p = _run("--log", str(log))
    assert p.returncode == 0, p.stdout + p.stderr
    assert "elastic_chaos: PASS" in p.stdout
    assert "serving_chaos: PASS" in p.stdout
    assert "fleet_chaos: PASS" in p.stdout
    assert "prefix_cache: PASS" in p.stdout
    assert "proc_fleet_chaos: PASS" in p.stdout
    assert "disagg_chaos: PASS" in p.stdout
    assert "serving_parity: PASS" in p.stdout
    assert "spec_decode: PASS" in p.stdout
    assert "autoscale_scenarios: PASS" in p.stdout
    assert "quant_serving: PASS" in p.stdout
    assert "fused_parity: PASS" in p.stdout
    assert "observability: PASS" in p.stdout
    assert "http_api: PASS" in p.stdout
    assert "all gates passed" in p.stdout


def test_over_budget_log_fails_the_driver(tmp_path):
    log = tmp_path / "t1.log"
    log.write_text("606 passed in 700.00s (0:11:40)\n")
    p = _run("--log", str(log), "--no-chaos", "--no-serving",
             "--no-fused", "--no-observability", "--no-http")
    assert p.returncode == 1
    assert "fast_tier_budget: FAIL" in p.stdout


def test_missing_log_is_a_failing_gate(tmp_path):
    p = _run("--log", str(tmp_path / "nope.log"), "--no-chaos",
             "--no-serving", "--no-fused", "--no-observability",
             "--no-http")
    assert p.returncode == 1     # silence must never read as clean


def test_no_budget_skips_only_the_budget_gate(tmp_path):
    p = _run("--no-budget", "--no-chaos", "--no-serving",
             "--no-fused", "--no-observability", "--no-http",
             "--log", str(tmp_path / "nope.log"))
    assert p.returncode == 0
    assert "atomic_writes: PASS" in p.stdout
    assert "fast_tier_budget" not in p.stdout
