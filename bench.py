"""Driver benchmark: Llama fwd/bwd bf16 on one chip (BASELINE config 2
shape; the 8B config does not fit a 16GB v5e, so the chip-appropriate Llama
variant is picked by HBM size and MFU is reported against the chip's peak).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline = achieved MFU / 0.40 (the north-star MFU target).
"""

from __future__ import annotations

import json
import sys
import time


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    # order matters: 'v6 lite' (v6e) must match before the generic
    # 'lite'/'v5' clauses
    if "v6" in kind:
        return 918e12  # v6e (Trillium) bf16 peak
    if "v5p" in kind or "v5 p" in kind:
        return 459e12
    if "v5" in kind or "v5e" in kind or "lite" in kind:
        return 197e12  # v5e bf16 peak
    if "v4" in kind:
        return 275e12
    return 50e12  # unknown / CPU fallback so the line still prints


def main():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    on_tpu = dev.platform.lower() in ("tpu", "axon")

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        try:
            hbm = dev.memory_stats().get("bytes_limit", 16e9)
        except Exception:
            hbm = 16e9
        if hbm > 64e9:
            cfg = LlamaConfig.llama3_8b()
            batch, seq = 4, 2048
            cfg.use_recompute = True
        else:
            # v5e 16GB: B=2 fits without remat (measured 47% MFU; remat
            # configs trade ~12 MFU points for batch)
            cfg = LlamaConfig.llama_1b()
            batch, seq = 2, 2048
            cfg.use_recompute = False
        cfg.scan_layers = False  # unrolled beats lax.scan on-chip today
        steps, warmup = 10, 3
    else:
        cfg = LlamaConfig.tiny()
        batch, seq = 2, 128
        steps, warmup = 5, 2
    cfg.tensor_parallel = False

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.to(dtype="bfloat16")

    import numpy as np
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size,
                                         (batch, seq + 1)).astype(np.int64))

    @paddle.jit.to_static
    def fwd_bwd(ids):
        _, loss = model(ids, labels=ids)
        loss.backward()
        # keep backward alive in the compiled program: fold grads into the
        # returned scalar, then drop them. (A no-compute
        # optimization_barrier was tried instead — it pins every grad
        # buffer live until the end of step and HBM-thrashes: 930 ms vs
        # 182 ms. The per-grad reduce lets each grad die right after it
        # is produced.)
        gsum = None
        for p in model.parameters():
            if p.grad is not None:
                s = p.grad.astype("float32").sum()
                gsum = s if gsum is None else gsum + s
        for p in model.parameters():
            p.clear_grad()
        return loss, gsum

    # distinct inputs per step: an execution-caching layer between host
    # and chip (e.g. the axon tunnel) must not be able to replay results
    step_ids = [paddle.to_tensor(np.roll(np.asarray(ids.numpy()), i,
                                         axis=1))
                for i in range(steps)]

    # warmup / compile (scalar fetch = the only true sync through the
    # axon tunnel; block_until_ready fake-completes there)
    for _ in range(warmup):
        loss, gsum = fwd_bwd(ids)
    float(loss.item())

    t0 = time.perf_counter()
    acc = None
    for i in range(steps):
        loss, gsum = fwd_bwd(step_ids[i])
        acc = loss if acc is None else acc + loss
    float(acc.item())  # device-chained; one final scalar sync
    dt = (time.perf_counter() - t0) / steps

    tokens = batch * seq
    n_params = sum(p.size for p in model.parameters())
    L, d = cfg.num_hidden_layers, cfg.hidden_size
    # MFU counts model FLOPs only (6*N*tokens + attention); recompute's
    # re-forward work is real hardware time but not model FLOPs, so it is
    # deliberately NOT added (that would report HFU and inflate the metric)
    flops_per_step = 6.0 * n_params * tokens + 12.0 * L * batch * seq * seq * d
    mfu = flops_per_step / dt / _peak_flops(dev)
    tok_per_s = tokens / dt

    print(json.dumps({
        "metric": f"llama_{n_params/1e9:.2f}B_fwd_bwd_bf16_tokens_per_sec"
                  + ("" if on_tpu else "_cpu_smoke"),
        "value": round(tok_per_s, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
    }))
    print(f"# step {dt*1000:.1f} ms, params {n_params/1e9:.3f}B, "
          f"MFU {mfu*100:.1f}% of {_peak_flops(dev)/1e12:.0f} TFLOP/s "
          f"({getattr(dev, 'device_kind', dev.platform)}), "
          f"loss {float(loss.item()):.3f}", file=sys.stderr)


if __name__ == "__main__":
    main()
