"""Driver benchmark (BASELINE configs 2 & 5, chip-sized):

1. TRAIN (headline metric): Llama fwd/bwd bf16 on one chip at the
   LARGEST config that fits its HBM — ~2.4B with rematerialization on a
   16GB v5e (the 8B config needs 16GB for bf16 params+grads alone; see
   BASELINE.md for the arithmetic). MFU is reported against the chip's
   bf16 peak; vs_baseline = MFU / 0.40 (the north-star target).
2. DECODE (secondary, extra JSON keys): KV-cache greedy decode
   throughput on the 1B config — tokens/s across a batch of streams.

Prints the JSON record line INCREMENTALLY: once after the core
(train/decode/cb) sections, then re-printed enriched after each MoE
section. Every printed line is a complete, parseable record — whichever
line is last when the driver's time limit hits carries everything
measured so far:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "decode_*": ..., "cb_*": ..., "moe_*": ..., "moe_decode_*": ...}
"""

from __future__ import annotations

import json
import os
import sys
import time


def _provenance(dev) -> dict:
    """Attribution metadata stamped into EVERY record line: when a
    round goes sideways (BENCH_r05's tunnel outage), the artifact alone
    must say which jax, which chip/backend, which restart round and
    which commit produced it — no cross-referencing driver logs."""
    import platform
    import subprocess

    import jax
    git_rev = None
    try:
        p = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        git_rev = p.stdout.strip() or None
    except Exception:
        pass
    return {
        "jax_version": jax.__version__,
        "backend": dev.platform,
        "chip": getattr(dev, "device_kind", None) or "?",
        "device_count": jax.device_count(),
        "restart_round": int(os.environ.get("PADDLE_RESTART_ROUND",
                                            "0")),
        "git_rev": git_rev,
        "python": platform.python_version(),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    }


def _retry_transient(fn, what, tries=3, wait=20.0):
    """Retry a timed section on transient runtime errors. The axon tunnel
    occasionally drops a compile/execute HTTP call (e.g. 'remote_compile:
    read body: response body closed'); one flake must not erase a whole
    round's metric (round-2 lost the decode number exactly this way)."""
    for attempt in range(tries):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — transient classification below
            msg = f"{type(e).__name__}: {e}"
            transient = any(s in msg for s in (
                "remote_compile", "response body", "DEADLINE_EXCEEDED",
                "UNAVAILABLE", "Connection", "connection", "timed out",
                "Timeout", "INTERNAL", "Socket",
                # backend-init shapes of the same tunnel outage (jax
                # wraps the PJRT plugin error; rounds 2 and 5)
                "Unable to initialize backend", "No devices found"))
            if attempt + 1 >= tries or not transient:
                raise
            print(f"# {what}: transient failure (attempt {attempt + 1}/"
                  f"{tries}): {msg}; retrying in {wait:.0f}s",
                  file=sys.stderr)
            time.sleep(wait)


def _peak_flops(device) -> float:
    # single source of truth with the profiler's MFU/roofline accounting
    # (per-generation peak table lives in profiler/cost.py)
    from paddle_tpu.profiler.cost import device_peaks
    return device_peaks(device).flops


def _train_bench(on_tpu, dev):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        try:
            hbm = dev.memory_stats().get("bytes_limit", 16e9)
        except Exception:
            hbm = 16e9
        if hbm > 64e9:
            cfg = LlamaConfig.llama3_8b()
            batch, seq = 4, 2048
            cfg.use_recompute = True
            cfg.recompute_granularity = "core_attn"
        else:
            # v5e 16GB: largest-fit ~2.4B with remat (dots_saveable);
            # shows the deep-config MFU, not just the 1B sweet spot
            cfg = LlamaConfig.llama_2_4b()
            batch, seq = 2, 2048
        cfg.scan_layers = False  # unrolled beats lax.scan on-chip today
        # (scan also OOMs at full depth: stacking weights into [L, ...]
        # transiently doubles parameter memory). Flash block sizes come
        # from the FLAGS defaults (256/512, tuned for this config).
        steps, warmup = 10, 3
    else:
        cfg = LlamaConfig.tiny()
        batch, seq = 2, 128
        steps, warmup = 5, 2
    cfg.tensor_parallel = False

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.to(dtype="bfloat16")

    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size,
                                         (batch, seq + 1)).astype(np.int64))

    @paddle.jit.to_static
    def fwd_bwd(ids):
        _, loss = model(ids, labels=ids)
        loss.backward()
        # keep backward alive in the compiled program: fold one element
        # of every grad into the returned scalar, then drop them. (A
        # no-compute optimization_barrier was tried instead — it pins
        # every grad buffer live until the end of step and HBM-thrashes:
        # 930 vs 182 ms. Full-grad sums were the round-3 choice; the
        # one-element read keeps every grad's producing ops alive while
        # skipping a 4.7GB reduce of the stacked grads — worth ~0.2 MFU
        # at 2.37B, round-4 A/B.)
        gsum = None
        for p in model.parameters():
            if p.grad is not None:
                s = p.grad.flatten()[0].astype("float32")
                gsum = s if gsum is None else gsum + s
        for p in model.parameters():
            p.clear_grad()
        return loss, gsum

    # distinct inputs per step: an execution-caching layer between host
    # and chip (e.g. the axon tunnel) must not be able to replay results
    step_ids = [paddle.to_tensor(np.roll(np.asarray(ids.numpy()), i,
                                         axis=1))
                for i in range(steps)]

    # warmup / compile (scalar fetch = the only true sync through the
    # axon tunnel; block_until_ready fake-completes there)
    for _ in range(warmup):
        loss, gsum = fwd_bwd(ids)
    float(loss.item())

    t0 = time.perf_counter()
    acc = None
    for i in range(steps):
        loss, gsum = fwd_bwd(step_ids[i])
        acc = loss if acc is None else acc + loss
    float(acc.item())  # device-chained; one final scalar sync
    dt = (time.perf_counter() - t0) / steps

    import os
    if os.environ.get("BENCH_AB_GUARD"):
        # A/B the keep-backward-alive trick: the one-element grad read
        # relies on XLA NOT sinking the slice into the backward dots; if
        # a future XLA applies slice-of-dot simplification it could DCE
        # weight-grad compute and silently inflate MFU. Time the
        # full-grad-sum variant and flag a divergence.
        @paddle.jit.to_static
        def fwd_bwd_full(ids):
            _, loss = model(ids, labels=ids)
            loss.backward()
            gsum = None
            for p in model.parameters():
                if p.grad is not None:
                    s = p.grad.astype("float32").sum()
                    gsum = s if gsum is None else gsum + s
                p.clear_grad()
            return loss, gsum

        for _ in range(2):
            loss_f, gsum_f = fwd_bwd_full(ids)
        float(loss_f.item())
        t0 = time.perf_counter()
        accf = None
        for i in range(4):
            loss_f, _ = fwd_bwd_full(step_ids[i])
            accf = loss_f if accf is None else accf + loss_f
        float(accf.item())
        dt_full = (time.perf_counter() - t0) / 4
        drift = (dt_full - dt) / dt_full
        print(f"# A/B guard: one-elem {dt*1000:.1f} ms vs full-grad-sum "
              f"{dt_full*1000:.1f} ms ({drift*100:+.1f}% incl. the "
              f"full 4.7GB reduce)", file=sys.stderr)
        if drift > 0.10:
            print("# A/B GUARD FAILED: one-element variant >10% faster "
                  "than full-grad-sum — XLA may be DCE'ing backward "
                  "compute; headline MFU suspect", file=sys.stderr)

    tokens = batch * seq
    n_params = sum(p.size for p in model.parameters())
    L, d = cfg.num_hidden_layers, cfg.hidden_size
    # MFU counts model FLOPs only (6*N*tokens + attention); recompute's
    # re-forward work is real hardware time but not model FLOPs, so it is
    # deliberately NOT added (that would report HFU and inflate the metric)
    flops_per_step = 6.0 * n_params * tokens \
        + 12.0 * L * batch * seq * seq * d
    mfu = flops_per_step / dt / _peak_flops(dev)
    tok_per_s = tokens / dt
    print(f"# train: step {dt*1000:.1f} ms, params {n_params/1e9:.3f}B, "
          f"MFU {mfu*100:.1f}% of {_peak_flops(dev)/1e12:.0f} TFLOP/s "
          f"({getattr(dev, 'device_kind', dev.platform)}), "
          f"loss {float(loss.item()):.3f}", file=sys.stderr)
    return n_params, tok_per_s, mfu


def _fit_e2e_bench(on_tpu, dev, autotune=False):
    """End-to-end fit-loop efficiency (ISSUE-5 tentpole): hapi
    ``Model.fit`` running the compiled step with device prefetch and
    non-blocking loss, measured against (a) the raw compiled
    fwd_bwd+update step over a pre-placed batch — the floor the fit
    loop should approach — and (b) the eager tape loop (CPU smoke
    only; eager per-op dispatch of the chip config through the tunnel
    would dwarf the section budget). Emits ``train_e2e_*`` keys plus
    ``input_*`` keys from the prefetch stage.

    autotune=True additionally sweeps the ``fit_pipeline`` surface
    (prefetch_depth × steps_in_flight) over short fits, committing the
    winner to the tuning cache (the serving_chunks pattern: the
    surface needs a live model + workload, so it cannot ride the
    standalone CLI builders)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.hapi import Model
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   LlamaPretrainingCriterion)

    if on_tpu:
        cfg = LlamaConfig.llama_1b()
        batch, seq, n_batches = 8, 1024, 12
    else:
        cfg = LlamaConfig.tiny()
        batch, seq, n_batches = 2, 64, 10
    cfg.tensor_parallel = False
    cfg.scan_layers = False

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    # SGD keeps optimizer-state HBM flat (the 1B + Adam moments would
    # crowd a 16GB chip next to activations); the fit-loop overhead
    # being measured is optimizer-agnostic
    m = Model(model)
    m.prepare(paddle.optimizer.SGD(1e-4, parameters=model.parameters()),
              LlamaPretrainingCriterion(cfg))

    ids_np = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch * n_batches, seq + 1)).astype(np.int64)
    ids_t = paddle.to_tensor(ids_np)
    ds = paddle.io.TensorDataset([ids_t, ids_t])

    # (a) raw compiled step over one resident batch — no loader, no
    # prefetch, no loss bookkeeping; scalar fetch only at the end.
    # Runs under the SAME fused-linear-CE default as fit (fit flips it
    # via flags.scoped_default) so the raw/fit comparison times one
    # program, and the StaticFunction cache discovered here matches
    # what fit reuses.
    from paddle_tpu.framework import flags as _flags
    x0 = paddle.to_tensor(ids_np[:batch])
    step_fn = m._static_train_step(donate=True)
    with _flags.scoped_default("FLAGS_fused_linear_cross_entropy", True):
        loss = step_fn(x0, x0)            # discovery
        loss = step_fn(x0, x0)            # compile+run
        float(np.asarray(loss._data))
        raw_steps = 2 * n_batches
        t0 = time.perf_counter()
        for _ in range(raw_steps):
            loss = step_fn(x0, x0)
        float(np.asarray(loss._data))
        raw_ms = (time.perf_counter() - t0) / raw_steps * 1e3

    tuned_fit = {}
    if autotune:
        from paddle_tpu import tuner
        from paddle_tpu.tuner.surface import sig_from_dict
        shape = {"bs": batch}
        key = tuner.make_key("fit_pipeline", sig_from_dict(shape), "-",
                             tuner.backend_signature())
        cache = tuner.get_cache()
        hit = cache.get(key)
        if hit is not None:
            tuned_fit = {"config": hit["config"], "cached_hit": True,
                         "shape_sig": sig_from_dict(shape)}
        else:
            surface = tuner.get_surface("fit_pipeline")
            # small DIVERSE slice (each candidate = one timed epoch):
            # default first, then an even stride across the rest so
            # both depth extremes get tried; candidates_tried reports
            # the truncation — no silent cap
            grid = surface.grid(shape)
            rest = [c for c in grid if c != surface.default]
            # default + both grid extremes + the middle: the corners
            # are the configs a sweep exists for, so pick them
            # literally instead of striding past them
            picks = ([rest[0], rest[len(rest) // 2], rest[-1]]
                     if rest else [])
            cands = grid[:1] + [c for i, c in enumerate(picks)
                                if c not in picks[:i]]
            trials = []
            for c in cands:
                m.fit(ds, batch_size=batch, epochs=1, verbose=0,
                      shuffle=False, log_freq=1_000_000,
                      prefetch_depth=c["prefetch_depth"],
                      steps_in_flight=c["steps_in_flight"])
                trials.append(
                    (dict(c), m._last_epoch_summary["avg_step_ms"]))
            win_cfg, win_ms = min(trials, key=lambda t: t[1])
            cache.put(key, win_cfg, median_ms=win_ms,
                      representative=on_tpu, source="search",
                      extra={"trials": len(trials)})
            tuned_fit = {"config": win_cfg, "cached_hit": False,
                         "shape_sig": sig_from_dict(shape),
                         "step_ms": round(win_ms, 3),
                         "candidates_tried": len(trials)}
            print(f"# fit autotune: {win_cfg} {win_ms:.2f} ms/step "
                  f"({len(trials)} candidates)", file=sys.stderr)

    # (b) the compiled fit loop: epoch 0 warms (compile + prefetch
    # spin-up), epoch 1 is the measurement — per-epoch stats ride the
    # profiler's epoch summary
    m.fit(ds, batch_size=batch, epochs=2, verbose=0, shuffle=False,
          log_freq=1_000_000)
    s = m._last_epoch_summary
    fit_ms = s["avg_step_ms"]
    tokens = batch * seq
    # goodput ledger projection (obs_* keys, docs/observability.md):
    # the compiled fit's wall-time partition — captured HERE, before
    # the eager oracle fit below replaces the model's ledger
    gp_keys = m._goodput.bench_keys() if m._goodput is not None else {}

    # (c) eager oracle loop (CPU smoke only — see docstring)
    eager_ms = None
    if not on_tpu:
        m.fit(ds, batch_size=batch, epochs=1, verbose=0, shuffle=False,
              log_freq=1_000_000, compiled=False)
        eager_ms = m._last_epoch_summary["avg_step_ms"]

    out = {
        "train_e2e_step_ms": round(fit_ms, 3),
        "train_e2e_raw_step_ms": round(raw_ms, 3),
        "train_e2e_overhead_ms": round(fit_ms - raw_ms, 3),
        "train_e2e_tokens_per_sec": round(tokens / (fit_ms / 1e3), 2),
        "input_wait_ms": s.get("input_wait_ms"),
        "input_h2d_mb": s.get("h2d_mb"),
        "input_prefetch_depth": m._fit_pipeline["prefetch_depth"],
        "input_steps_in_flight": m._fit_pipeline["steps_in_flight"],
    }
    out.update(gp_keys)
    if eager_ms is not None:
        out["train_e2e_eager_step_ms"] = round(eager_ms, 3)
        out["train_e2e_vs_eager"] = round(eager_ms / fit_ms, 4)
    if tuned_fit:
        out["tuned_fit_pipeline"] = tuned_fit
    print(f"# fit e2e: {fit_ms:.2f} ms/step (raw step {raw_ms:.2f} ms, "
          f"overhead {fit_ms - raw_ms:+.2f} ms"
          + (f", eager {eager_ms:.2f} ms" if eager_ms is not None else "")
          + f"), input wait {s.get('input_wait_ms')} ms/epoch",
          file=sys.stderr)
    return out


def _train_mem_bench(on_tpu, dev):
    """Peak-HBM accounting for the training hot path (ISSUE-8): turns
    the fused linear+CE memory claim into TRACKED bench records.

    Measures the lm_head+CE tail (fwd + dh/dW backward, the exact
    sub-program the fused op replaces) at the train bench geometry via
    XLA's compile-time memory analysis — ``lower().compile()`` only,
    nothing executes, so the probe is cheap and deterministic on CPU
    and TPU alike. Emits:

    - ``train_peak_hbm_gb`` / ``train_peak_hbm_unfused_gb``: peak
      temp-buffer bytes of the fused vs materialized-[N, V] tail;
      ``train_peak_hbm_ratio`` is the headline (>= 4x expected — the
      acceptance bar).
    - ``train_max_fit``: the largest ``(batch, seq)`` whose fused tail
      fits the activation budget (real ``bytes_limit`` on TPU minus
      the weight-resident floor; a nominal v5e 16GB elsewhere), found
      by doubling batch; ``train_max_fit_unfused`` for contrast — the
      bigger-batch headroom the fused path buys, as a record."""
    import numpy as np  # noqa: F401  (symmetry with sibling sections)

    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.fused_ce import fused_linear_cross_entropy

    if on_tpu:
        batch, seq, d, v = 2, 2048, 2560, 32000   # llama_2_4b train bench
        try:
            budget = float(dev.memory_stats().get("bytes_limit", 16e9))
        except Exception:
            budget = 16e9
    else:
        # the CPU-smoke fit geometry's head (llama_1b: d 2048, v 32000)
        # against the nominal v5e budget — same accounting, no chip
        batch, seq, d, v = 8, 1024, 2048, 32000
        budget = 16e9
    # activations may use roughly what is left after bf16 params+grads
    # of the 2.4B bench config (~9.6GB); the probe budget is the rest
    act_budget = budget * 0.4
    dt = jnp.bfloat16

    def tail_fused(h, w, labels):
        return fused_linear_cross_entropy(h, w, labels)

    def tail_unfused(h, w, labels):
        logits = (h @ w).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits, axis=-1)
        per = -jnp.take_along_axis(lp, labels[:, None], -1)[:, 0]
        return per.mean()

    def tail_peak_bytes(fn, n):
        """Peak temp bytes of jit(grad(tail)) at N=n rows — compile
        only, never executed."""
        h = jax.ShapeDtypeStruct((n, d), dt)
        w = jax.ShapeDtypeStruct((d, v), dt)
        lab = jax.ShapeDtypeStruct((n,), jnp.int32)
        step = jax.jit(jax.grad(fn, argnums=(0, 1)))
        mem = step.lower(h, w, lab).compile().memory_analysis()
        if mem is None:
            return None
        return float(mem.temp_size_in_bytes)

    n0 = batch * seq
    fused_b = tail_peak_bytes(tail_fused, n0)
    unfused_b = tail_peak_bytes(tail_unfused, n0)
    if fused_b is None or unfused_b is None:
        print("# train mem: memory_analysis unavailable on this "
              "backend; skipping", file=sys.stderr)
        return None

    def max_fit(fn, base_peak, cap_doublings=7):
        """Largest batch (power-of-2 ladder from the bench batch) whose
        tail fits act_budget; ``base_peak`` reuses the bench-geometry
        measurement above so the ladder's first rung never recompiles."""
        best, b, peak = None, batch, base_peak
        for _ in range(cap_doublings + 1):
            if peak is None or peak > act_budget:
                break
            best, b = b, b * 2
            peak = tail_peak_bytes(fn, b * seq)
        return best

    fit_fused = max_fit(tail_fused, fused_b)
    fit_unfused = max_fit(tail_unfused, unfused_b)
    out = {
        "train_peak_hbm_gb": round(fused_b / 1e9, 4),
        "train_peak_hbm_unfused_gb": round(unfused_b / 1e9, 4),
        "train_peak_hbm_ratio": round(unfused_b / max(fused_b, 1.0), 2),
        "train_peak_hbm_geometry": {"batch": batch, "seq": seq, "d": d,
                                    "v": v},
        "train_max_fit": {"batch": fit_fused, "seq": seq},
        "train_max_fit_unfused": {"batch": fit_unfused, "seq": seq},
    }
    if on_tpu:
        # the real chip's high-water mark across the sections run so
        # far (PJRT counts all live buffers — params included)
        try:
            peak = dev.memory_stats().get("peak_bytes_in_use")
            if peak:
                out["train_device_peak_hbm_gb"] = round(peak / 1e9, 4)
        except Exception:
            pass
    print(f"# train mem: lm_head+CE tail peak {fused_b/1e6:.1f} MB "
          f"fused vs {unfused_b/1e6:.1f} MB with [N, V] logits "
          f"(x{out['train_peak_hbm_ratio']:.1f}); max-fit batch @ seq "
          f"{seq}: {fit_fused} fused vs {fit_unfused} unfused",
          file=sys.stderr)
    return out


def _decode_bench(on_tpu):
    """Greedy KV-cache decode throughput (BASELINE config 5's serving
    shape, chip-sized): batch of streams, measure generated tokens/s in
    the steady state (prefill excluded via a timed second run whose extra
    length isolates decode)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        cfg = LlamaConfig.llama_1b()
        # long decode: the per-token time comes from a long-minus-short
        # difference, which must dominate tunnel round-trip variance
        batch, prompt, n_new = 8, 128, 512
    else:
        cfg = LlamaConfig.tiny()
        batch, prompt, n_new = 2, 8, 8
    cfg.tensor_parallel = False
    cfg.scan_layers = False

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.to(dtype="bfloat16")
    model.eval()
    ids = paddle.to_tensor(np.random.RandomState(1).randint(
        0, cfg.vocab_size, (batch, prompt)).astype(np.int64))

    def run(n, prompt):
        out, _ = model.generate(prompt, max_new_tokens=n,
                                decode_strategy="greedy_search",
                                eos_token_id=None, pad_token_id=0)
        return int(out[0, -1].item())   # scalar fetch = true sync

    # distinct prompts per call: an execution-caching layer between host
    # and chip (the axon tunnel) must not be able to replay results
    base = np.asarray(ids.numpy())
    import paddle_tpu as _p
    prompts = [_p.to_tensor(np.roll(base, i + 1, axis=1)) for i in range(6)]
    # n_new is part of the fused program's signature: warm up BOTH
    # trip counts so neither timed run pays compilation
    run(n_new, ids)
    run(4, prompts[0])

    def timed(n, prompt):
        t0 = time.perf_counter()
        run(n, prompt)
        return time.perf_counter() - t0

    # min over reps: dispatch/tunnel latency varies by ~100ms; the
    # long-short difference isolates pure decode time
    dt_long = min(timed(n_new, prompts[1]), timed(n_new, prompts[2]))
    dt_short = min(timed(4, prompts[3]), timed(4, prompts[4]))
    per_tok = max(dt_long - dt_short, 1e-9) / (n_new - 4)
    tok_per_s = batch / per_tok
    print(f"# decode: {per_tok*1000:.2f} ms/token/batch, "
          f"{tok_per_s:.0f} tokens/s (batch {batch})", file=sys.stderr)
    return tok_per_s


def _cb_bench(on_tpu, autotune=False):
    """Continuous batching over paged KV (the serving-depth metric):
    mixed-length prompt streams scheduled through fixed decode slots,
    aggregate generated tokens/s. More streams than slots, so the run
    exercises drain + re-admit mid-flight.

    autotune=True makes this section the serving_chunks sweep vehicle
    (the surface needs a model + workload, so it cannot ride the
    standalone CLI builders): a few candidate ladders from the
    registered grid each get their own engine + timed run, the
    fastest commits to the tuning cache, and the tuned_serving_chunks
    record entry reports it."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        cfg = LlamaConfig.llama_1b()
        slots, page, chunk = 8, 32, 32
        max_len, buckets = 384, (64, 128, 256)
        specs = [(64, 128), (128, 96), (192, 128), (64, 64),
                 (128, 128), (192, 96), (64, 128), (128, 64),
                 (96, 128), (160, 96), (64, 96), (128, 128)]
        reps = 2
    else:
        cfg = LlamaConfig.tiny()
        slots, page, chunk = 2, 8, 4
        max_len, buckets = 48, (8, 16)
        specs = [(6, 8), (12, 5), (9, 10), (4, 6)]
        reps = 1
    cfg.tensor_parallel = False
    cfg.scan_layers = False
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()

    # ONE engine across warmup + timed reps: the compiled prefill-bucket
    # and decode-chunk programs are cached per engine instance, and a
    # remote compile through the tunnel costs seconds — rebuilding the
    # engine inside the timed region would benchmark compilation
    eng = ContinuousBatchingEngine(model, num_slots=slots, page_size=page,
                                   max_len=max_len, decode_chunk=chunk,
                                   prompt_buckets=buckets, greedy=True)

    def timed_engine(e):
        """warmup (compiles prefill + chunk ladder) then best timed
        rep; returns (tokens/s, wall_s of the best rep, tokens)."""
        def erun(seed):
            rng = np.random.RandomState(seed)
            for plen, n in specs:
                # distinct prompts per run: the tunnel replay-caches
                # whole executions keyed on inputs
                e.add_request(rng.randint(0, cfg.vocab_size,
                                          (plen,)).astype(np.int32), n)
            done = e.run()
            return sum(len(r.tokens) for r in done)

        erun(100)
        e.reset_gauges()
        b, t, w = 0.0, 0, None
        for i in range(reps):
            t0 = time.perf_counter()
            t = erun(101 + i)
            dt = time.perf_counter() - t0
            if t / dt > b:
                b, w = t / dt, dt
        return b, w, t

    best, best_wall, toks = timed_engine(eng)

    tuned_cb = {}
    if autotune:
        # serving_chunks sweep: the bench ladder is the incumbent; a
        # few grid alternates each get a fresh engine (own compiled
        # programs) and the same workload. Winner commits to the cache
        # so every ctor that leaves the knobs None inherits it.
        from paddle_tpu import tuner
        from paddle_tpu.tuner.surface import sig_from_dict
        shape = {"slots": slots, "max_len": max_len, "page": page}
        dtype = next(iter(model.parameters()))._data.dtype
        backend = tuner.backend_signature()
        key = tuner.make_key("serving_chunks", sig_from_dict(shape),
                             str(dtype), backend)
        cache = tuner.get_cache()
        hit = cache.get(key)
        incumbent = {"decode_chunk": chunk,
                     "prefill_chunk": eng.prefill_chunk,
                     "admit_batch": eng.admit_batch}
        if hit is not None:
            tuned_cb = {"config": hit["config"], "cached_hit": True,
                        "shape_sig": sig_from_dict(shape)}
        else:
            surface = tuner.get_surface("serving_chunks")
            # small diverse slice of the grid (compile cost per
            # candidate is a whole engine); dropped breadth is implied
            # by candidates_tried in the record — not a silent cap
            cands = [c for c in surface.grid(shape)
                     if c != incumbent][:2]
            trials = [(incumbent, best_wall, best)]
            for c in cands:
                try:
                    e = ContinuousBatchingEngine(
                        model, num_slots=slots, page_size=page,
                        max_len=max_len,
                        decode_chunk=c["decode_chunk"],
                        prefill_chunk=c["prefill_chunk"],
                        admit_batch=c["admit_batch"],
                        prompt_buckets=buckets, greedy=True)
                    tps, wall, _ = timed_engine(e)
                    trials.append((dict(c), wall, tps))
                except Exception as exc:  # candidate-scoped, like the
                    print(f"# cb autotune candidate {c} failed: "
                          f"{exc!r}", file=sys.stderr)  # trial engine
            win_cfg, win_wall, win_tps = min(trials, key=lambda t: t[1])
            cache.put(key, win_cfg, median_ms=win_wall * 1e3,
                      representative=on_tpu, source="search",
                      extra={"trials": len(trials),
                             "tok_s": round(win_tps, 2)})
            tuned_cb = {"config": win_cfg, "cached_hit": False,
                        "shape_sig": sig_from_dict(shape),
                        "tok_s": round(win_tps, 2),
                        "default_tok_s": round(best, 2),
                        "candidates_tried": len(trials)}
            print(f"# cb autotune: {win_cfg} {win_tps:.0f} tok/s vs "
                  f"incumbent {best:.0f} tok/s "
                  f"({len(trials)} candidates)", file=sys.stderr)
            best = max(best, win_tps)
    # occupancy / admission-overlap / latency gauges (profiler
    # subsystem): the numbers BASELINE.md's CB-ceiling argument was
    # previously deriving by hand, plus the TTFT/ITL percentiles and
    # the compiled-signature count (ONE unified batching-step program
    # — the PR-3 engine compiled 1 prefill + a decode-chunk ladder,
    # the per-bucket baseline one prefill per bucket AND per length)
    gauges = eng.gauges()
    print(f"# continuous batching: {toks} tokens across "
          f"{len(specs)} mixed-length streams, {best:.0f} tokens/s "
          f"(occupancy {gauges['slot_occupancy'] * 100:.0f}%, prefill "
          f"overlap {gauges['prefill_overlap_frac'] * 100:.0f}%, "
          f"ttft p50 {gauges['ttft_ms_p50']:.1f}ms, itl p50 "
          f"{gauges['itl_ms_p50']:.2f}ms, {gauges['compiled_programs']} "
          f"compiled programs, {gauges['unified_steps']} unified steps)",
          file=sys.stderr)
    # A/B the PR-3 legacy engine on the SAME workload (acceptance
    # evidence for the unified-kernel rebuild: cb tok/s >= legacy).
    # Same warmup + best-rep protocol, own compiled programs.
    legacy_tps = None
    try:
        leg = ContinuousBatchingEngine(
            model, num_slots=slots, page_size=page, max_len=max_len,
            decode_chunk=chunk, prompt_buckets=buckets, greedy=True,
            unified=False)
        legacy_tps, _, _ = timed_engine(leg)
        print(f"# continuous batching (legacy engine): "
              f"{legacy_tps:.0f} tokens/s "
              f"({leg.gauges()['compiled_programs']} compiled "
              f"programs) -> unified is x{best / legacy_tps:.2f}",
              file=sys.stderr)
    except Exception as exc:  # A/B is telemetry, never fails the bench
        print(f"# legacy-engine A/B failed: {exc!r}", file=sys.stderr)
    return best, gauges, tuned_cb, legacy_tps


def _cb_spec_bench(on_tpu, autotune=False):
    """Speculative decoding A/B (ISSUE 18): spec-on vs plain on the
    SAME model and geometry at decode batch 1/4/8 — the small-batch
    decode-bound regime where one compiled program per emitted token
    is the cost spec decoding amortizes. Both legs run decode_chunk=1
    so the A/B isolates per-program amortization (the scan-tail chunk
    ladder is the OTHER amortization axis, measured by cb_value); the
    workload is n-gram-friendly (prompts with repeated spans, the
    templated-text shape) so acceptance is high — cb_spec_accept_rate
    in the record says how high, and BASELINE.md documents the caveat.

    autotune=True makes this section the ``spec_decode`` surface's
    sweep vehicle (K ladder x draft source at the batch-1 geometry;
    the surface needs a model + workload, so it cannot ride the
    standalone CLI builders): the winner commits to the tuning cache,
    where every ctor that leaves spec_k/spec_draft None inherits it.

    Plus the goodput leg: the PR-15 HTTP load harness drives the
    ``short_chat_batch1`` trace mix (low concurrency, long
    generations) against a spec-backed and a plain-backed ApiServer.
    """
    import json as _json
    import subprocess
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference import ApiServer, ContinuousBatchingEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        cfg = LlamaConfig.llama_1b()
        page, max_len, buckets = 32, 384, (64,)
        base_len, tile, n_new, reps = 16, 3, 96, 2
        http_req, http_conc = 12, 2
    else:
        cfg = LlamaConfig.tiny()
        page, max_len, buckets = 8, 64, (16,)
        base_len, tile, n_new, reps = 4, 3, 24, 2
        http_req, http_conc = 8, 2
    spec_k = 4
    cfg.tensor_parallel = False
    cfg.scan_layers = False
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()

    def make_engine(nslots, spec, **kw):
        skw = dict(spec_k=spec_k, spec_draft="ngram") if spec else {}
        skw.update(kw)
        return ContinuousBatchingEngine(
            model, num_slots=nslots, page_size=page, max_len=max_len,
            decode_chunk=1, prompt_buckets=buckets, greedy=True, **skw)

    def prompts_for(nreq, seed):
        # repeated-span prompts: the generated stream re-walks its own
        # prompt, the n-gram source's best case
        rng = np.random.RandomState(seed)
        return [np.tile(rng.randint(0, cfg.vocab_size,
                                    (base_len,)).astype(np.int32),
                        tile) for _ in range(nreq)]

    def timed(eng, nreq, seed0):
        """Warmup (compiles) then best-of-reps tok/s + gauges."""
        def erun(seed):
            for p in prompts_for(nreq, seed):
                eng.add_request(p, n_new)
            done = eng.run()
            return sum(len(r.tokens) for r in done)

        erun(900)
        eng.reset_gauges()
        best = 0.0
        for i in range(reps):
            t0 = time.perf_counter()
            t = erun(seed0 + i)
            best = max(best, t / max(time.perf_counter() - t0, 1e-9))
        return best, eng.gauges()

    batches = {}
    for b in (1, 4, 8):
        nreq = b if on_tpu else max(b, 2)
        plain_tps, _ = timed(make_engine(b, spec=False), nreq, 910 + b)
        spec_tps, g = timed(make_engine(b, spec=True), nreq, 910 + b)
        batches[f"b{b}"] = {
            "tok_s": round(spec_tps, 2),
            "plain_tok_s": round(plain_tps, 2),
            "vs_plain": round(spec_tps / plain_tps, 4)
            if plain_tps else 0.0,
            "itl_ms_p99": round(g["itl_ms_p99"], 3),
            "accept_rate": round(g["spec_accept_rate"], 4),
        }
        print(f"# cb spec b{b}: {spec_tps:.1f} tok/s vs plain "
              f"{plain_tps:.1f} (x{batches[f'b{b}']['vs_plain']}), "
              f"accept {batches[f'b{b}']['accept_rate']}, itl p99 "
              f"{batches[f'b{b}']['itl_ms_p99']} ms", file=sys.stderr)

    b1 = batches["b1"]
    out = {
        # headline keys = the batch-1 interactive regime where one
        # program per token hurts most (acceptance criterion:
        # cb_spec_vs_plain >= 1.0 here on the CPU smoke)
        "cb_spec_tok_s": b1["tok_s"],
        "cb_spec_vs_plain": b1["vs_plain"],
        "cb_spec_accept_rate": b1["accept_rate"],
        "cb_spec_itl_ms_p99": b1["itl_ms_p99"],
        "cb_spec_batches": batches,
    }

    if autotune:
        # spec_decode sweep (K x source) at the batch-1 geometry; the
        # small slice is not a silent cap — candidates_tried reports it
        from paddle_tpu import tuner
        from paddle_tpu.tuner.surface import sig_from_dict
        shape = {"slots": 1, "max_len": max_len, "page": page}
        dtype = next(iter(model.parameters()))._data.dtype
        key = tuner.make_key("spec_decode", sig_from_dict(shape),
                             str(dtype), tuner.backend_signature())
        cache = tuner.get_cache()
        hit = cache.get(key)
        if hit is not None:
            out["tuned_spec_decode"] = {
                "config": hit["config"], "cached_hit": True,
                "shape_sig": sig_from_dict(shape)}
        else:
            surface = tuner.get_surface("spec_decode")
            incumbent = {"k": spec_k, "source": "ngram"}
            cands = [c for c in surface.grid(shape)
                     if c != incumbent][:3]
            trials = [(incumbent, b1["tok_s"])]
            for c in cands:
                try:
                    e = make_engine(1, spec=False, spec_k=c["k"],
                                    spec_draft=c["source"])
                    tps, _ = timed(e, 1 if on_tpu else 2, 950)
                    trials.append((dict(c), tps))
                except Exception as exc:
                    print(f"# spec autotune candidate {c} failed: "
                          f"{exc!r}", file=sys.stderr)
            win_cfg, win_tps = max(trials, key=lambda t: t[1])
            cache.put(key, win_cfg, median_ms=None,
                      representative=on_tpu, source="search",
                      extra={"trials": len(trials),
                             "tok_s": round(win_tps, 2)})
            out["tuned_spec_decode"] = {
                "config": win_cfg, "cached_hit": False,
                "shape_sig": sig_from_dict(shape),
                "tok_s": round(win_tps, 2),
                "candidates_tried": len(trials)}
            print(f"# spec autotune: {win_cfg} {win_tps:.1f} tok/s "
                  f"({len(trials)} candidates)", file=sys.stderr)

    # goodput leg: short_chat_batch1 through the HTTP front door,
    # spec-backed vs plain-backed ApiServer on the same trace
    def http_leg(spec):
        eng = make_engine(2, spec=spec)
        for p in prompts_for(2, 990):
            eng.add_request(p, 4)
        eng.run()                   # warm the compiles off the clock
        srv = ApiServer(eng, stream_chunk_tokens=8).start()
        try:
            with tempfile.NamedTemporaryFile(
                    suffix=".json", delete=False) as tf:
                rep_path = tf.name
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(
                     os.path.abspath(__file__)),
                     "tools", "load_harness.py"),
                 "--url", srv.url, "--requests", str(http_req),
                 "--concurrency", str(http_conc), "--mode", "closed",
                 "--vocab", str(cfg.vocab_size),
                 "--trace-mix", "short_chat_batch1",
                 "--seed", "18", "--report", rep_path],
                capture_output=True, text=True, timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"load harness failed: {proc.stderr[-500:]}")
            with open(rep_path) as f:
                report = _json.load(f)
            os.unlink(rep_path)
            return report
        finally:
            srv.stop()

    try:
        plain_rep = http_leg(spec=False)
        spec_rep = http_leg(spec=True)
        out["cb_spec_http_tok_s"] = round(spec_rep["tok_s"], 2)
        out["cb_spec_http_goodput_frac"] = round(
            spec_rep["goodput_frac"], 4)
        out["cb_spec_http_vs_plain"] = round(
            spec_rep["tok_s"] / plain_rep["tok_s"], 4) \
            if plain_rep["tok_s"] else 0.0
        print(f"# cb spec http: {out['cb_spec_http_tok_s']} tok/s "
              f"delivered (plain {plain_rep['tok_s']:.1f}, "
              f"x{out['cb_spec_http_vs_plain']}), goodput "
              f"{out['cb_spec_http_goodput_frac']}", file=sys.stderr)
    except Exception as exc:    # the A/B headline survives a flaky leg
        print(f"# cb spec http leg failed: {exc!r}", file=sys.stderr)
    return out


def _cb_overload_bench(on_tpu):
    """Serving-reliability economics under synthetic heavy traffic
    (ISSUE 10): drive the engine ~4x past its page capacity with
    mixed-priority, deadlined requests through the
    AdmissionController + EngineSupervisor stack and report the
    overload survival numbers — throughput, tail TTFT, shed fraction,
    preemption rate and SLO goodput. BASELINE.md documents the keys."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference import (AdmissionController,
                                      ContinuousBatchingEngine,
                                      EngineSupervisor, Overloaded)
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        cfg = LlamaConfig.llama_1b()
        slots, page, chunk, max_len = 8, 32, 32, 384
        n_req, plen_lo, plen_hi, new_lo, new_hi = 96, 48, 192, 32, 96
        ttft_slo_s, total_slo_s = 30.0, 120.0
    else:
        cfg = LlamaConfig.tiny()
        slots, page, chunk, max_len = 2, 8, 4, 48
        n_req, plen_lo, plen_hi, new_lo, new_hi = 16, 3, 11, 2, 7
        ttft_slo_s, total_slo_s = 60.0, 120.0
    cfg.tensor_parallel = False
    cfg.scan_layers = False
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()

    def factory():
        # default pool (slots * pages_per_slot + 1): the queue depth
        # below is what oversubscribes it ~4x
        return ContinuousBatchingEngine(
            model, num_slots=slots, page_size=page, max_len=max_len,
            decode_chunk=chunk, greedy=True)

    sup = EngineSupervisor(factory, max_restarts=2)
    # bound chosen so a slice of the offered load is SHED (the door is
    # part of what this section measures)
    adm = AdmissionController(sup, max_queue=max(4, n_req // 2),
                              default_ttft_slo_s=ttft_slo_s)
    rng = np.random.RandomState(33)
    offered = n_req
    accepted_ids, shed = [], 0
    slos = {}
    t0 = time.perf_counter()
    for i in range(n_req):
        plen = int(rng.randint(plen_lo, plen_hi + 1))
        n_new = int(rng.randint(new_lo, new_hi + 1))
        try:
            rid = adm.submit(
                rng.randint(0, cfg.vocab_size,
                            (plen,)).astype(np.int32),
                n_new, priority=int(rng.randint(0, 3)),
                ttft_deadline_s=ttft_slo_s, deadline_s=total_slo_s)
            accepted_ids.append(rid)
            slos[rid] = (ttft_slo_s, total_slo_s)
        except Overloaded:
            shed += 1
    done = sup.run()
    wall = max(time.perf_counter() - t0, 1e-9)
    by = {r.request_id: r for r in done}
    ok = [by[i] for i in accepted_ids if by[i].error is None]
    toks = sum(len(r.tokens) for r in ok)
    ttfts = sorted((r.t_first - r.t_arrive) * 1e3
                   for r in ok if r.t_first)
    p99 = ttfts[max(0, int(round(0.99 * (len(ttfts) - 1))))] \
        if ttfts else 0.0
    slo_met = [r for r in ok
               if (r.t_first - r.t_arrive) <= slos[r.request_id][0]
               and (r.t_done - r.t_arrive) <= slos[r.request_id][1]]
    g = sup.gauges()   # counters carried across supervised restarts
    out = {
        "cb_overload_tok_s": round(toks / wall, 2),
        "cb_overload_p99_ttft_ms": round(p99, 2),
        "cb_shed_frac": round(shed / offered, 4),
        "cb_preempt_rate": round(
            g["preempt_evictions"] / max(1, len(accepted_ids)), 4),
        "cb_goodput_frac": round(
            len(slo_met) / max(1, len(accepted_ids)), 4),
    }
    print(f"# cb overload: {offered} offered / {len(accepted_ids)} "
          f"accepted / {shed} shed, {toks} tokens in {wall:.1f}s "
          f"({out['cb_overload_tok_s']} tok/s), p99 ttft "
          f"{out['cb_overload_p99_ttft_ms']} ms, preempt rate "
          f"{out['cb_preempt_rate']}, goodput "
          f"{out['cb_goodput_frac']}, restarts {sup.restarts}",
          file=sys.stderr)
    return out


def _cb_fleet_bench(on_tpu):
    """Multi-replica serving fleet (ISSUE 11): the cb workload fanned
    across 4 supervised replicas behind the fault-tolerant router,
    with a MID-RUN replica kill hard enough to trip its circuit
    breaker — aggregate delivered tok/s (failover cost included), the
    tail TTFT a routed client sees, the failover latency itself, and
    the ratio vs the SAME workload on one engine. BASELINE.md
    documents the keys."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference import ContinuousBatchingEngine, ServingFleet
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.profiler.slo import SLORule
    from paddle_tpu.testing import FaultInjector

    if on_tpu:
        cfg = LlamaConfig.llama_1b()
        slots, page, chunk, max_len = 8, 32, 32, 384
        n_req, plen_lo, plen_hi, new_lo, new_hi = 64, 48, 192, 16, 48
        kill_after = 8
    else:
        cfg = LlamaConfig.tiny()
        slots, page, chunk, max_len = 2, 8, 4, 48
        n_req, plen_lo, plen_hi, new_lo, new_hi = 24, 3, 11, 2, 7
        kill_after = 3
    cfg.tensor_parallel = False
    cfg.scan_layers = False
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()

    def factory():
        return ContinuousBatchingEngine(
            model, num_slots=slots, page_size=page, max_len=max_len,
            decode_chunk=chunk, greedy=True)

    rng = np.random.RandomState(44)
    specs = [(rng.randint(0, cfg.vocab_size,
                          (int(rng.randint(plen_lo, plen_hi + 1)),))
              .astype(np.int32),
              int(rng.randint(new_lo, new_hi + 1)))
             for _ in range(n_req)]

    # single-engine A/B: the SAME workload through one engine (its own
    # warmup) — the denominator of cb_fleet_vs_single
    single = factory()
    single.add_request(specs[0][0], specs[0][1])
    single.run()                       # warmup compiles
    single.reset_gauges()
    t0 = time.perf_counter()
    for p, n in specs:
        single.add_request(p, n)
    sdone = single.run()
    single_wall = max(time.perf_counter() - t0, 1e-9)
    single_toks = sum(len(r.tokens) for r in sdone)
    single_tps = single_toks / single_wall

    # per-tenant SLO accounting (ISSUE 13): two synthetic tenants, a
    # generous TTFT objective (the kill + failover must not break it)
    # and a delivery-success objective — the record stamps worst
    # attainment + alerts fired so the regression sentinel can gate on
    # "we kept our SLOs through the chaos", not just raw tok/s
    fleet = ServingFleet(
        factory, num_replicas=4, max_restarts=1,
        retry_backoff_s=0.01,
        slo_rules=[SLORule("ttft", kind="ttft", threshold_ms=60_000,
                           target=0.9, min_events=5),
                   SLORule("success", kind="success", target=0.9,
                           min_events=5)])
    # warm every replica outside the timed region (compiles)
    for rep in fleet.replicas.values():
        fleet._warm(rep)
    t0 = time.perf_counter()
    with FaultInjector() as fi:
        # replica 1 dies for good after a few steps: supervisor
        # restart, budget exhaustion, breaker, failover — all inside
        # the timed region (the cost IS the metric)
        fi.kill_replica(1, times=10_000, after_steps=kill_after)
        fids = [fleet.submit(p, n, tenant=f"tenant{i % 2}")
                for i, (p, n) in enumerate(specs)]
        done = fleet.run()
    wall = max(time.perf_counter() - t0, 1e-9)
    by = {r.request_id: r for r in done}
    ok = [by[f] for f in fids if by[f].error is None]
    toks = sum(len(r.tokens) for r in ok)
    ttfts = sorted((r.t_first - r.t_arrive) * 1e3
                   for r in ok if r.t_first)
    p99 = ttfts[max(0, int(round(0.99 * (len(ttfts) - 1))))] \
        if ttfts else 0.0
    g = fleet.gauges()
    slo = fleet.slo.summary()
    out = {
        "cb_fleet_tok_s": round(toks / wall, 2),
        "cb_fleet_p99_ttft_ms": round(p99, 2),
        "cb_fleet_failover_ms": round(g["failover_ms_p99"], 2),
        "cb_fleet_vs_single": round(toks / wall / single_tps, 4)
        if single_tps else 0.0,
        # SLO accounting through the chaos (BASELINE.md): worst
        # per-tenant attainment across the declared rules + burn-rate
        # alerts fired — the sentinel gates obs_slo_attainment
        "obs_slo_attainment": round(slo["worst_attainment"], 4),
        "slo_alerts": int(slo["alerts_fired"]),
        "obs_fleet_overhead_frac": round(g["obs_overhead_frac"], 5),
    }
    print(f"# cb fleet: {len(fids)} requests over 4 replicas, "
          f"replica 1 killed mid-run (breaker "
          f"{'open' if g['breaker_open'] else 'CLOSED?'}), "
          f"{toks} tokens in {wall:.1f}s "
          f"({out['cb_fleet_tok_s']} tok/s), p99 ttft "
          f"{out['cb_fleet_p99_ttft_ms']} ms, failover "
          f"{out['cb_fleet_failover_ms']} ms, vs single engine "
          f"x{out['cb_fleet_vs_single']} "
          f"(requeued {g['requeued']}, retries {g['retries']}, "
          f"delivered {len(ok)}/{len(fids)}, slo attainment "
          f"{out['obs_slo_attainment']}, alerts {out['slo_alerts']})",
          file=sys.stderr)
    return out


def _cb_procfleet_bench(on_tpu):
    """Process-backed serving fleet (ISSUE 16): the fleet workload
    over 4 REAL worker processes (``ProcReplica`` spawning ``python -m
    paddle_tpu.inference.worker``), with one worker SIGKILLed mid-run
    hard enough to spend its respawn budget and trip the breaker —
    aggregate delivered tok/s with the wire + failover cost included,
    the routed p99 TTFT, the failover latency, and the ratio vs the
    SAME workload + kill on the in-process fleet (the process
    boundary's all-in cost; ``vs_*`` keys are never gated). The
    survivors then serve a small load-harness trace through an
    ``ApiServer`` mounted on the proc-backed fleet — the front-door
    smoke key. Workers always run the tiny CPU model, even on a TPU
    host: this section measures orchestration (wire RPCs, respawn,
    salvage, reroute), which the accelerator does not change, and N
    worker processes cannot share one chip. BASELINE.md documents the
    keys (and the TPU-host caveat on the in-proc denominator)."""
    import json as _json
    import subprocess
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference import (ApiServer,
                                      ContinuousBatchingEngine,
                                      ProcReplica, ServingFleet)
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.testing import FaultInjector

    eng_kw = dict(num_slots=2, page_size=8, max_len=48,
                  decode_chunk=4, prompt_buckets=(8, 16), greedy=True)
    spec = {"factory": "paddle_tpu.inference.worker:llama_engine",
            "kwargs": dict(model="tiny", num_hidden_layers=1, seed=0,
                           **eng_kw)}
    # kill at the SECOND step: any request costs >= 2 steps, so the
    # budget-spending kill always finds in-flight work to salvage —
    # a later kill can land on a replica whose whole share already
    # finished (the PR-15 kill-smoke lesson), zeroing the failover
    # sample the section exists to price
    n_req, kill_after = 24, 1
    h_req, h_conc = 12, 4

    cfg = LlamaConfig.tiny()
    cfg.tensor_parallel = False
    cfg.scan_layers = False
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()

    def factory():
        return ContinuousBatchingEngine(model, **eng_kw)

    rng = np.random.RandomState(44)
    specs = [(rng.randint(0, cfg.vocab_size,
                          (int(rng.randint(3, 10)),)).astype(np.int32),
              int(rng.randint(2, 7))) for _ in range(n_req)]

    def run_leg(fleet, fi_install):
        for rep in fleet.replicas.values():
            fleet._warm(rep)
        t0 = time.perf_counter()
        with FaultInjector() as fi:
            fi_install(fi)
            fids = [fleet.submit(p, n) for p, n in specs]
            done = fleet.run()
        wall = max(time.perf_counter() - t0, 1e-9)
        by = {r.request_id: r for r in done}
        ok = [by[f] for f in fids if by[f].error is None]
        toks = sum(len(r.tokens) for r in ok)
        ttfts = sorted((r.t_first - r.t_arrive) * 1e3
                       for r in ok if r.t_first)
        p99 = ttfts[max(0, int(round(0.99 * (len(ttfts) - 1))))] \
            if ttfts else 0.0
        return toks / wall, p99, len(ok), len(fids)

    # in-process A/B: the SAME workload + mid-run kill through the
    # in-process fleet — the denominator of cb_procfleet_vs_inproc
    inproc = ServingFleet(factory, num_replicas=4, max_restarts=1,
                          retry_backoff_s=0.01)
    inproc_tps, _, _, _ = run_leg(
        inproc, lambda fi: fi.kill_replica(1, times=10_000,
                                           after_steps=kill_after))

    # worker processes inherit the parent's platform pin; force CPU
    # for the section's whole lifetime so RESPAWNS stay CPU too
    prev_plat = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    fleet = ServingFleet(spec, num_replicas=4, max_restarts=1,
                         retry_backoff_s=0.01,
                         replica_cls=ProcReplica,
                         replica_kwargs=dict(hb_timeout_s=5.0,
                                             respawn_backoff_s=0.01))
    srv = None
    try:
        tps, p99, n_ok, n_all = run_leg(
            fleet, lambda fi: fi.kill_worker(1, times=10_000,
                                             after_steps=kill_after))
        g = fleet.gauges()

        # front-door smoke: the surviving workers behind an ApiServer,
        # driven by the load harness as a separate client process
        srv = ApiServer(fleet).start()
        with tempfile.NamedTemporaryFile(suffix=".json",
                                         delete=False) as tf:
            rep_path = tf.name
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "load_harness.py"),
             "--url", srv.url, "--requests", str(h_req),
             "--concurrency", str(h_conc), "--mode", "closed",
             "--vocab", str(cfg.vocab_size),
             "--prompt-len", "3", "5", "--max-new", "2", "6",
             "--seed", "44", "--report", rep_path],
            capture_output=True, text=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                f"load harness failed: {proc.stderr[-500:]}")
        with open(rep_path) as f:
            report = _json.load(f)
        os.unlink(rep_path)
    finally:
        if srv is not None:
            srv.stop()
        fleet.close()
        if prev_plat is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = prev_plat

    out = {
        "cb_procfleet_tok_s": round(tps, 2),
        "cb_procfleet_p99_ttft_ms": round(p99, 2),
        "cb_procfleet_failover_ms": round(g["failover_ms_p99"], 2),
        "cb_procfleet_vs_inproc": round(tps / inproc_tps, 4)
        if inproc_tps else 0.0,
        "cb_procfleet_http_goodput_frac": round(
            report["goodput_frac"], 4),
    }
    print(f"# cb procfleet: {n_all} requests over 4 process workers, "
          f"worker 1 SIGKILLed mid-run (breaker "
          f"{'open' if g['breaker_open'] else 'CLOSED?'}), "
          f"{out['cb_procfleet_tok_s']} tok/s delivered "
          f"({n_ok}/{n_all} ok, vs in-proc fleet "
          f"x{out['cb_procfleet_vs_inproc']}), p99 ttft "
          f"{out['cb_procfleet_p99_ttft_ms']} ms, failover "
          f"{out['cb_procfleet_failover_ms']} ms, http goodput "
          f"{out['cb_procfleet_http_goodput_frac']} "
          f"({report['completed_ok']}/{report['requests']} ok)",
          file=sys.stderr)
    return out


def _cb_disagg_bench(on_tpu):
    """Disaggregated prefill/decode A/B (ISSUE 17): the named
    ``long_prompt_flood`` trace mix through 2 prefill + 2 decode
    process workers (``DisaggServingFleet``) vs the SAME mix through 4
    colocated process workers — aggregate delivered tok/s with the KV
    migration cost included, the p99 TTFT of the SHORT-chat subset
    (the number disaggregation exists to protect: colocated replicas
    stall short prefills behind long ones and behind resident decode
    turns; prefill-role slots turn over after one prefill), the p99
    migration leg, and the tok/s ratio vs colocated (``vs_*`` keys are
    never gated). Workers always run the tiny CPU model, even on a TPU
    host: the section measures role-aware orchestration (routing,
    KV transfer, slot turnover), which the accelerator does not
    change. BASELINE.md documents the keys."""
    import numpy as np

    from paddle_tpu.inference import (DisaggServingFleet, ProcReplica,
                                      ServingFleet)
    from paddle_tpu.models import LlamaConfig

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    try:
        from load_harness import build_trace_mix
    finally:
        sys.path.pop(0)

    # geometry fits the mix's long tail: prompts up to 40 tokens + 12
    # new -> max_len 64, a 40-wide prefill bucket for the floods and
    # an 8-wide one so short chats never pay the flood's padding.
    # num_pages leaves headroom for exported-page pins, so a parked
    # migration never blocks the next admission. Each role gets a
    # role-SHAPED program — the provisioning freedom that is the point
    # of disaggregation: prefill replicas keep the 40-wide mixed pass
    # but drop the decode tail they never use (decode_chunk=2), decode
    # replicas drop the 40-wide pass they never use (imported pages
    # re-prefill only short suffixes -> prompt_buckets=(8,)); the
    # colocated baseline must provision one program for BOTH phases
    eng_kw = dict(num_slots=2, page_size=8, max_len=64,
                  num_pages=48, decode_chunk=4,
                  prompt_buckets=(8, 40), greedy=True)

    def _spec(**over):
        kw = dict(model="tiny", num_hidden_layers=1, seed=0,
                  **dict(eng_kw, **over))
        return {"factory": "paddle_tpu.inference.worker:llama_engine",
                "kwargs": kw}

    spec = _spec()
    n_req = 128
    cfg = LlamaConfig.tiny()
    mix = build_trace_mix("long_prompt_flood", n_req,
                          vocab=cfg.vocab_size, seed=17)

    def run_leg(fleet):
        try:
            for rep in fleet.replicas.values():
                fleet._warm(rep)
            # workload-shaped warm wave: the sacrificial warm request
            # compiles only the 8-wide bucket; one long prompt per
            # slot compiles the 40-wide pass (and, on the disagg
            # fleet, the KV import + decode-side programs) OUTSIDE
            # the timed region — the A/B measures serving structure,
            # not whose turn 1 pays which XLA compile
            for i in range(8):
                fleet.submit(((np.arange(40) + 97 * i)
                              % cfg.vocab_size).astype(np.int32), 12)
            fleet.run()
            h = getattr(fleet, "_h_migration", None)
            if h is not None:
                h.reset()
            g0 = fleet.gauges()
            t0 = time.perf_counter()
            fids = [fleet.submit(
                np.asarray(it["prompt"], dtype=np.int32),
                int(it["max_new"])) for it in mix]
            done = fleet.run()
            wall = max(time.perf_counter() - t0, 1e-9)
            by = {r.request_id: r for r in done}
            ok = [by[f] for f in fids if by[f].error is None]
            toks = sum(len(r.tokens) for r in ok)
            short = sorted(
                (by[f].t_first - by[f].t_arrive) * 1e3
                for f, it in zip(fids, mix)
                if it["kind"] == "short" and by[f].error is None
                and by[f].t_first)
            p99 = short[max(0, int(round(0.99 * (len(short) - 1))))] \
                if short else 0.0
            g = fleet.gauges()
            g["migrations"] = (g.get("migrations", 0)
                               - g0.get("migrations", 0))
            return toks / wall, p99, len(ok), g
        finally:
            fleet.close()

    # worker processes inherit the parent's platform pin; force CPU
    # for the section's whole lifetime (same rationale as procfleet)
    prev_plat = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        repl_kw = dict(replica_cls=ProcReplica,
                       replica_kwargs=dict(hb_timeout_s=10.0,
                                           respawn_backoff_s=0.01))
        colo_tps, colo_p99, colo_ok, _ = run_leg(
            ServingFleet(spec, num_replicas=4, **repl_kw))
        # role-shaped SLOT provisioning, the other half of the
        # disaggregation win: a prefill slot parks after one token, so
        # a prefill replica can hold 6 slots where a colocated replica
        # — whose slots carry decode residency for a request's whole
        # lifetime — holds 2. num_pages grows with the slot count
        # (6 slots x 64/8 pages + exported pins in flight).
        disagg = DisaggServingFleet(
            _spec(role="prefill", decode_chunk=2, num_slots=6,
                  num_pages=96), num_prefill=2,
            num_decode=0, **repl_kw)
        for _ in range(2):
            disagg.scale_up(
                engine_factory=_spec(role="decode",
                                     prompt_buckets=(8,)),
                warm=False, role="decode")
        tps, p99, n_ok, g = run_leg(disagg)
    finally:
        if prev_plat is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = prev_plat

    out = {
        "cb_disagg_tok_s": round(tps, 2),
        "cb_disagg_p99_ttft_ms": round(p99, 2),
        "cb_disagg_colocated_p99_ttft_ms": round(colo_p99, 2),
        "cb_disagg_migration_ms_p99": round(
            g.get("migration_ms_p99", 0.0), 2),
        "cb_disagg_vs_colocated": round(tps / colo_tps, 4)
        if colo_tps else 0.0,
    }
    print(f"# cb disagg: {n_req} long_prompt_flood requests, "
          f"2 prefill + 2 decode workers "
          f"({g.get('migrations', 0)} migrations, "
          f"{n_ok}/{n_req} ok) {out['cb_disagg_tok_s']} tok/s "
          f"(x{out['cb_disagg_vs_colocated']} vs 4 colocated, "
          f"{colo_ok}/{n_req} ok), short-chat p99 ttft "
          f"{out['cb_disagg_p99_ttft_ms']} ms vs "
          f"{out['cb_disagg_colocated_p99_ttft_ms']} ms colocated, "
          f"migration p99 {out['cb_disagg_migration_ms_p99']} ms",
          file=sys.stderr)
    return out


def _cb_autoscale_bench(on_tpu):
    """SLO-driven autoscaler A/B (ISSUE 19): the seeded ``diurnal``
    and ``flash_crowd`` scenarios through a fleet with the
    :class:`FleetAutoscaler` closing the loop (1..3 replicas) vs the
    SAME schedules through a max-size FIXED fleet (3 replicas pinned).
    The claim on the goodput-vs-chips frontier: goodput and the
    scenarios' own SLO attainment bars hold while the chip-seconds
    bill (the cost model's ready-replica integral on the harness's
    virtual clock) comes in under the fixed fleet's.
    ``autoscale_vs_fixed_chips`` is a vs_* ratio — never gated.
    Always the tiny 1-layer model: the section measures the control
    loop (signals, rules, hysteresis, warm spares, drains), which the
    accelerator does not change. BASELINE.md documents the keys."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import (ContinuousBatchingEngine,
                                      FleetAutoscaler, Overloaded,
                                      ServingFleet)
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.profiler.slo import SLORule

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    try:
        from load_harness import (SCENARIOS, TickClock,
                                  build_scenario, run_fleet_scenario)
    finally:
        sys.path.pop(0)

    cfg = LlamaConfig.tiny()
    cfg.tensor_parallel = False
    cfg.scan_layers = False
    cfg.num_hidden_layers = 1
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()

    def factory():
        return ContinuousBatchingEngine(
            model, num_slots=2, page_size=8, max_len=48,
            decode_chunk=4, prompt_buckets=(8, 16), greedy=True)

    max_r = 3
    ctl_kw = dict(min_replicas=1, max_replicas=max_r,
                  up_cooldown_s=2.0, down_cooldown_s=3.0,
                  queue_high=3.0, queue_low=0.5,
                  down_stable_ticks=3)
    # few fleet turns per tick so the bursts genuinely outrun a lone
    # replica and the controller has to act (same lever as the
    # scenario gate)
    steps = {"diurnal": 1, "flash_crowd": 2}
    goodputs, attains, legs = [], [], []
    chip_auto = chip_fixed = 0.0
    decisions = 0

    for name in ("diurnal", "flash_crowd"):
        sc = SCENARIOS[name]
        schedule = build_scenario(name, vocab=cfg.vocab_size, seed=23)
        rules = [SLORule(**d) for d in sc["slo_rules"]]

        # the fixed leg: max-size fleet, no controller
        fleet = ServingFleet(factory, max_r, slo_rules=rules,
                             hedge_delay_s=None, seed=0)
        clock = TickClock()
        try:
            fixed = run_fleet_scenario(
                fleet, schedule, clock=clock, shed_exc=Overloaded,
                steps_per_tick=steps[name])
        finally:
            fleet.close()
        chip_fixed += max_r * clock.t

        # the autoscaled leg: start at the floor, let the loop drive
        fleet = ServingFleet(factory, 1, slo_rules=rules,
                             hedge_delay_s=None, seed=0)
        clock = TickClock()
        ctl = FleetAutoscaler(fleet, now_fn=clock, **ctl_kw)
        try:
            rep = run_fleet_scenario(
                fleet, schedule, autoscaler=ctl, clock=clock,
                shed_exc=Overloaded, steps_per_tick=steps[name])
        finally:
            fleet.close()
        goodputs.append(rep["goodput_frac"])
        attains.append(rep["slo"]["worst_attainment"])
        chip_auto += rep["chip_seconds"]
        decisions += int(
            fleet.metrics.counter("autoscale/decisions").value)
        legs.append((name, rep, fixed))

    out = {
        # the gated pair: worst leg carries the claim
        "autoscale_goodput_frac": round(min(goodputs), 4),
        "autoscale_slo_attainment": round(min(attains), 4),
        # lower-is-better / diagnostics: never gated
        "autoscale_chip_seconds": round(chip_auto, 2),
        "autoscale_decisions": decisions,
        "autoscale_vs_fixed_chips": round(chip_auto / chip_fixed, 4)
        if chip_fixed else 0.0,
    }
    for name, rep, fixed in legs:
        print(f"# cb autoscale {name}: goodput "
              f"{rep['goodput_frac']} (fixed {fixed['goodput_frac']}),"
              f" attainment {rep['slo']['worst_attainment']}, peak "
              f"{rep['peak_ready']} ready, chip-s "
              f"{rep['chip_seconds']}", file=sys.stderr)
    print(f"# cb autoscale: attainment "
          f"{out['autoscale_slo_attainment']}, chip-s "
          f"{out['autoscale_chip_seconds']} "
          f"(x{out['autoscale_vs_fixed_chips']} vs fixed "
          f"{max_r}-replica fleet), {decisions} decisions",
          file=sys.stderr)
    return out


def _cb_prefix_bench(on_tpu):
    """Shared-prefix storm (ISSUE 12): the acceptance A/B for
    radix-tree prefix caching — N requests sharing one long prefix
    (>= 64 requests x >= 512 prefix tokens on TPU), run COLD (cache
    empty; it self-populates mid-run, which is exactly the production
    cold shape) then WARM (prefix resident) on ONE engine, compiled
    programs kept and the cache dropped in between. Reports hit rate,
    the fraction of prefill tokens skipped, p99 TTFT cold vs warm, and
    a token-identity check against a cache-OFF engine on the same
    workload. BASELINE.md documents the keys."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        cfg = LlamaConfig.llama_1b()
        slots, page, chunk, max_len = 8, 32, 32, 768
        n_req, prefix_len, tail_hi, n_new = 64, 512, 64, 32
        prefill_chunk = 256
    else:
        cfg = LlamaConfig.tiny()
        slots, page, chunk, max_len = 2, 8, 4, 48
        n_req, prefix_len, tail_hi, n_new = 12, 24, 5, 4
        prefill_chunk = 32
    cfg.tensor_parallel = False
    cfg.scan_layers = False
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()

    rng = np.random.RandomState(55)
    prefix = rng.randint(0, cfg.vocab_size,
                         (prefix_len,)).astype(np.int32)
    specs = []
    for _ in range(n_req):
        tail = rng.randint(0, cfg.vocab_size,
                           (int(rng.randint(0, tail_hi)),)
                           ).astype(np.int32)
        specs.append((np.concatenate([prefix, tail]), n_new))
    prompt_tokens = sum(len(p) for p, _ in specs)

    def make_engine(**kw):
        return ContinuousBatchingEngine(
            model, num_slots=slots, page_size=page, max_len=max_len,
            decode_chunk=chunk, prefill_chunk=prefill_chunk,
            greedy=True, **kw)

    def storm(e):
        """One timed storm pass; returns (tok_s, p99_ttft_ms,
        gauges, streams-by-spec-index)."""
        e.reset_gauges()
        t0 = time.perf_counter()
        ids = [e.add_request(p, n) for p, n in specs]
        done = e.run()
        wall = max(time.perf_counter() - t0, 1e-9)
        by = {r.request_id: r for r in done}
        toks = sum(len(r.tokens) for r in done)
        ttfts = sorted((by[i].t_first - by[i].t_arrive) * 1e3
                       for i in ids if by[i].t_first)
        p99 = ttfts[max(0, int(round(0.99 * (len(ttfts) - 1))))] \
            if ttfts else 0.0
        return (toks / wall, p99, e.gauges(),
                [by[i].tokens for i in ids])

    eng = make_engine()
    eng.add_request(specs[0][0], 2)
    eng.run()                            # warmup: compiles
    eng.reset_prefix_cache()             # drop the warmup's pages
    cold_tps, cold_p99, cold_g, cold_streams = storm(eng)
    warm_tps, warm_p99, warm_g, warm_streams = storm(eng)
    # token-identity oracle: the SAME storm, prefix cache OFF
    off = make_engine(prefix_cache=False)
    off.add_request(specs[0][0], 2)
    off.run()
    _, off_p99, _, off_streams = storm(off)
    identical = warm_streams == off_streams \
        and cold_streams == off_streams
    saved_frac = warm_g["prefix_cache_tokens_saved"] / prompt_tokens
    out = {
        "cb_prefix_warm_tok_s": round(warm_tps, 2),
        "cb_prefix_cold_tok_s": round(cold_tps, 2),
        "cb_prefix_hit_rate": round(warm_g["prefix_cache_hit_rate"],
                                    4),
        "cb_prefix_tokens_saved_frac": round(saved_frac, 4),
        "cb_prefix_p99_ttft_ms_warm": round(warm_p99, 2),
        "cb_prefix_p99_ttft_ms_cold": round(cold_p99, 2),
        "cb_prefix_p99_ttft_ms_off": round(off_p99, 2),
        "cb_prefix_cow_forks": int(warm_g["prefix_cache_cow_forks"]),
        "cb_prefix_identical": bool(identical),
    }
    print(f"# cb prefix storm: {n_req} requests x {prefix_len}-token "
          f"shared prefix, warm {out['cb_prefix_warm_tok_s']} tok/s "
          f"vs cold {out['cb_prefix_cold_tok_s']} (cache off: "
          f"{off_p99:.1f}ms p99 ttft), hit rate "
          f"{out['cb_prefix_hit_rate']}, prefill tokens saved "
          f"{out['cb_prefix_tokens_saved_frac'] * 100:.0f}%, p99 ttft "
          f"{out['cb_prefix_p99_ttft_ms_warm']}ms warm vs "
          f"{out['cb_prefix_p99_ttft_ms_cold']}ms cold, "
          f"{out['cb_prefix_cow_forks']} cow forks, greedy streams "
          f"{'IDENTICAL' if identical else 'DIVERGED!'} vs cache-off",
          file=sys.stderr)
    return out


def _cb_quant_bench(on_tpu, autotune=False):
    """Quantized serving A/B (ISSUE 20): int8 paged-KV + weight-only
    int8 against the full-precision engine on one custom model
    (hidden 256 / head_dim 64 — wide enough that the per-token f32
    scale column amortizes: page-byte ratio 2d/(d+4) ~ 1.88 under
    bf16 pools, ~3.56 under the CPU smoke's f32 pools).

    Legs:
    - capacity (the headline): the ``capacity_probe`` trace mix —
      every request carries a real prompt AND decode budget, so page
      demand is the binding constraint — through a base-precision
      engine and an int8-KV engine holding the SAME page-pool byte
      budget (the int8 page count is derived from the engines' own
      pool-byte gauges, so the budget can never drift from the real
      allocation). ``cb_quant_capacity_ratio`` is the peak-concurrent-
      residency ratio; admission reserves a request's whole-lifetime
      pages, so peak residency IS page capacity. ``*_ratio`` keys are
      never regression-gated (they move with the host's pool dtype);
      tok/s and the accuracy keys are.
    - accuracy: greedy token-level top-1 agreement vs a same-weights
      full-precision engine, for int8-KV and for weight-only int8,
      plus a teacher-forced perplexity delta for the weight path (KV
      quantization does not touch the cacheless forward).
    - residency: prefix-cache pages resident after the same storm at
      equal bytes — more pages per byte keeps more warm prefix.
    - wire: one exported prefill migration, base vs int8, through the
      disagg JSON codec — quantized pages ship natively (no
      dequant->requant), so wire bytes drop by ~the page-byte ratio.

    autotune=True additionally sweeps the QUANTIZED ragged-attention
    surface at this bench's geometry (the ``kvq`` shape-sig component
    keeps its winner apart from bf16 entries) and commits the winner
    to the tuning cache. BASELINE.md documents the keys."""
    import json as _json

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.framework.core import Tensor
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.inference.disagg import kv_payload_to_wire
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nn.quant import quantize_for_serving

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    try:
        from load_harness import build_trace_mix
    finally:
        sys.path.pop(0)

    def make_cfg(**over):
        cfg = LlamaConfig(
            vocab_size=256, hidden_size=256, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=512, max_position_embeddings=64, **over)
        cfg.tensor_parallel = False
        cfg.scan_layers = False
        return cfg

    slots, page, max_len = 16, 8, 40
    base_pages = 17                    # 16 usable + trash page 0
    n_req = 64 if on_tpu else 36
    n_acc, acc_new = (8, 10) if on_tpu else (6, 8)

    paddle.seed(0)
    model = LlamaForCausalLM(make_cfg())
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()
    vocab = model.config.vocab_size

    def make_engine(m=None, pages=None, nslots=slots, **kw):
        return ContinuousBatchingEngine(
            m if m is not None else model, num_slots=nslots,
            page_size=page, max_len=max_len, num_pages=pages,
            decode_chunk=4, prompt_buckets=(16,), greedy=True, **kw)

    # equal-byte provisioning from the engines' OWN pool-byte gauges
    base_eng = make_engine(pages=base_pages)
    base_bytes = base_eng.gauges()["kv_quant_pool_bytes"]
    probe = make_engine(pages=base_pages, nslots=1, kv_quant="int8")
    gq = probe.gauges()
    per_page_q = (gq["kv_quant_pool_bytes"]
                  + gq["kv_quant_scale_pool_bytes"]) / base_pages
    q_pages = int(base_bytes // per_page_q)
    del probe
    quant_eng = make_engine(pages=q_pages, kv_quant="int8")

    mix = build_trace_mix("capacity_probe", n_req, vocab=vocab,
                          seed=20)

    def storm(e):
        e.add_request(np.asarray(mix[0]["prompt"], np.int32), 2)
        e.run()                      # warmup: compiles off the clock
        e.reset_prefix_cache()       # drop the warmup's pages
        e.reset_gauges()
        t0 = time.perf_counter()
        ids = [e.add_request(np.asarray(it["prompt"], np.int32),
                             int(it["max_new"])) for it in mix]
        done = e.run()
        wall = max(time.perf_counter() - t0, 1e-9)
        by = {r.request_id: r for r in done}
        ok = [by[i] for i in ids if by[i].error is None]
        toks = sum(len(r.tokens) for r in ok)
        # peak concurrent residency by interval overlap: a slot holds
        # its whole-lifetime page reservation from t_admit to t_done
        evs = sorted([(r.t_admit, 1) for r in ok if r.t_admit]
                     + [(r.t_done, -1) for r in ok if r.t_admit])
        cur = peak = 0
        for _, step in evs:
            cur += step
            peak = max(peak, cur)
        return toks / wall, peak, e.gauges()

    base_tps, base_peak, base_g = storm(base_eng)
    quant_tps, quant_peak, quant_g = storm(quant_eng)
    res_ratio = quant_g["prefix_cache_pages"] / \
        max(base_g["prefix_cache_pages"], 1)

    # accuracy: greedy token streams vs the full-precision engine on
    # the SAME weights (fresh small engines so pool pressure cannot
    # preempt and muddy the comparison)
    rng = np.random.RandomState(77)
    prompts = [rng.randint(0, vocab,
                           (int(rng.randint(6, 13)),)).astype(np.int32)
               for _ in range(n_acc)]

    def greedy_streams(e):
        ids = [e.add_request(p, acc_new) for p in prompts]
        done = e.run()
        by = {r.request_id: r for r in done}
        return [by[i].tokens for i in ids]

    def agreement(a, b):
        num = den = 0
        for x, y in zip(a, b):
            den += max(len(x), len(y))
            num += sum(1 for u, w in zip(x, y) if u == w)
        return num / max(den, 1)

    oracle = greedy_streams(make_engine(nslots=4))
    kv_top1 = agreement(oracle,
                        greedy_streams(make_engine(nslots=4,
                                                   kv_quant="int8")))

    paddle.seed(0)                     # identical init -> same weights
    wmodel = LlamaForCausalLM(make_cfg(
        weight_quant="weight_only_int8"))
    if on_tpu:
        wmodel.to(dtype="bfloat16")
    wmodel.eval()
    wstats = quantize_for_serving(wmodel)   # engine ctor then no-ops
    w_top1 = agreement(oracle, greedy_streams(make_engine(m=wmodel,
                                                          nslots=4)))
    wbytes_ratio = (wstats["bytes"] + wstats["bytes_saved"]) \
        / max(wstats["bytes"], 1)

    def mean_nll(m):
        rs = np.random.RandomState(88)
        tot = cnt = 0
        for _ in range(3):
            seq = rs.randint(0, vocab, (1, 24)).astype(np.int32)
            logits = np.asarray(m(Tensor(seq))._data, np.float32)[0]
            x = logits[:-1] - logits[:-1].max(-1, keepdims=True)
            lse = np.log(np.exp(x).sum(-1))
            tok = seq[0, 1:]
            tot += float((lse - x[np.arange(len(tok)), tok]).sum())
            cnt += len(tok)
        return tot / cnt
    ppl_delta = float(np.exp(mean_nll(wmodel)) - np.exp(mean_nll(model)))

    # wire: the disagg codec ships quantized pages natively — measure
    # one exported prefill migration base vs int8
    def wire_bytes(kvq):
        e = make_engine(nslots=2, role="prefill", kv_quant=kvq)
        e.add_request(prompts[0], 4)
        e.run()
        _, payload = e.take_migrations()[0]
        return len(_json.dumps(kv_payload_to_wire(payload)))

    wire_ratio = wire_bytes("none") / max(wire_bytes("int8"), 1)

    out = {
        "cb_quant_tok_s": round(quant_tps, 2),
        "cb_quant_base_tok_s": round(base_tps, 2),
        "cb_quant_capacity_ratio": round(
            quant_peak / max(base_peak, 1), 4),
        "cb_quant_peak_seqs": int(quant_peak),
        "cb_quant_base_peak_seqs": int(base_peak),
        "cb_quant_pages": int(q_pages - 1),
        "cb_quant_base_pages": int(base_pages - 1),
        "cb_quant_kv_bits": int(quant_g["kv_quant_bits"]),
        "cb_quant_top1_agreement": round(kv_top1, 4),
        "cb_quant_weight_top1_agreement": round(w_top1, 4),
        "cb_quant_ppl_delta": round(ppl_delta, 4),
        "cb_quant_prefix_residency_ratio": round(res_ratio, 4),
        "cb_quant_weight_bytes_ratio": round(wbytes_ratio, 4),
        "cb_quant_kv_wire_bytes_ratio": round(wire_ratio, 4),
    }

    if autotune:
        # sweep the quantized ragged surface at this bench's kernel
        # geometry; the "kvq" sig component keeps the winner apart
        # from bf16 entries (TrialEngine persists it to the cache)
        from paddle_tpu.tuner.engine import TrialEngine
        from paddle_tpu.tuner.sweeps import (ensure_builtin_surfaces,
                                             ragged_attention_builder)
        ensure_builtin_surfaces()
        d = model.config.hidden_size // model.config.num_attention_heads
        shape = {"c": 4, "pages": -(-max_len // page), "page": page,
                 "d": d, "kvq": 1}
        dtype = next(iter(model.parameters()))._data.dtype
        res = TrialEngine(warmup=1, repeats=3).search(
            "ragged_paged_attention", shape,
            ragged_attention_builder(dtype=str(dtype)),
            dtype=str(dtype))
        out["tuned_ragged_quant"] = {
            "config": dict(res.best_config),
            "shape_sig": res.shape_sig,
            "cached_hit": bool(res.cached_hit),
            "median_ms": res.best_ms}
        print(f"# quant autotune: {res.best_config} @ "
              f"{res.shape_sig} ({'cache hit' if res.cached_hit else f'{len(res.trials)} trials'})",
              file=sys.stderr)

    print(f"# cb quant: capacity x{out['cb_quant_capacity_ratio']} "
          f"({out['cb_quant_peak_seqs']} vs "
          f"{out['cb_quant_base_peak_seqs']} peak seqs at "
          f"{out['cb_quant_pages']} vs {out['cb_quant_base_pages']} "
          f"equal-byte pages), {out['cb_quant_tok_s']} tok/s (base "
          f"{out['cb_quant_base_tok_s']}), top1 agreement kv "
          f"{out['cb_quant_top1_agreement']} / weights "
          f"{out['cb_quant_weight_top1_agreement']} (ppl delta "
          f"{out['cb_quant_ppl_delta']:+.3f}), prefix residency "
          f"x{out['cb_quant_prefix_residency_ratio']}, weight bytes "
          f"x{out['cb_quant_weight_bytes_ratio']}, kv wire bytes "
          f"x{out['cb_quant_kv_wire_bytes_ratio']}", file=sys.stderr)
    return out


def _cb_http_bench(on_tpu):
    """HTTP front door overhead (ISSUE 15): the load harness drives
    the OpenAI-compatible API server (tools/load_harness.py as a
    SEPARATE process — a real client, not an in-process shortcut)
    against an engine-backed ApiServer, next to the SAME workload
    pushed straight into an identically configured engine. Interleaved
    best-of-N on both legs because single-core boxes drift; the ratio
    is the front door's all-in cost (asyncio sockets, SSE framing,
    pump bridging, AND the client's own parsing — which shares the
    engine's core when there is only one). BASELINE.md documents the
    keys and the single-core caveat."""
    import json as _json
    import subprocess
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference import ApiServer, ContinuousBatchingEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        cfg = LlamaConfig.llama_1b()
        slots, page, chunk, max_len = 8, 32, 32, 384
        n_req, conc, new_lo, new_hi = 64, 24, 128, 192
        sse_chunk, reps = 32, 2
    else:
        cfg = LlamaConfig.tiny()
        slots, page, chunk, max_len = 4, 8, 4, 128
        n_req, conc, new_lo, new_hi = 48, 16, 80, 100
        sse_chunk, reps = 32, 3
    cfg.tensor_parallel = False
    cfg.scan_layers = False
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()

    def factory():
        return ContinuousBatchingEngine(
            model, num_slots=slots, page_size=page, max_len=max_len,
            decode_chunk=chunk, prompt_buckets=(8, 16), greedy=True)

    rng = np.random.RandomState(44)
    specs = [(rng.randint(0, cfg.vocab_size,
                          (int(rng.randint(3, 6)),)).astype(np.int32),
              int(rng.randint(new_lo, new_hi + 1)))
             for _ in range(n_req)]

    def warm(e):
        for p, n in specs[:8]:
            e.add_request(p, n)
        e.run()

    direct = factory()
    warm(direct)
    served = factory()
    warm(served)
    srv = ApiServer(served, stream_chunk_tokens=sse_chunk).start()

    def direct_once():
        t0 = time.perf_counter()
        for p, n in specs:
            direct.add_request(p, n)
        done = direct.run()
        wall = max(time.perf_counter() - t0, 1e-9)
        return sum(len(r.tokens) for r in done) / wall

    def http_once():
        with tempfile.NamedTemporaryFile(
                suffix=".json", delete=False) as tf:
            rep_path = tf.name
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "load_harness.py"),
             "--url", srv.url, "--requests", str(n_req),
             "--concurrency", str(conc), "--mode", "closed",
             "--vocab", str(cfg.vocab_size),
             "--prompt-len", "3", "5",
             "--max-new", str(new_lo), str(new_hi),
             "--prefix-frac", "0.25", "--prefix-len", "4",
             "--tenants", "tenant0,tenant1",
             "--seed", "44", "--report", rep_path],
            capture_output=True, text=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                f"load harness failed: {proc.stderr[-500:]}")
        with open(rep_path) as f:
            report = _json.load(f)
        os.unlink(rep_path)
        return report

    try:
        direct_tps = 0.0
        best = None
        for _ in range(reps):
            direct_tps = max(direct_tps, direct_once())
            rep = http_once()
            if best is None or rep["tok_s"] > best["tok_s"]:
                best = rep
    finally:
        srv.stop()

    out = {
        "cb_http_tok_s": round(best["tok_s"], 2),
        "cb_http_p99_ttft_ms": round(best["ttft_ms_p99"], 2),
        "cb_http_goodput_frac": round(best["goodput_frac"], 4),
        "cb_http_vs_engine": round(best["tok_s"] / direct_tps, 4)
        if direct_tps else 0.0,
    }
    print(f"# cb http: {n_req} SSE streams x{conc} concurrent through "
          f"the front door, {out['cb_http_tok_s']} tok/s delivered "
          f"(direct engine {direct_tps:.1f}, "
          f"x{out['cb_http_vs_engine']}), p99 ttft "
          f"{out['cb_http_p99_ttft_ms']} ms, goodput "
          f"{out['cb_http_goodput_frac']}, "
          f"{best['completed_ok']}/{best['requests']} ok, "
          f"errors {best['errors'] or '{}'}",
          file=sys.stderr)
    return out


def _moe_bench_config(on_tpu):
    """The BASELINE config-5 bench shape, shared by the MoE train
    section and the breakdown section (attribution fractions are only
    meaningful on the config whose MFU they explain)."""
    import dataclasses

    from paddle_tpu.models import Qwen2MoeConfig

    if on_tpu:
        cfg = Qwen2MoeConfig(
            vocab_size=32000, hidden_size=1024, num_hidden_layers=12,
            num_attention_heads=8, num_key_value_heads=4,
            intermediate_size=2816, max_position_embeddings=4096,
            rope_theta=10000.0, num_experts=16, num_experts_per_tok=2,
            moe_intermediate_size=1408,
            shared_expert_intermediate_size=2816,
            capacity_factor=2.0, scan_layers=False,
            # dropless grouped-matmul dispatch (Pallas): kills the
            # cf=2.0 capacity padding (2x executed expert FLOPs) for
            # ~12% tile padding. Measured round 5: 235 ms/step, 38.3%
            # MFU vs 34.6-37.3 capacity
            moe_dropless=True,
            use_recompute=True,
            # remat dose: every 2nd layer saves its activations whole —
            # fs=1 / batch 6-8 still OOM 16GB even dropless (measured)
            full_save_interval=2,
            # aux folded out: the per-layer aux attribute cannot cross
            # the recompute boundary (see qwen2.py); router still trains
            # through the dispatch gradient
            router_aux_loss_coef=0.0)
        # batch 8 OOMs 16GB: the un-rematerialized expert intermediates
        # ([E, C, moe_inter] per layer) dominate activation memory
        return cfg, 4, 2048
    cfg = dataclasses.replace(Qwen2MoeConfig.tiny(), scan_layers=False)
    return cfg, 2, 64


def _moe_train_bench(on_tpu, dev):
    """MoE train MFU (BASELINE config 5: Qwen2-MoE shape, chip-sized).

    MFU counts ACTIVATED FLOPs: 6·N_active·tokens + the S² attention
    term, where N_active replaces each layer's E-expert bank with the
    k experts a token actually visits (router + shared expert + attn
    params all included). Dispatch runs the index gather/scatter path
    (ops/moe.py), so expert matmuls dominate the step, not routing."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import Qwen2MoeForCausalLM

    cfg, batch, seq = _moe_bench_config(on_tpu)
    steps, warmup = (8, 3) if on_tpu else (3, 1)

    paddle.seed(0)
    model = Qwen2MoeForCausalLM(cfg)
    model.to(dtype="bfloat16")
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size,
                                         (batch, seq + 1)).astype(np.int64))

    @paddle.jit.to_static
    def fwd_bwd(ids):
        _, loss = model(ids, labels=ids)
        loss.backward()
        gsum = None
        for p in model.parameters():
            if p.grad is not None:
                s = p.grad.astype("float32").sum()
                gsum = s if gsum is None else gsum + s
        for p in model.parameters():
            p.clear_grad()
        return loss, gsum

    step_ids = [paddle.to_tensor(np.roll(np.asarray(ids.numpy()), i,
                                         axis=1))
                for i in range(steps)]
    for _ in range(warmup):
        loss, gsum = fwd_bwd(ids)
    float(loss.item())

    t0 = time.perf_counter()
    acc = None
    for i in range(steps):
        loss, gsum = fwd_bwd(step_ids[i])
        acc = loss if acc is None else acc + loss
    float(acc.item())
    dt = (time.perf_counter() - t0) / steps

    tokens = batch * seq
    n_total = sum(p.size for p in model.parameters())
    L, d = cfg.num_hidden_layers, cfg.hidden_size
    per_expert = 3 * d * cfg.moe_intermediate_size
    n_active = n_total - L * (cfg.num_experts
                              - cfg.num_experts_per_tok) * per_expert
    flops_per_step = 6.0 * n_active * tokens \
        + 12.0 * L * batch * seq * seq * d
    mfu = flops_per_step / dt / _peak_flops(dev)
    tok_per_s = tokens / dt
    print(f"# moe train: step {dt*1000:.1f} ms, params {n_total/1e9:.3f}B "
          f"({n_active/1e9:.3f}B active), MFU {mfu*100:.1f}%, "
          f"loss {float(loss.item()):.3f}", file=sys.stderr)
    return n_total, tok_per_s, mfu


def _moe_breakdown_bench(on_tpu, dev):
    """Per-section attribution of the MoE train step (profiler
    subsystem): gating / sort / a2a / expert-matmul / other via
    compiled-variant ablation (paddle_tpu.profiler.moe_step_breakdown),
    with per-section MFU + roofline columns. This is the table VERDICT
    r5 demand 2 asked for before the next MoE tuning round — the ~60%
    non-matmul step time, attributed. Returns (breakdown_dict,
    chrome_trace_path)."""
    import os

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import Qwen2MoeForCausalLM
    from paddle_tpu.profiler import moe_step_breakdown

    cfg, batch, seq = _moe_bench_config(on_tpu)
    # each ablation variant is a fresh compile (~5 programs); keep the
    # timed loop short — attribution needs deltas, not tight CIs
    steps, warmup = (3, 1) if on_tpu else (2, 1)

    paddle.seed(0)
    model = Qwen2MoeForCausalLM(cfg)
    model.to(dtype="bfloat16")
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size,
                                         (batch, seq + 1)).astype(np.int64))
    bd = moe_step_breakdown(model, ids, steps=steps, warmup=warmup)
    trace_path = os.path.join(
        os.environ.get("PADDLE_PROFILER_LOG_DIR", "./profiler_log"),
        "moe_breakdown_trace.json")
    bd.export_chrome_trace(trace_path)
    print("# moe breakdown: step "
          f"{bd.step_ms:.1f} ms; " + "  ".join(
              f"{r['section']}={r['frac'] * 100:.1f}%"
              + (f" (MFU {r['mfu'] * 100:.1f}%)"
                 if r.get("mfu") is not None else "")
              for r in bd.rows), file=sys.stderr)
    return bd.to_dict(), trace_path


def _moe_decode_bench(on_tpu):
    """DeepSeek-V2 greedy decode through the MLA LATENT KV cache
    (the memory-side point of MLA: the cache holds [B, T, R] latents
    + rope keys instead of full per-head K/V)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import DeepseekV2Config, DeepseekV2ForCausalLM

    if on_tpu:
        cfg = DeepseekV2Config(
            vocab_size=32000, hidden_size=1024, num_hidden_layers=12,
            num_attention_heads=16, q_lora_rank=384, kv_lora_rank=256,
            qk_nope_head_dim=64, qk_rope_head_dim=32, v_head_dim=64,
            intermediate_size=2816, moe_intermediate_size=704,
            n_routed_experts=16, n_shared_experts=2,
            num_experts_per_tok=2, first_k_dense_replace=1,
            routed_scaling_factor=1.0, norm_topk_prob=True,
            max_position_embeddings=2048)
        batch, prompt, n_new = 8, 128, 256
    else:
        cfg = DeepseekV2Config.tiny()
        batch, prompt, n_new = 2, 8, 8

    paddle.seed(0)
    model = DeepseekV2ForCausalLM(cfg)
    model.to(dtype="bfloat16")
    model.eval()
    ids = paddle.to_tensor(np.random.RandomState(1).randint(
        0, cfg.vocab_size, (batch, prompt)).astype(np.int64))

    def run(n, prompt_t):
        out, _ = model.generate(prompt_t, max_new_tokens=n,
                                decode_strategy="greedy_search",
                                eos_token_id=None, pad_token_id=0)
        return int(out[0, -1].item())

    base = np.asarray(ids.numpy())
    prompts = [paddle.to_tensor(np.roll(base, i + 1, axis=1))
               for i in range(5)]
    run(n_new, ids)
    run(4, prompts[0])

    def timed(n, prompt_t):
        t0 = time.perf_counter()
        run(n, prompt_t)
        return time.perf_counter() - t0

    dt_long = min(timed(n_new, prompts[1]), timed(n_new, prompts[2]))
    dt_short = min(timed(4, prompts[3]), timed(4, prompts[4]))
    per_tok = max(dt_long - dt_short, 1e-9) / (n_new - 4)
    tok_per_s = batch / per_tok
    print(f"# moe decode (MLA latent cache): {per_tok*1000:.2f} "
          f"ms/token/batch, {tok_per_s:.0f} tokens/s (batch {batch})",
          file=sys.stderr)
    return tok_per_s


def _autotune_bench(on_tpu):
    """--autotune mode: sweep the kernel tunable surfaces at THIS
    bench's workload shapes through the trial engine and emit
    ``tuned_*`` record keys (format reserved in BASELINE.md). Runs
    BEFORE the train/moe sections so the committed winners feed them
    (the kernels consult the cache at trace time). The default config
    is always in the trial table (default-first grid order), so the
    tuned pick matches or beats the static defaults by construction —
    ``vs_default`` reports the ratio. Resumable: every finished
    (surface, shape) key is already committed atomically; a re-run
    skips it."""
    from paddle_tpu import tuner
    from paddle_tpu.tuner import sweeps

    sweeps.ensure_builtin_surfaces()
    engine = tuner.TrialEngine(warmup=2 if on_tpu else 1,
                               repeats=5 if on_tpu else 2)
    if on_tpu:
        # the MoE bench bank (config 5: d 1024, moe_inter 1408, E 16,
        # rows = batch*seq*k) and the llama train attention shape
        jobs = [
            ("grouped_matmul", {"d": 1024, "h": 1408, "E": 16},
             sweeps.grouped_matmul_builder(rows=16384), 12),
            ("grouped_matmul", {"d": 1408, "h": 1024, "E": 16},
             sweeps.grouped_matmul_builder(rows=16384), 12),
            ("flash_attention", {"sq": 2048, "sk": 2048, "d": 128},
             sweeps.flash_attention_builder(batch=2, heads=20), 8),
            # the training-kernel suite (ISSUE 8) at the 2.4B train
            # bench geometry — swept BEFORE the train sections so the
            # committed winners feed the compiled fit step
            ("rms_norm_residual", {"d": 2560},
             sweeps.rms_norm_residual_builder(rows=4096), 5),
            ("swiglu", {"h": 6912},
             sweeps.swiglu_builder(rows=4096), 9),
            ("fused_ce", {"d": 2560, "v": 32000},
             sweeps.fused_ce_builder(rows=4096), 4),
            # the cb section's unified batching-step kernel at its v5e
            # bench geometry (llama_1b: chunk 32, 12 x 32-token pages,
            # head_dim 128, 16:8 GQA) — swept BEFORE the cb section so
            # the committed winner feeds the engine's traced kernel
            ("ragged_paged_attention",
             {"c": 32, "pages": 12, "page": 32, "d": 128},
             sweeps.ragged_attention_builder(slots=8, heads=16,
                                             kv_heads=8), 10),
        ]
    else:
        jobs = [
            ("grouped_matmul", {"d": 64, "h": 128, "E": 4},
             sweeps.grouped_matmul_builder(rows=1024), 3),
            ("flash_attention", {"sq": 128, "sk": 128, "d": 64},
             sweeps.flash_attention_builder(batch=1, heads=2), 2),
            ("rms_norm_residual", {"d": 128},
             sweeps.rms_norm_residual_builder(rows=256), 2),
            ("swiglu", {"h": 256},
             sweeps.swiglu_builder(rows=256), 2),
            ("fused_ce", {"d": 64, "v": 1024},
             sweeps.fused_ce_builder(rows=256), 2),
            ("ragged_paged_attention",
             {"c": 8, "pages": 4, "page": 8, "d": 16},
             sweeps.ragged_attention_builder(slots=2, heads=4,
                                             kv_heads=2), 2),
        ]

    out = {"tuned_cache_path": engine.cache.path,
           "tuned_backend": engine.backend}
    for surface, shape, builder, max_trials in jobs:
        res = engine.search(surface, shape, builder,
                            max_trials=max_trials)
        entry = {"config": res.best_config,
                 "median_ms": None if res.best_ms is None
                 else round(res.best_ms, 4),
                 "shape_sig": res.shape_sig,
                 "representative": res.representative,
                 "cached_hit": res.cached_hit,
                 # the static default can be INVALID at a shape (e.g.
                 # flash 256/512 at sq=128 smoke shapes): the grid
                 # drops it and no default trial exists — flagged, not
                 # silently absent (BASELINE.md key reservation)
                 "default_timed": False}
        default = tuner.get_surface(surface).default
        for cfg, ms in res.trials:
            if cfg == default:
                entry["default_timed"] = True
                entry["default_ms"] = round(ms, 4)
                if res.best_ms:
                    entry["vs_default"] = round(ms / res.best_ms, 4)
                break
        key = f"tuned_{surface}_{res.shape_sig.replace(',', '_')}"
        out[key] = entry
        print(f"# autotune {surface} @ {res.shape_sig}: "
              f"{entry['config']}"
              + (f" {entry['median_ms']:.2f} ms" if entry["median_ms"]
                 else "")
              + (f" (default {entry['default_ms']:.2f} ms, "
                 f"x{entry['vs_default']:.3f})"
                 if "default_ms" in entry else "")
              + (" [cached]" if res.cached_hit else "")
              + ("" if res.representative
                 else " [NON-REPRESENTATIVE backend]"),
              file=sys.stderr)
    return out


def _emit_record(record, path=None):
    """Print the running record line AND (when ``path`` is set) flush
    it to disk with the atomic stage-then-rename protocol. Called
    after EVERY completed section: a round that times out or dies on a
    backend outage mid-run (BENCH_r04/r05 left nothing parseable)
    still leaves a complete JSON file carrying every section measured
    so far, which tools/check_bench_regression.py compares key-by-key
    against the trajectory."""
    line = json.dumps(record)
    print(line, flush=True)
    if path:
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:    # the flush is telemetry durability,
            print(f"# record flush to {path} failed: {e}",
                  file=sys.stderr)    # never a bench failure
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _timed_section(what, fn):
    """Run a bench section, logging wall time to stderr (budget telemetry:
    round-4's record never printed because the sections overran the
    driver's limit — per-section times make the budget auditable)."""
    t0 = time.perf_counter()
    try:
        return fn()
    finally:
        print(f"# [{what}: {time.perf_counter() - t0:.0f}s]",
              file=sys.stderr)


def main():
    import argparse

    import jax

    ap = argparse.ArgumentParser(description="paddle_tpu driver bench")
    ap.add_argument("--autotune", action="store_true",
                    help="sweep kernel tunable surfaces at the bench "
                         "shapes first (paddle_tpu.tuner) and emit "
                         "tuned_* record keys; winners persist to the "
                         "tuning cache and feed the timed sections")
    ap.add_argument("--record-out", default=os.environ.get(
                        "PADDLE_BENCH_RECORD"),
                    help="atomically rewrite the running record to "
                         "this file after every completed section — a "
                         "timed-out round leaves a parseable partial "
                         "record (also via $PADDLE_BENCH_RECORD)")
    args, _unknown = ap.parse_known_args()
    rec_out = args.record_out

    # Backend init is retried with LONG backoff: the rounds-2/5 axon
    # tunnel outages were transient on the scale of hours, and an
    # unretried jax.devices() here zeroed round 5's entire record
    # (BENCH_r05.json rc=1 before any section ran — VERDICT missing #1).
    def _init_backend():
        try:
            return jax.devices()[0]
        except Exception:
            # jax memoizes failed backend init; drop the cache so the
            # next attempt actually re-dials the tunnel
            try:
                import jax.extend.backend as _jeb
                _jeb.clear_backends()
            except Exception:
                pass
            raise

    dev = _retry_transient(_init_backend, "backend init",
                           tries=5, wait=120.0)
    on_tpu = dev.platform.lower() in ("tpu", "axon")

    import gc
    suffix = "" if on_tpu else "_cpu_smoke"
    tuned = {}
    if args.autotune:
        # before the timed sections: committed winners feed them (the
        # kernels read the cache at trace time); a sweep failure must
        # never sink the headline metrics
        try:
            tuned = _timed_section(
                "autotune", lambda: _retry_transient(
                    lambda: _autotune_bench(on_tpu),
                    "autotune bench"))
        except Exception as e:
            print(f"# autotune bench failed: {e!r}", file=sys.stderr)
            tuned = {}
    # The running record is re-printed after EVERY completed section:
    # whichever complete JSON line is last when the driver's time limit
    # hits carries everything measured so far. Round-4's record printed
    # only at the very end — one slow section erased every completed
    # metric (BENCH_r04.json parsed:null).
    n_params, train_tok_s, mfu = _timed_section(
        "train", lambda: _retry_transient(
            lambda: _train_bench(on_tpu, dev), "train bench"))
    record = {
        "metric": f"llama_{n_params/1e9:.2f}B_fwd_bwd_bf16_tokens_per_sec"
                  + suffix,
        "value": round(train_tok_s, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        # provenance rides every printed line (the record is re-printed
        # incrementally; each line stays attributable on its own)
        "provenance": _provenance(dev),
    }
    record.update(tuned)
    _emit_record(record, rec_out)
    gc.collect()

    # fit-loop e2e (ISSUE 5): right after the headline train metric —
    # the whole point is fit() reaching the raw step's rate
    try:
        fit_e2e = _timed_section(
            "fit e2e", lambda: _retry_transient(
                lambda: _fit_e2e_bench(on_tpu, dev,
                                       autotune=args.autotune),
                "fit e2e bench"))
    except Exception as e:
        print(f"# fit e2e bench failed: {e!r}", file=sys.stderr)
        fit_e2e = None
    gc.collect()
    if fit_e2e is not None:
        record["train_e2e_metric"] = ("llama_fit_loop_compiled_step"
                                      + suffix)
        record["train_e2e_unit"] = "tokens/s/chip"
        record.update(fit_e2e)
        _emit_record(record, rec_out)

    # peak-HBM accounting (ISSUE 8): compile-only probe — cheap, so it
    # sits right after the fit section whose memory story it documents
    try:
        mem_keys = _timed_section(
            "train mem", lambda: _train_mem_bench(on_tpu, dev))
    except Exception as e:
        print(f"# train mem bench failed: {e!r}", file=sys.stderr)
        mem_keys = None
    if mem_keys is not None:
        record.update(mem_keys)
        _emit_record(record, rec_out)

    # Section order = evidentiary priority under the driver's time
    # limit (measured round 5: train 593s, decode 353s — mostly
    # tunnel init/compile, not measurement): the MoE train MFU is the
    # round's headline addition, then serving depth (cb), then the
    # decode secondaries.
    try:
        moe_params, moe_tok_s, moe_mfu = _timed_section(
            "moe train", lambda: _retry_transient(
                lambda: _moe_train_bench(on_tpu, dev), "moe train bench"))
    except Exception as e:
        print(f"# moe train bench failed: {e!r}", file=sys.stderr)
        moe_params = moe_tok_s = moe_mfu = None
    # a failed section's exception traceback pins its model (frames hold
    # locals) — without this collect, one OOM sinks every later section
    gc.collect()
    if moe_tok_s is not None:
        record["moe_metric"] = (
            f"qwen2_moe_{moe_params/1e9:.2f}B_fwd_bwd_bf16_tokens_per_sec"
            + suffix)
        record["moe_value"] = round(moe_tok_s, 2)
        record["moe_unit"] = "tokens/s/chip"
        record["moe_mfu"] = round(moe_mfu, 4)
        _emit_record(record, rec_out)

    try:
        cb_tok_s, cb_gauges, cb_tuned, cb_legacy = _timed_section(
            "cb", lambda: _retry_transient(
                lambda: _cb_bench(on_tpu, autotune=args.autotune),
                "cb bench"))
    except Exception as e:
        print(f"# continuous-batching bench failed: {e!r}", file=sys.stderr)
        cb_tok_s = cb_gauges = cb_tuned = cb_legacy = None
    if cb_tok_s is not None:
        record["cb_metric"] = ("llama_1B_continuous_batching_mixed_lengths"
                               + suffix)
        record["cb_value"] = round(cb_tok_s, 2)
        record["cb_unit"] = "tokens/s/chip"
        record["cb_occupancy"] = round(cb_gauges["slot_occupancy"], 4)
        record["cb_prefill_overlap"] = round(
            cb_gauges["prefill_overlap_frac"], 4)
        # ISSUE-3 latency + compile-budget keys (engine gauges ride the
        # PR-2 tracer; these are the headline serving-latency numbers)
        record["cb_ttft_ms_p50"] = round(cb_gauges["ttft_ms_p50"], 2)
        record["cb_ttft_ms_p99"] = round(cb_gauges["ttft_ms_p99"], 2)
        record["cb_itl_ms_p50"] = round(cb_gauges["itl_ms_p50"], 3)
        record["cb_itl_ms_p99"] = round(cb_gauges["itl_ms_p99"], 3)
        record["cb_compiles"] = cb_gauges["compiled_programs"]
        # ISSUE-7 unified-batching-step keys: the engine now runs ONE
        # compiled program per scheduler turn (cb_compiles expected
        # ~1 steady-state), with the PR-3 engine A/B'd on the same
        # workload as the regression reference
        # (aliases of cb_value / cb_gauges.unified_steps so rounds
        # grep ONE name — assigned from the record, cannot diverge)
        record["cb_unified_tok_s"] = record["cb_value"]
        record["cb_unified_steps"] = cb_gauges["unified_steps"]
        # observability self-measurement: instrumentation's share of
        # the serving hot loop (<2% pinned by test_metrics)
        record["obs_overhead_frac"] = round(
            cb_gauges.get("obs_overhead_frac", 0.0), 6)
        if cb_legacy:
            record["cb_legacy_tok_s"] = round(cb_legacy, 2)
            record["cb_unified_vs_legacy"] = round(
                cb_tok_s / cb_legacy, 4)
        record["cb_gauges"] = {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in cb_gauges.items()}
        if cb_tuned:
            record["tuned_serving_chunks"] = cb_tuned
        _emit_record(record, rec_out)
    gc.collect()

    # speculative decoding A/B (ISSUE 18): this round's headline
    # addition, right after the cb section whose engine it accelerates
    # — the decode-batch-1/4/8 sweep, the accept-rate economics, and
    # the short_chat_batch1 goodput leg through the HTTP front door
    try:
        cb_spec = _timed_section(
            "cb spec", lambda: _retry_transient(
                lambda: _cb_spec_bench(on_tpu, autotune=args.autotune),
                "cb spec bench"))
    except Exception as e:
        print(f"# cb spec bench failed: {e!r}", file=sys.stderr)
        cb_spec = None
    gc.collect()
    if cb_spec is not None:
        record.update(cb_spec)
        _emit_record(record, rec_out)

    # serving reliability under overload (ISSUE 10): right after the
    # cb section whose engine it stresses — the survival economics
    # (shed/preempt/goodput) contextualize the throughput number above
    try:
        cb_overload = _timed_section(
            "cb overload", lambda: _retry_transient(
                lambda: _cb_overload_bench(on_tpu),
                "cb overload bench"))
    except Exception as e:
        print(f"# cb overload bench failed: {e!r}", file=sys.stderr)
        cb_overload = None
    gc.collect()
    if cb_overload is not None:
        record.update(cb_overload)
        _emit_record(record, rec_out)

    # multi-replica fleet (ISSUE 11): the scale-out + failover
    # economics next to the single-engine numbers they contextualize
    try:
        cb_fleet = _timed_section(
            "cb fleet", lambda: _retry_transient(
                lambda: _cb_fleet_bench(on_tpu),
                "cb fleet bench"))
    except Exception as e:
        print(f"# cb fleet bench failed: {e!r}", file=sys.stderr)
        cb_fleet = None
    gc.collect()
    if cb_fleet is not None:
        record.update(cb_fleet)
        _emit_record(record, rec_out)

    # process-backed fleet (ISSUE 16): the same failover economics
    # with REAL worker processes on the wire, next to the in-process
    # fleet numbers they contextualize
    try:
        cb_procfleet = _timed_section(
            "cb procfleet", lambda: _retry_transient(
                lambda: _cb_procfleet_bench(on_tpu),
                "cb procfleet bench"))
    except Exception as e:
        print(f"# cb procfleet bench failed: {e!r}", file=sys.stderr)
        cb_procfleet = None
    gc.collect()
    if cb_procfleet is not None:
        record.update(cb_procfleet)
        _emit_record(record, rec_out)

    # disaggregated prefill/decode (ISSUE 17): the colocated-vs-disagg
    # A/B on the long_prompt_flood mix, right after the proc fleet
    # whose wire + worker machinery it rides
    try:
        cb_disagg = _timed_section(
            "cb disagg", lambda: _retry_transient(
                lambda: _cb_disagg_bench(on_tpu),
                "cb disagg bench"))
    except Exception as e:
        print(f"# cb disagg bench failed: {e!r}", file=sys.stderr)
        cb_disagg = None
    gc.collect()
    if cb_disagg is not None:
        record.update(cb_disagg)
        _emit_record(record, rec_out)

    # SLO-driven autoscaler (ISSUE 19): the goodput-vs-chips frontier
    # A/B right after the fleets whose control loop it closes
    try:
        cb_autoscale = _timed_section(
            "cb autoscale", lambda: _retry_transient(
                lambda: _cb_autoscale_bench(on_tpu),
                "cb autoscale bench"))
    except Exception as e:
        print(f"# cb autoscale bench failed: {e!r}", file=sys.stderr)
        cb_autoscale = None
    gc.collect()
    if cb_autoscale is not None:
        record.update(cb_autoscale)
        _emit_record(record, rec_out)

    # shared-prefix storm (ISSUE 12): the prefix-cache cold/warm A/B
    # right after the serving sections whose capacity it multiplies
    try:
        cb_prefix = _timed_section(
            "cb prefix", lambda: _retry_transient(
                lambda: _cb_prefix_bench(on_tpu),
                "cb prefix bench"))
    except Exception as e:
        print(f"# cb prefix bench failed: {e!r}", file=sys.stderr)
        cb_prefix = None
    gc.collect()
    if cb_prefix is not None:
        record.update(cb_prefix)
        _emit_record(record, rec_out)

    # quantized serving (ISSUE 20): the equal-byte capacity A/B plus
    # the accuracy gate's numbers, right after the prefix cache whose
    # residency the quantized pools multiply
    try:
        cb_quant = _timed_section(
            "cb quant", lambda: _retry_transient(
                lambda: _cb_quant_bench(on_tpu,
                                        autotune=args.autotune),
                "cb quant bench"))
    except Exception as e:
        print(f"# cb quant bench failed: {e!r}", file=sys.stderr)
        cb_quant = None
    gc.collect()
    if cb_quant is not None:
        record.update(cb_quant)
        _emit_record(record, rec_out)

    # HTTP front door (ISSUE 15): what serving costs once a real
    # client on a real socket is in the loop, next to the raw engine
    try:
        cb_http = _timed_section(
            "cb http", lambda: _retry_transient(
                lambda: _cb_http_bench(on_tpu),
                "cb http bench"))
    except Exception as e:
        print(f"# cb http bench failed: {e!r}", file=sys.stderr)
        cb_http = None
    gc.collect()
    if cb_http is not None:
        record.update(cb_http)
        _emit_record(record, rec_out)

    try:
        decode_tok_s = _timed_section(
            "decode", lambda: _retry_transient(
                lambda: _decode_bench(on_tpu), "decode bench"))
    except Exception as e:  # decode is secondary: never sink the headline
        print(f"# decode bench failed: {e!r}", file=sys.stderr)
        decode_tok_s = None
    if decode_tok_s is not None:
        record["decode_metric"] = "llama_1B_kv_cache_greedy_decode" + suffix
        record["decode_value"] = round(decode_tok_s, 2)
        record["decode_unit"] = "tokens/s/chip"
        _emit_record(record, rec_out)
    gc.collect()

    try:
        moe_decode_tok_s = _timed_section(
            "moe decode", lambda: _retry_transient(
                lambda: _moe_decode_bench(on_tpu), "moe decode bench"))
    except Exception as e:
        print(f"# moe decode bench failed: {e!r}", file=sys.stderr)
        moe_decode_tok_s = None
    gc.collect()
    if moe_decode_tok_s is not None:
        record["moe_decode_metric"] = (
            "deepseek_v2_mla_latent_cache_greedy_decode" + suffix)
        record["moe_decode_value"] = round(moe_decode_tok_s, 2)
        record["moe_decode_unit"] = "tokens/s/chip"
        _emit_record(record, rec_out)

    # MoE step-time attribution (the tentpole evidence table): LAST,
    # after every headline metric has printed — its ~5 fresh variant
    # compiles can never starve a metric a prior round recorded; the
    # record line re-prints with the breakdown attached when it lands.
    try:
        moe_bd, moe_bd_trace = _timed_section(
            "moe breakdown", lambda: _retry_transient(
                lambda: _moe_breakdown_bench(on_tpu, dev),
                "moe breakdown bench"))
    except Exception as e:
        print(f"# moe breakdown bench failed: {e!r}", file=sys.stderr)
        moe_bd = moe_bd_trace = None
    gc.collect()
    if moe_bd is not None:
        record["moe_breakdown"] = moe_bd
        record["moe_breakdown_trace"] = moe_bd_trace
        _emit_record(record, rec_out)


if __name__ == "__main__":
    main()
