"""``paddle.summary`` (hapi summary parity, UNVERIFIED)."""

from __future__ import annotations

import numpy as np

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = p.size
        total += n
        if p.trainable:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max((len(r[0]) for r in rows), default=20) + 2
    print(f"{'Layer (param)':<{width}}{'Shape':<24}{'Param #':>12}")
    print("-" * (width + 36))
    for name, shape, n in rows:
        print(f"{name:<{width}}{str(shape):<24}{n:>12,}")
    print("-" * (width + 36))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    return {"total_params": total, "trainable_params": trainable}
