"""High-level ``paddle.Model`` API (python/paddle/hapi/model.py parity,
UNVERIFIED): prepare/fit/evaluate/predict/save/load."""

from __future__ import annotations

import numpy as np

from ..framework.core import Tensor, no_grad
from ..framework.io import save as save_obj, load as load_obj
from ..io import DataLoader

__all__ = ["Model"]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        # AMP integration (upstream: amp_configs='O1'/'O2' or a dict):
        # O1 = bf16 autocast around fwd/loss; O2 additionally keeps fp32
        # master weights via GradScaler-less bf16-native flow (TPU bf16
        # needs no loss scaling)
        self._amp_level = None
        if amp_configs is not None:
            if isinstance(amp_configs, str):
                self._amp_level = amp_configs.upper()
            else:
                self._amp_level = str(amp_configs.get("level",
                                                      "O1")).upper()
            if self._amp_level not in ("O0", "O1", "O2"):
                raise ValueError(
                    f"amp_configs level must be O0/O1/O2, got "
                    f"{self._amp_level}")
            if self._amp_level == "O0":
                self._amp_level = None
            elif self._amp_level == "O2":
                from ..amp import decorate
                out = decorate(models=self.network,
                               optimizers=self._optimizer, level="O2")
                self.network = out[0] if isinstance(out, (list, tuple)) \
                    else out

    def _compute_loss(self, outputs, labels):
        if callable(self._loss):
            return self._loss(outputs, labels)
        raise RuntimeError("prepare(loss=...) first")

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if getattr(self, "_amp_level", None):
            from ..amp import auto_cast
            with auto_cast(enable=True,
                           level=self._amp_level):
                outputs = self.network(*inputs)
                loss = self._compute_loss(outputs, labels)
        else:
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        return [float(loss.item())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad():
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
        return [float(loss.item())]

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad():
            out = self.network(*inputs)
        return out

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, resume=None, keep_last_n=None,
            legacy_save=True):
        """Train. ``save_dir`` writes a committed ``step_N``
        distributed checkpoint per epoch (``keep_last_n`` bounds its
        retention) plus — unless ``legacy_save=False`` — the upstream
        ``epoch_N.pdparams`` files. ``resume=True`` restarts from the
        newest *committed* checkpoint — ``PADDLE_RESUME_CHECKPOINT``
        if the elastic launcher exported one, else the newest valid
        ``step_N`` under ``save_dir`` — skipping any save torn by a
        crash; ``resume=<path>`` loads that checkpoint explicitly."""
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                       drop_last=drop_last, num_workers=num_workers)
        start_epoch = 0
        if resume:
            ckpt_path = resume if isinstance(resume, str) else None
            if ckpt_path is None:
                import os
                ckpt_path = os.environ.get("PADDLE_RESUME_CHECKPOINT")
            if ckpt_path is None and save_dir is not None:
                from ..distributed.checkpoint import \
                    latest_valid_checkpoint
                ckpt_path = latest_valid_checkpoint(save_dir)
            if ckpt_path:
                start_epoch = self.load_checkpoint(ckpt_path) + 1
                if verbose:
                    print(f"resuming from {ckpt_path} "
                          f"(epoch {start_epoch})")
        import time as _time
        from ..profiler import trace as _trace
        for epoch in range(start_epoch, epochs):
            losses = []
            epoch_t0 = _time.perf_counter()
            for step, batch in enumerate(loader):
                *xs, y = batch if isinstance(batch, (list, tuple)) \
                    else (batch,)
                with _trace.trace_span("hapi/train_batch", cat="train",
                                       epoch=epoch, step=step):
                    loss = self.train_batch(xs, y)
                losses.append(loss[0])
                from ..utils import monitor
                monitor.emit_step_metrics(epoch=epoch, loss=loss[0])
                if verbose and step % log_freq == 0:
                    print(f"epoch {epoch} step {step}: "
                          f"loss {loss[0]:.5f}")
            # per-epoch perf summary through the trace layer (INFO log +
            # gauges; profiler subsystem) — avg step time is the number
            # every perf regression shows up in first
            summary = _trace.epoch_summary(
                epoch, steps=len(losses),
                seconds=_time.perf_counter() - epoch_t0,
                mean_loss=round(float(np.mean(losses)), 6)
                if losses else None)
            self._last_epoch_summary = summary
            if verbose:
                print(f"epoch {epoch} done: {summary['steps']} steps in "
                      f"{summary['epoch_s']:.2f}s "
                      f"(avg {summary['avg_step_ms']:.1f} ms/step)")
            if save_dir is not None and epoch % save_freq == 0:
                if legacy_save:
                    self.save(f"{save_dir}/epoch_{epoch}")
                self.save_checkpoint(f"{save_dir}/step_{epoch}",
                                     epoch=epoch,
                                     keep_last_n=keep_last_n)
            if eval_data is not None and epoch % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size,
                              verbose=verbose)

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size)
        losses = []
        for batch in loader:
            *xs, y = batch if isinstance(batch, (list, tuple)) else (batch,)
            losses.append(self.eval_batch(xs, y)[0])
        result = {"loss": [float(np.mean(losses))]}
        if verbose:
            print(f"Eval loss: {result['loss'][0]:.5f}")
        return result

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size)
        outs = []
        for batch in loader:
            xs = batch if isinstance(batch, (list, tuple)) else (batch,)
            outs.append(self.predict_batch(list(xs)))
        return outs

    def save(self, path, training=True):
        save_obj(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save_obj(self._optimizer.state_dict(), path + ".pdopt")

    def save_checkpoint(self, path, epoch=None, keep_last_n=None):
        """Atomic (commit-protocol) checkpoint of model + optimizer +
        epoch: the directory either appears fully committed or not at
        all, so a crash mid-save can never corrupt the resume point."""
        from ..distributed import checkpoint as dckpt
        state = {"model": self.network.state_dict()}
        if self._optimizer is not None:
            state["optimizer"] = self._optimizer.state_dict()
        if epoch is not None:
            state["epoch"] = int(epoch)
        dckpt.save_state_dict(state, path, keep_last_n=keep_last_n)

    def load_checkpoint(self, path):
        """Validated load of a committed checkpoint (checksums verified;
        torn/corrupt dirs raise). Returns the epoch recorded at save
        time, or -1."""
        from ..distributed import checkpoint as dckpt
        target = {"model": self.network.state_dict()}
        dckpt.load_state_dict(target, path)
        if self._optimizer is not None:
            # read (not in-place load): optimizer slots are created
            # lazily, so a fresh process has no target tensors yet —
            # set_state_dict stashes state until the slots materialize
            flat = dckpt.read_state_dict(path, prefix="optimizer")
            opt_state = {}
            for k, v in flat.items():
                # the optimizer state dict has exactly one nested
                # level (LR_Scheduler); other keys are flat slot names
                # that may themselves contain dots
                if k.startswith("LR_Scheduler."):
                    opt_state.setdefault("LR_Scheduler", {})[
                        k[len("LR_Scheduler."):]] = v
                else:
                    opt_state[k] = v
            if opt_state:
                self._optimizer.set_state_dict(opt_state)
        vals = dckpt.load_values(path)
        return int(vals.get("epoch", -1))

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        self.network.set_state_dict(load_obj(path + ".pdparams"))
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(load_obj(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        n_params = sum(p.size for p in self.network.parameters())
        print(f"Total params: {n_params}")
        return {"total_params": n_params}
