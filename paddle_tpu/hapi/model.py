"""High-level ``paddle.Model`` API (python/paddle/hapi/model.py parity,
UNVERIFIED): prepare/fit/evaluate/predict/save/load.

Training hot path: ``fit`` runs a to_static-COMPILED train step by
default — forward, loss, backward and the optimizer update lower into
one XLA program with the persistable state (params + optimizer slots)
donated, fed by a background device-prefetch stage
(``io.DevicePrefetcher``) and a non-blocking loss window: up to
``steps_in_flight`` dispatched steps stay un-fetched, loss scalars
resolve only at ``log_freq``/epoch boundaries, so the host loop stays
dispatch-ahead of the device (the GSPMD-style host-overlap discipline;
docs/data_pipeline.md). The eager ``train_batch`` loop remains as
``fit(compiled=False)`` — the parity oracle and the fallback for
un-traceable user code (to_static itself also falls back per-signature
on genuine graph breaks, so ``compiled=True`` is always safe)."""

from __future__ import annotations

import collections
import time

import numpy as np

from ..framework.core import Tensor, no_grad
from ..framework.io import save as save_obj, load as load_obj
from ..io import DataLoader, DevicePrefetcher
from ..profiler import flight_recorder as _frec
from ..profiler import metrics as _pmetrics
from ..profiler import trace as _trace
from ..profiler.goodput import GoodputLedger
from ..tuner.surface import TunableSurface, register_surface
from ..utils import monitor

__all__ = ["Model"]

#: process-wide registry: fit-pipeline gauges + elastic/restart
#: accounting flow through it (updates mirror into the structured
#: tracer while tracing is enabled, so chrome exports keep carrying
#: them — docs/observability.md)
_REG = _pmetrics.get_registry()

_pmetrics.declare("hapi/input_wait_ms", "gauge",
                  "prefetcher starvation: ms the fit loop waited on "
                  "input this epoch")
_pmetrics.declare("hapi/steps_in_flight", "gauge",
                  "dispatched-but-unfetched compiled steps at last "
                  "dispatch")
_pmetrics.declare("hapi/h2d_bytes", "gauge",
                  "bytes device-placed by the input pipeline this "
                  "epoch")
_pmetrics.declare("hapi/avg_step_ms", "gauge",
                  "per-epoch mean train-step wall time (epoch summary)")
_pmetrics.declare("elastic/preempt_requested", "counter",
                  "preemption signals that reached the fit loop")
_pmetrics.declare("elastic/emergency_save_ms", "gauge",
                  "wall time of the bounded-time emergency checkpoint")
_pmetrics.declare("elastic/emergency_step", "gauge",
                  "epoch-relative step the emergency checkpoint "
                  "captured")
_pmetrics.declare("restart/round", "gauge",
                  "the launcher's PADDLE_RESTART_ROUND at resume")
_pmetrics.declare("restart/resume_epoch", "gauge",
                  "epoch training resumed at")
_pmetrics.declare("restart/resume_step", "gauge",
                  "first step consumed after a mid-epoch resume (0 = "
                  "epoch start)")


#: fit's pipeline knobs registered as a tunable surface (next to the
#: knob, like the serving chunk ladder): prefetch_depth = batches the
#: DevicePrefetcher places ahead of the consumer; steps_in_flight =
#: dispatched-but-unfetched compiled steps before backpressure.
#: ``bench.py --autotune`` sweeps this grid; fit consults the tuning
#: cache when both knobs are left None (arg > cache > default).
register_surface(TunableSurface(
    name="fit_pipeline",
    params=("prefetch_depth", "steps_in_flight"),
    default={"prefetch_depth": 2, "steps_in_flight": 2},
    candidates=lambda shape: [
        {"prefetch_depth": p, "steps_in_flight": s}
        for p in (1, 2, 4) for s in (1, 2, 4)],
    describe="hapi.Model.fit device-prefetch depth and in-flight "
             "compiled-step window"))


def _persist_ledger(ledger):
    """Best-effort goodput-ledger persist: an ENOSPC on the bookkeeping
    file must never mask an in-flight Preempted (the exit-75 launcher
    contract), skip fit's finally-block cleanup, or fail a training run
    that otherwise succeeded."""
    try:
        ledger.persist()
    except OSError as e:
        import warnings
        warnings.warn(f"goodput ledger persist failed ({e!r}); "
                      "continuing without on-disk goodput continuity")


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._scaler = None
        self._metrics = []
        self._compiled_train_step = None
        self._compiled_eval_step = None
        self._fit_pipeline = None
        self._resume_mid_step = None
        self._goodput = None

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, scaler=None):
        self._optimizer = optimizer
        self._loss = loss
        # optional GradScaler: train steps route the update through
        # scale/unscale/update, and its device scalars (scale +
        # good/bad counters) ride every checkpoint — an elastic resume
        # restores dynamic-loss-scaling state exactly
        self._scaler = scaler
        # the compiled steps close over optimizer/loss/amp — re-prepare
        # must rebuild them
        self._compiled_train_step = None
        self._compiled_eval_step = None
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        # AMP integration (upstream: amp_configs='O1'/'O2' or a dict):
        # O1 = bf16 autocast around fwd/loss; O2 additionally keeps fp32
        # master weights via GradScaler-less bf16-native flow (TPU bf16
        # needs no loss scaling)
        self._amp_level = None
        if amp_configs is not None:
            if isinstance(amp_configs, str):
                self._amp_level = amp_configs.upper()
            else:
                self._amp_level = str(amp_configs.get("level",
                                                      "O1")).upper()
            if self._amp_level not in ("O0", "O1", "O2"):
                raise ValueError(
                    f"amp_configs level must be O0/O1/O2, got "
                    f"{self._amp_level}")
            if self._amp_level == "O0":
                self._amp_level = None
            elif self._amp_level == "O2":
                from ..amp import decorate
                out = decorate(models=self.network,
                               optimizers=self._optimizer, level="O2")
                self.network = out[0] if isinstance(out, (list, tuple)) \
                    else out

    def _compute_loss(self, outputs, labels):
        if callable(self._loss):
            return self._loss(outputs, labels)
        raise RuntimeError("prepare(loss=...) first")

    def _fused_network_loss(self):
        """True when the compiled steps should route labels INTO the
        network and take its fused linear+cross-entropy loss
        (ops/fused_ce.py — never materializes [N, V] logits) instead of
        running the criterion over materialized logits. Requires BOTH
        the flag (fit turns it on by default for the compiled path via
        flags.scoped_default) and a criterion that certifies the
        network's labeled loss is numerics-identical
        (``fuses_with_network_loss`` — e.g. LlamaPretrainingCriterion).
        The eager ``train_batch`` loop never takes this path: it stays
        the unfused parity oracle."""
        from ..framework import flags
        return (flags.flag("FLAGS_fused_linear_cross_entropy")
                and getattr(self._loss, "fuses_with_network_loss",
                            False))

    def _backward_and_step(self, loss):
        """Backward + optimizer update, through the GradScaler when one
        was prepared (scale → backward → unscale/step/update, the
        dynamic-loss-scaling flow; its counters are traced device math,
        so the compiled fit loop keeps them live)."""
        scaler = self._scaler
        if scaler is not None and scaler.is_enable():
            scaler.scale(loss).backward()
            scaler.step(self._optimizer)
        else:
            loss.backward()
            self._optimizer.step()
        self._optimizer.clear_grad()

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if getattr(self, "_amp_level", None):
            from ..amp import auto_cast
            with auto_cast(enable=True,
                           level=self._amp_level):
                outputs = self.network(*inputs)
                loss = self._compute_loss(outputs, labels)
        else:
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
        if update:
            self._backward_and_step(loss)
        else:
            loss.backward()
        return [float(loss.item())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad():
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
        return [float(loss.item())]

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad():
            out = self.network(*inputs)
        return out

    # ---- compiled steps (the fit hot path) -------------------------------

    def _static_train_step(self, donate: bool = True):
        """The jitted train step: forward + loss + backward + optimizer
        update functionalized into ONE compiled program via the
        to_static machinery, with params and optimizer slots donated
        (``donate_state``) so XLA updates state in place instead of
        allocating a fresh copy per step. Returns the loss TENSOR — no
        host fetch; the fit loop resolves values at log boundaries.
        ``train_batch`` stays the eager parity oracle."""
        sf = getattr(self, "_compiled_train_step", None)
        if sf is not None and \
                getattr(self, "_compiled_train_donate", None) != donate:
            sf = None    # donation setting changed: rebuild
        # the fused-loss branch is decided at TRACE time; if the flag
        # state changed since this step was built (e.g. an explicit
        # set_flags OFF after a fused fit), the cached program is stale
        # — rebuild so the explicit choice actually wins
        fused_now = self._fused_network_loss()
        if sf is not None and \
                getattr(self, "_compiled_train_fused", None) != fused_now:
            sf = None
        if sf is None:
            def train_step(*args):
                *xs, y = args
                self.network.train()

                def fwd_loss():
                    if self._fused_network_loss():
                        # labeled forward: the network's fused lm_head
                        # +CE tail (returns (None|logits, loss))
                        return self.network(*xs, labels=y)[1]
                    return self._compute_loss(self.network(*xs), y)

                if getattr(self, "_amp_level", None):
                    from ..amp import auto_cast
                    with auto_cast(enable=True, level=self._amp_level):
                        loss = fwd_loss()
                else:
                    loss = fwd_loss()
                self._backward_and_step(loss)
                return loss

            from ..jit.to_static_api import StaticFunction
            sf = StaticFunction(train_step, donate_state=donate)
            self._compiled_train_step = sf
            self._compiled_train_donate = donate
            self._compiled_train_fused = fused_now
        return sf

    def _static_eval_step(self):
        sf = getattr(self, "_compiled_eval_step", None)
        # same staleness rule as the train step: the fused-loss branch
        # bakes in at trace time, so a flag-state change rebuilds
        fused_now = self._fused_network_loss()
        if sf is not None and \
                getattr(self, "_compiled_eval_fused", None) != fused_now:
            sf = None
        if sf is None:
            def eval_step(*args):
                *xs, y = args
                self.network.eval()
                with no_grad():
                    if self._fused_network_loss():
                        loss = self.network(*xs, labels=y)[1]
                    else:
                        loss = self._compute_loss(self.network(*xs), y)
                return loss

            from ..jit.to_static_api import StaticFunction
            sf = StaticFunction(eval_step)
            self._compiled_eval_step = sf
            self._compiled_eval_fused = fused_now
        return sf

    def _resolve_fit_pipeline(self, batch_size, prefetch_depth,
                              steps_in_flight) -> dict:
        """Pipeline-knob resolution, the serving-engine precedence:
        explicit fit() arg > tuning-cache entry > surface default."""
        cfg = {"prefetch_depth": prefetch_depth,
               "steps_in_flight": steps_in_flight}
        if any(v is None for v in cfg.values()):
            from ..tuner.surface import get_surface
            base = dict(get_surface("fit_pipeline").default)
            try:
                from .. import tuner
                hit = tuner.lookup("fit_pipeline",
                                   {"bs": int(batch_size or 0)},
                                   dtype="-")
            except Exception:
                hit = None
            if hit:
                base.update(hit)
            for k, v in cfg.items():
                if v is None:
                    cfg[k] = base.get(k, 2)
        cfg = {k: int(v) for k, v in cfg.items()}
        bad = {k: v for k, v in cfg.items() if v < 1}
        if bad:
            # 0 must not silently mean 1 — the fully synchronous,
            # unpipelined path is fit(compiled=False)
            raise ValueError(
                f"fit pipeline knobs must be >= 1, got {bad}; use "
                "compiled=False for the synchronous eager loop")
        self._fit_pipeline = cfg    # introspection (tests, bench)
        return cfg

    # ---- epoch loops -----------------------------------------------------

    def _fit_epoch_compiled(self, loader, step_fn, epoch, log_freq,
                            verbose, pipeline, device_sharding,
                            explicit_depth=False, guard=None,
                            skip_to=0):
        """One epoch at compiled-step speed: device-prefetched input,
        up to ``steps_in_flight`` dispatched steps un-fetched, loss
        scalars resolved only at log/epoch boundaries. ``guard`` is
        polled at each step boundary — on a preemption signal the loop
        stops dispatching, drains the in-flight loss window, and
        reports back so fit can emergency-checkpoint within the grace
        bound. ``skip_to`` fast-forwards a mid-epoch resume past the
        steps the preempted run already consumed (they are iterated but
        never dispatched). Returns (losses, prefetcher,
        host_dispatch_seconds, last_step, preempted)."""
        it = iter(loader)
        host_skipped = 0
        if isinstance(it, DevicePrefetcher):
            # the loader was built with prefetch_to_device= — use ITS
            # prefetch stage (a second wrapper would double-place every
            # batch, double-count h2d_bytes, and undo the loader's own
            # device_sharding)
            pf = it
            ignored = []
            if device_sharding is not None and \
                    pf.sharding != device_sharding:
                ignored.append("device_sharding")
            if explicit_depth and pf.depth != pipeline["prefetch_depth"]:
                ignored.append("prefetch_depth")
            if ignored:
                import warnings
                warnings.warn(
                    f"fit({'/'.join(ignored)}=...) ignored: the "
                    "DataLoader was built with prefetch_to_device= "
                    "and its own prefetch config wins — set these on "
                    "the DataLoader instead")
        else:
            # mid-epoch resume: skip consumed batches on the HOST
            # iterator, before the prefetch stage ever device-places
            # them (a restart should not pay H2D for batches it will
            # discard, nor inflate the h2d_bytes/input_wait gauges)
            for _ in range(skip_to):
                try:
                    next(it)
                except StopIteration:
                    break
            pf = DevicePrefetcher(it, depth=pipeline["prefetch_depth"],
                                  sharding=device_sharding)
            host_skipped = skip_to
        in_flight = pipeline["steps_in_flight"]
        pending: collections.deque = collections.deque()
        losses: list[float] = []
        host_s = 0.0

        def resolve_pending():
            # the ONLY host←device value fetches of the epoch
            while pending:
                _s, t = pending.popleft()
                v = float(np.asarray(t._data))
                losses.append(v)
                monitor.emit_step_metrics(epoch=epoch, loss=v)
            _REG.gauge("hapi/input_wait_ms").set(
                round(pf.input_wait_s * 1e3, 3), epoch=epoch)

        last_step = skip_to - 1
        preempted = False
        _wd_token = _frec.arm("fit compiled epoch")
        try:
            for step, batch in enumerate(pf, start=host_skipped):
                # step-boundary progress for the watchdog (owner-token
                # scoped so these beats never mask another component)
                _frec.beat(_wd_token)
                if guard is not None and guard.requested():
                    # step boundary: stop dispatching; the drain below
                    # resolves every in-flight step before the
                    # emergency checkpoint snapshots state
                    preempted = True
                    break
                if step < skip_to:
                    # mid-epoch resume behind a loader-owned prefetch
                    # stage (already device-placed): discard-iterate
                    continue
                batch = batch if isinstance(batch, (list, tuple)) \
                    else (batch,)
                t0 = time.perf_counter()
                with _trace.trace_span("hapi/train_batch", cat="train",
                                       epoch=epoch, step=step,
                                       mode="compiled"):
                    loss_t = step_fn(*batch)
                host_s += time.perf_counter() - t0
                last_step = step
                pending.append((step, loss_t))
                in_flight_now = min(len(pending), in_flight)
                _REG.gauge("hapi/steps_in_flight").set(in_flight_now)
                if len(pending) > in_flight:
                    # backpressure: block on the readiness (not the
                    # value) of the step in_flight behind the newest —
                    # at most in_flight UNFINISHED steps stay queued,
                    # however long resolution is deferred. (pending[0]
                    # would be a no-op once the first step completes.)
                    _trace.block_on(pending[-in_flight - 1][1]._data)
                if step % log_freq == 0:
                    resolve_pending()
                    if verbose:
                        print(f"epoch {epoch} step {step}: "
                              f"loss {losses[-1]:.5f}")
            resolve_pending()
        finally:
            _frec.disarm(_wd_token)
            pf.close()
        _REG.gauge("hapi/h2d_bytes").set(pf.h2d_bytes, epoch=epoch)
        return losses, pf, host_s, last_step, preempted

    def _fit_epoch_eager(self, loader, epoch, log_freq, verbose,
                         guard=None, skip_to=0):
        """The eager parity-oracle loop (per-step host sync); same
        preemption/skip contract as the compiled loop."""
        losses: list[float] = []
        last_step = skip_to - 1
        preempted = False
        for step, batch in enumerate(loader):
            if guard is not None and guard.requested():
                preempted = True
                break
            if step < skip_to:
                continue  # host batches only: no device cost to skip
            *xs, y = batch if isinstance(batch, (list, tuple)) \
                else (batch,)
            with _trace.trace_span("hapi/train_batch", cat="train",
                                   epoch=epoch, step=step):
                loss = self.train_batch(xs, y)
            last_step = step
            losses.append(loss[0])
            monitor.emit_step_metrics(epoch=epoch, loss=loss[0])
            if verbose and step % log_freq == 0:
                print(f"epoch {epoch} step {step}: loss {loss[0]:.5f}")
        return losses, last_step, preempted

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, resume=None, keep_last_n=None,
            legacy_save=True, compiled=True, donate=True,
            prefetch_depth=None, steps_in_flight=None,
            device_sharding=None, preemptible=None):
        """Train. ``save_dir`` writes a committed ``step_N``
        distributed checkpoint per epoch (``keep_last_n`` bounds its
        retention) plus — unless ``legacy_save=False`` — the upstream
        ``epoch_N.pdparams`` files. ``resume=True`` restarts from the
        newest *committed* checkpoint — ``PADDLE_RESUME_CHECKPOINT``
        if the elastic launcher exported one, else the newest valid
        ``step_N`` under ``save_dir`` — skipping any save torn by a
        crash; ``resume=<path>`` loads that checkpoint explicitly.
        Checkpoints are topology-aware: a resume may run on a
        different mesh (dp/mp resized either way) and each tensor is
        resharded on load, optimizer slots and device step/scale
        scalars included.

        **Preemption** (``preemptible``, default: on whenever
        ``save_dir`` is set): a SIGTERM observed at a step boundary
        drains the in-flight loss window, writes a bounded-time
        emergency checkpoint (``PADDLE_PREEMPT_GRACE_S`` caps the
        commit barrier) recording the mid-epoch step, and raises
        :class:`~paddle_tpu.distributed.fleet.elastic.Preempted`; the
        elastic launcher classifies the resulting EX_TEMPFAIL exit as
        a clean preemption and relaunches without burning the crash
        budget. A mid-epoch resume fast-forwards the loader past the
        consumed steps — with a deterministic batch order (seeded or
        ``shuffle=False``) the loss trajectory continues exactly.
        Pass a ``PreemptionGuard`` instance to share one across loops,
        or ``False`` to opt out.

        Hot-path knobs (module docstring, docs/data_pipeline.md):
        ``compiled=True`` runs the jitted train step (``donate``
        controls state-buffer donation); ``prefetch_depth`` /
        ``steps_in_flight`` override the pipeline depths (default:
        tuning cache, then 2/2); ``device_sharding`` (a jax Sharding,
        e.g. NamedSharding over a dp mesh axis) device-places each
        global batch sharded across the mesh."""
        import os as _os
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                       drop_last=drop_last, num_workers=num_workers)
        # goodput ledger: end-to-end wall-time partition (productive
        # compiled steps vs input-wait / saves / restarts / recompiles
        # — docs/observability.md). In-memory always; persisted next to
        # the checkpoints so restart rounds accumulate into ONE ledger
        # and a preempted run still reports honest end-to-end goodput.
        # load=resume: a deliberately fresh fit into a reused save_dir
        # must not inherit (and book days of "restart" loss against) a
        # previous run's ledger; elastic relaunches pass resume=True
        ledger = GoodputLedger(
            path=f"{save_dir}/goodput.json" if save_dir else None,
            load=bool(resume))
        self._goodput = ledger
        # register as the process's CURRENT ledger so the /statusz
        # goodput section (profiler/exposition.py, ISSUE 13) reads the
        # live run without a handle threaded through the stack
        from ..profiler import goodput as _goodput_mod
        _goodput_mod.set_current(ledger)
        start_epoch = 0
        resume_skip = 0  # steps already consumed in start_epoch
        if resume:
            ckpt_path = resume if isinstance(resume, str) else None
            if ckpt_path is None:
                ckpt_path = _os.environ.get("PADDLE_RESUME_CHECKPOINT")
            if ckpt_path is None and save_dir is not None:
                from ..distributed.checkpoint import \
                    latest_valid_checkpoint
                ckpt_path = latest_valid_checkpoint(save_dir)
            if ckpt_path:
                # resume restore (validated load + cross-mesh reshard)
                # is lost time the ledger books against "reshard"
                with ledger.measure("reshard"):
                    epoch_done = self.load_checkpoint(ckpt_path)
                mid = self._resume_mid_step
                if mid is None:
                    start_epoch = epoch_done + 1
                else:
                    # emergency checkpoint mid-epoch: redo THIS epoch
                    # from the step after the last one consumed
                    start_epoch = epoch_done
                    resume_skip = int(mid) + 1
                _REG.gauge("restart/round").set(
                    int(_os.environ.get("PADDLE_RESTART_ROUND", "0")))
                _REG.gauge("restart/resume_epoch").set(start_epoch)
                _REG.gauge("restart/resume_step").set(resume_skip)
                _frec.record_event("resume", epoch=start_epoch,
                                   step=resume_skip,
                                   checkpoint=str(ckpt_path))
                if verbose:
                    mid_msg = f" step {resume_skip}" if resume_skip \
                        else ""
                    print(f"resuming from {ckpt_path} "
                          f"(epoch {start_epoch}{mid_msg})")
        # cache keying must see the REAL batch size when the caller
        # handed us a pre-built DataLoader (batch_size stays at its
        # default of 1 in that case)
        eff_bs = batch_size
        if isinstance(train_data, DataLoader):
            sampler = getattr(loader, "batch_sampler", None)
            eff_bs = getattr(sampler, "batch_size", None) \
                or getattr(loader, "batch_size", None) or batch_size
        pipeline = self._resolve_fit_pipeline(eff_bs, prefetch_depth,
                                              steps_in_flight)
        # preemptible: False = off, a PreemptionGuard = use that one,
        # None (default) = on when save_dir is set, True = on (needs
        # save_dir for the emergency checkpoint)
        guard = None
        own_guard = False
        if preemptible is True and save_dir is None:
            raise ValueError(
                "fit(preemptible=True) needs save_dir=: an emergency "
                "checkpoint has nowhere to commit")
        if preemptible is not None and not isinstance(preemptible, bool):
            guard = preemptible
            guard.install()
        elif preemptible is not False and save_dir is not None:
            from ..distributed.fleet.elastic import PreemptionGuard
            guard = PreemptionGuard().install()
            own_guard = True
        # the compiled hot path defaults the fused linear+CE tail ON
        # (the [N, V] logits buffer is what caps per-chip batch there);
        # scoped_default only applies while the flag is untouched — an
        # explicit env/set_flags OFF (or ON) wins — and is restored on
        # exit, so eager code outside fit stays the unfused oracle.
        # Entered BEFORE the step is built: _static_train_step keys its
        # cache on the fused-loss state, which must match what the
        # trace inside the epoch loop will see; the try/finally below
        # owns the scope, so no error path can leak the default.
        import contextlib
        from ..framework import flags as _flags
        _scope = contextlib.ExitStack()
        try:
            if compiled:
                _scope.enter_context(_flags.scoped_default(
                    "FLAGS_fused_linear_cross_entropy", True))
            step_fn = self._static_train_step(donate) if compiled \
                else None
            for epoch in range(start_epoch, epochs):
                epoch_t0 = time.perf_counter()
                skip_to = resume_skip if epoch == start_epoch else 0
                extra = {}
                if compiled:
                    runs0 = (step_fn.n_compiled_runs,
                             step_fn.n_eager_runs)
                    comp_s0 = step_fn.compile_seconds
                    losses, pf, host_s, last_step, preempted = \
                        self._fit_epoch_compiled(
                            loader, step_fn, epoch, log_freq, verbose,
                            pipeline, device_sharding,
                            explicit_depth=prefetch_depth is not None,
                            guard=guard, skip_to=skip_to)
                    # host-vs-device attribution: host_dispatch_ms is
                    # the python/dispatch cost of the epoch; the rest
                    # of epoch_s is device compute + input wait. Run
                    # counters are cumulative on the StaticFunction —
                    # report the per-epoch delta.
                    extra = {"input_wait_ms":
                                 round(pf.input_wait_s * 1e3, 3),
                             "h2d_mb": round(pf.h2d_bytes / 1e6, 3),
                             "host_dispatch_ms": round(host_s * 1e3, 3),
                             "compiled_steps":
                                 step_fn.n_compiled_runs - runs0[0],
                             "eager_steps":
                                 step_fn.n_eager_runs - runs0[1]}
                    ledger.add("input_wait", pf.input_wait_s)
                    ledger.add("recompile",
                               step_fn.compile_seconds - comp_s0)
                else:
                    losses, last_step, preempted = self._fit_epoch_eager(
                        loader, epoch, log_freq, verbose,
                        guard=guard, skip_to=skip_to)
                # per-epoch perf summary through the trace layer (INFO
                # log + gauges; profiler subsystem) — avg step time is
                # the number every perf regression shows up in first
                summary = _trace.epoch_summary(
                    epoch, steps=len(losses),
                    seconds=time.perf_counter() - epoch_t0,
                    mean_loss=round(float(np.mean(losses)), 6)
                    if losses else None,
                    goodput_frac=ledger.summary()["goodput_frac"],
                    **extra)
                self._last_epoch_summary = summary
                if preempted:
                    ck = self._emergency_checkpoint(
                        save_dir, epoch, last_step, keep_last_n, guard)
                    _persist_ledger(ledger)
                    from ..distributed.fleet.elastic import Preempted
                    raise Preempted(
                        f"preempted at epoch {epoch} step {last_step}; "
                        f"emergency checkpoint committed at {ck}",
                        checkpoint=ck, epoch=epoch, step=last_step)
                if verbose:
                    print(f"epoch {epoch} done: {summary['steps']} "
                          f"steps in {summary['epoch_s']:.2f}s "
                          f"(avg {summary['avg_step_ms']:.1f} ms/step)")
                if save_dir is not None and epoch % save_freq == 0:
                    with ledger.measure("checkpoint_save"):
                        if legacy_save:
                            self.save(f"{save_dir}/epoch_{epoch}")
                        self.save_checkpoint(f"{save_dir}/step_{epoch}",
                                             epoch=epoch,
                                             keep_last_n=keep_last_n)
                    _persist_ledger(ledger)
                if eval_data is not None and epoch % eval_freq == 0:
                    self.evaluate(eval_data, batch_size=batch_size,
                                  verbose=verbose, compiled=compiled)
        finally:
            # freeze the wall clock at end-of-run: the ledger stays on
            # self._goodput, and a summary()/bench_keys() read hours
            # later must not book the idle gap as productive time
            ledger.close()
            _persist_ledger(ledger)
            _scope.close()
            if own_guard:
                guard.uninstall()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, compiled=True):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size,
                       num_workers=num_workers)
        losses = []
        if compiled:
            step_fn = self._static_eval_step()
            in_flight = (self._fit_pipeline
                         or {"steps_in_flight": 2})["steps_in_flight"]
            pending = []
            for batch in loader:
                batch = batch if isinstance(batch, (list, tuple)) \
                    else (batch,)
                pending.append(step_fn(*batch))
                if len(pending) > in_flight:
                    # same backpressure as fit: bound the device queue
                    # by the READINESS of the step in_flight back —
                    # values still resolve only once at the end
                    _trace.block_on(pending[-in_flight - 1]._data)
            losses = [float(np.asarray(t._data)) for t in pending]
        else:
            for batch in loader:
                *xs, y = batch if isinstance(batch, (list, tuple)) \
                    else (batch,)
                losses.append(self.eval_batch(xs, y)[0])
        result = {"loss": [float(np.mean(losses))]}
        if verbose:
            print(f"Eval loss: {result['loss'][0]:.5f}")
        return result

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size)
        outs = []
        for batch in loader:
            xs = batch if isinstance(batch, (list, tuple)) else (batch,)
            outs.append(self.predict_batch(list(xs)))
        return outs

    def save(self, path, training=True):
        save_obj(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save_obj(self._optimizer.state_dict(), path + ".pdopt")

    def _checkpoint_state(self, epoch=None, mid_epoch_step=None):
        """The full resumable-state dict: model + optimizer (slots AND
        the device ``@step`` scalar) + GradScaler scale/counters +
        epoch/step markers."""
        state = {"model": self.network.state_dict()}
        if self._optimizer is not None:
            state["optimizer"] = self._optimizer.state_dict()
        if self._scaler is not None:
            state["scaler"] = self._scaler.state_dict()
        if epoch is not None:
            state["epoch"] = int(epoch)
        if mid_epoch_step is not None:
            state["mid_epoch_step"] = int(mid_epoch_step)
        return state

    def save_checkpoint(self, path, epoch=None, keep_last_n=None,
                        mid_epoch_step=None, barrier_timeout=None):
        """Atomic (commit-protocol) checkpoint of model + optimizer +
        scaler + epoch: the directory either appears fully committed or
        not at all, so a crash mid-save can never corrupt the resume
        point. ``mid_epoch_step`` marks an emergency (preemption)
        checkpoint taken inside an epoch; resume redoes the epoch from
        the following step. ``barrier_timeout`` bounds the commit
        barrier (the preemption grace window)."""
        from ..distributed import checkpoint as dckpt
        dckpt.save_state_dict(
            self._checkpoint_state(epoch, mid_epoch_step), path,
            keep_last_n=keep_last_n, barrier_timeout=barrier_timeout)

    def _emergency_checkpoint(self, save_dir, epoch, step, keep_last_n,
                              guard):
        """Bounded-time preemption checkpoint at a step boundary: the
        in-flight window is already drained, so device state is exactly
        post-step ``step`` of ``epoch``. Returns the committed path
        (None when fit has no save_dir to commit into)."""
        _REG.counter("elastic/preempt_requested").inc()
        _frec.record_event("preempt_requested", epoch=epoch, step=step)
        if save_dir is None:
            return None
        t0 = time.perf_counter()
        path = f"{save_dir}/step_{epoch}"
        bound = guard.remaining() if guard is not None else None
        if bound is not None and not np.isfinite(bound):
            bound = None
        self.save_checkpoint(path, epoch=epoch, keep_last_n=keep_last_n,
                             mid_epoch_step=step, barrier_timeout=bound)
        elapsed = time.perf_counter() - t0
        ledger = getattr(self, "_goodput", None)
        if ledger is not None:
            ledger.add("emergency_save", elapsed)
        _REG.gauge("elastic/emergency_save_ms").set(
            round(elapsed * 1e3, 3))
        _REG.gauge("elastic/emergency_step").set(int(step), epoch=epoch)
        return path

    def load_checkpoint(self, path):
        """Validated load of a committed checkpoint (checksums verified;
        torn/corrupt dirs raise), resharding every tensor — params,
        optimizer slots, device step/scale scalars — onto the CURRENT
        mesh layout. Returns the epoch recorded at save time, or -1;
        an emergency checkpoint's mid-epoch step lands in
        ``self._resume_mid_step`` (None otherwise)."""
        from ..distributed import checkpoint as dckpt
        target = {"model": self.network.state_dict()}
        dckpt.load_state_dict(target, path)
        if self._optimizer is not None:
            # read (not in-place load): optimizer slots are created
            # lazily, so a fresh process has no target tensors yet —
            # set_state_dict stashes state until the slots materialize
            flat = dckpt.read_state_dict(path, prefix="optimizer")
            opt_state = {}
            for k, v in flat.items():
                # the optimizer state dict has exactly one nested
                # level (LR_Scheduler); other keys are flat slot names
                # that may themselves contain dots
                if k.startswith("LR_Scheduler."):
                    opt_state.setdefault("LR_Scheduler", {})[
                        k[len("LR_Scheduler."):]] = v
                else:
                    opt_state[k] = v
            if opt_state:
                self._optimizer.set_state_dict(opt_state)
        vals = dckpt.load_values(path)
        if self._scaler is not None and isinstance(
                vals.get("scaler"), dict):
            self._scaler.load_state_dict(vals["scaler"])
        mid = vals.get("mid_epoch_step")
        self._resume_mid_step = int(mid) if mid is not None else None
        return int(vals.get("epoch", -1))

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        self.network.set_state_dict(load_obj(path + ".pdparams"))
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(load_obj(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        n_params = sum(p.size for p in self.network.parameters())
        print(f"Total params: {n_params}")
        return {"total_params": n_params}
