"""hapi callbacks (python/paddle/hapi/callbacks.py parity, UNVERIFIED)."""

from __future__ import annotations

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            print(f"step {step}: {logs}")


class ModelCheckpoint(Callback):
    """Epoch-end checkpointing. By default saves a *committed*
    ``step_{epoch}`` distributed checkpoint (atomic commit protocol:
    model + optimizer + epoch; a crash mid-save never leaves a
    loadable-but-wrong dir) that ``Model.fit(resume=True)`` can
    auto-resume from, with ``keep_last_n`` retention. ``atomic=False``
    restores the legacy ``model.save(f"{dir}/{epoch}")`` behavior."""

    def __init__(self, save_freq=1, save_dir=None, keep_last_n=None,
                 atomic=True):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.keep_last_n = keep_last_n
        self.atomic = atomic

    def on_epoch_end(self, epoch, logs=None):
        if self.model and self.save_dir and epoch % self.save_freq == 0:
            if self.atomic and hasattr(self.model, "save_checkpoint"):
                self.model.save_checkpoint(
                    f"{self.save_dir}/step_{epoch}", epoch=epoch,
                    keep_last_n=self.keep_last_n)
            else:
                self.model.save(f"{self.save_dir}/{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.best = None
        self.wait = 0
        self.stopped = False

    def on_eval_end(self, logs=None):
        if not logs or self.monitor not in logs:
            return
        v = logs[self.monitor]
        v = v[0] if isinstance(v, (list, tuple)) else v
        if self.best is None or v < self.best:
            self.best = v
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class VisualDL(Callback):
    """Scalar logging callback (hapi VisualDL parity). The visualdl
    package is not available on this backend; scalars are written through
    ``paddle_tpu.utils.monitor.ScalarWriter`` (JSONL, TensorBoard-style
    tags) so training curves are still captured."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        from ..utils.monitor import ScalarWriter
        self._writer = ScalarWriter(log_dir)
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            try:
                self._writer.add_scalar(f"train/{k}", float(v), self._step)
            except (TypeError, ValueError):
                pass

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            try:
                self._writer.add_scalar(f"eval/{k}", float(v), self._step)
            except (TypeError, ValueError):
                pass


class ReduceLROnPlateau(Callback):
    """Drop LR when a monitored metric plateaus (hapi parity)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 verbose=1, mode="auto", min_delta=1e-4, cooldown=0,
                 min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = float(factor)
        self.patience = int(patience)
        self.min_delta = float(min_delta)
        self.cooldown = int(cooldown)
        self.min_lr = float(min_lr)
        self.mode = mode
        self._best = None
        self._bad = 0
        self._cool = 0

    def _better(self, cur):
        if self._best is None:
            return True
        if self.mode == "max":
            return cur > self._best + self.min_delta
        return cur < self._best - self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self._cool > 0:
            self._cool -= 1
        if self._better(cur):
            self._best = cur
            self._bad = 0
            return
        if self._cool > 0:
            return
        self._bad += 1
        if self._bad > self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                lr = opt.get_lr()
                new = max(lr * self.factor, self.min_lr)
                if new < lr:
                    opt.set_lr(new)
            self._bad = 0
            self._cool = self.cooldown


class WandbCallback(Callback):
    """Weights & Biases logging (hapi parity). Gated on the wandb
    package; when absent (this image has no network), the callback warns
    once and disables itself."""

    def __init__(self, project=None, **kwargs):
        super().__init__()
        try:
            import wandb
            self._wandb = wandb
            self._run = wandb.init(project=project, **kwargs)
        except Exception as e:  # noqa: BLE001 — missing pkg, no API key,
            import warnings     # no network: all degrade to a no-op
            warnings.warn(f"wandb unavailable ({type(e).__name__}: {e}); "
                          "WandbCallback is a no-op", UserWarning)
            self._wandb = None

    def on_train_batch_end(self, step, logs=None):
        if self._wandb is not None:
            self._wandb.log({f"train/{k}": v
                             for k, v in (logs or {}).items()})

    def on_eval_end(self, logs=None):
        if self._wandb is not None:
            self._wandb.log({f"eval/{k}": v
                             for k, v in (logs or {}).items()})

    def on_train_end(self, logs=None):
        if self._wandb is not None:
            self._run.finish()


__all__ += ["VisualDL", "ReduceLROnPlateau", "WandbCallback"]
