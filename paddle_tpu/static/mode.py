"""Dynamic/static mode switch (paddle.enable_static parity)."""

from __future__ import annotations

_static_mode = False


def enable_static() -> None:
    global _static_mode
    _static_mode = True


def disable_static() -> None:
    global _static_mode
    _static_mode = False


def in_dynamic_mode() -> bool:
    return not _static_mode


def in_static_mode() -> bool:
    return _static_mode
