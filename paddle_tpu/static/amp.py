"""``paddle.static.amp`` — static-graph AMP surface (upstream
python/paddle/static/amp/, UNVERIFIED; reference mount empty).

Static programs here are captured replays of dygraph code, so static
AMP IS dygraph AMP: ``decorate`` delegates to ``paddle.amp.decorate``'s
optimizer/model casting and ``fp16_guard`` scopes an ``auto_cast``
region (the role of the reference's fp16_guard program annotation)."""

from __future__ import annotations

import contextlib

from ..amp import auto_cast as _auto_cast_mod
from ..amp import decorate as _decorate

__all__ = ["decorate", "fp16_guard", "CustomOpLists", "amp_guard",
           "amp_decorate"]


class CustomOpLists:
    """White/black op lists for AMP (AutoMixedPrecisionLists parity)."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None, dtype="float16"):
        self.white_list = set(custom_white_list or [])
        self.black_list = set(custom_black_list or [])
        self.black_varnames = set(custom_black_varnames or [])
        self.dtype = dtype


def decorate(optimizer=None, amp_lists=None, init_loss_scaling=2.0 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8, use_dynamic_loss_scaling=True,
             use_amp_guard=False, use_master_grad=False,
             use_promote=False, models=None, level="O1",
             dtype="float16", **kwargs):
    """Returns the decorated optimizer (and models when given) — the
    upstream static decorate returns an OptimizerWithMixedPrecision; the
    dygraph decorate plays that role here. With no model the optimizer
    passes through: under auto_cast/GradScaler the step already runs the
    mixed-precision path (TPU bf16-first; fp16 scaling via GradScaler)."""
    if models is None:
        return optimizer
    out = _decorate(models=models, optimizers=optimizer, level=level,
                    dtype=dtype)
    return out


@contextlib.contextmanager
def fp16_guard():
    """Region whose ops run under the fp16 auto_cast policy."""
    with _auto_cast_mod(True, dtype="float16"):
        yield


amp_decorate = decorate
# the argument-taking legacy guard IS the dygraph auto_cast
from ..amp.auto_cast import amp_guard  # noqa: E402,F401
