"""Program pass manager — the role of the reference's PIR/IR pass
infrastructure (``paddle/fluid/pir/transforms``, UNVERIFIED; reference
mount empty).

TPU-native stance: XLA already runs the reference's optimization passes
(constant folding, DCE, CSE, elementwise/matmul fusion, layout
assignment) on every jitted Program, so those pass NAMES are accepted
and recorded as delegated no-ops — requesting them is never an error.
What remains genuinely useful at the Program level is *function-to-
function rewriting* of the captured builder (feed->fetch callable):
``register_pass`` installs such a rewrite under a name, and
``PassManager([...]).apply(program)`` threads the program's builder
through each pass. ``auto_mixed_precision`` ships as a real example —
it wraps the builder in ``paddle.amp.auto_cast``.
"""

from __future__ import annotations

import functools

__all__ = ["PassManager", "register_pass", "apply_build_strategy",
           "XLA_DELEGATED_PASSES"]

#: reference pass names whose work XLA performs automatically on every
#: compiled Program; accepted and recorded, nothing to do
XLA_DELEGATED_PASSES = frozenset({
    "constant_folding", "dead_code_elimination",
    "common_subexpression_elimination", "fuse_gemm_epilogue",
    "fuse_elewise_add_act", "fuse_bn_act", "fuse_bn_add_act",
    "fused_attention", "fused_feedforward", "inplace_addto_op",
    "identity_op_clean", "map_op_to_another", "matmul_scale_fuse",
})

_PASS_REGISTRY: dict = {}


def register_pass(name):
    """Register a builder rewrite: ``fn(build_fn) -> new_build_fn`` where
    build_fn maps a feed dict to the fetch dict. Mirrors
    ``paddle.incubate.passes``' role with python functions instead of IR
    pattern DSL (the jaxpr IR is rewritten by XLA; python rewrites happen
    at the builder level)."""
    def deco(fn):
        _PASS_REGISTRY[name] = fn
        return fn
    return deco


@register_pass("auto_mixed_precision")
def _amp_pass(build_fn):
    """Run the captured Program under bf16 autocast (the reference's AMP
    pass inserts cast ops; on TPU the same effect comes from autocast +
    XLA fusion)."""
    from ..amp import auto_cast

    @functools.wraps(build_fn)
    def wrapped(feed):
        with auto_cast(enable=True, dtype="bfloat16"):
            return build_fn(feed)
    return wrapped


class PassManager:
    """``paddle.incubate.pass_utils``-shaped driver: validates names,
    applies registered rewrites in order, records delegated ones."""

    def __init__(self, passes, extra_delegated=frozenset()):
        self.names = list(passes)
        allowed = XLA_DELEGATED_PASSES | frozenset(extra_delegated)
        unknown = [n for n in self.names
                   if n not in _PASS_REGISTRY and n not in allowed]
        if unknown:
            raise ValueError(
                f"unknown pass(es) {unknown}; registered: "
                f"{sorted(_PASS_REGISTRY)}, delegated: "
                f"{sorted(allowed)}")

    def apply(self, program):
        applied = getattr(program, "_applied_passes", None)
        if applied is None:
            applied = program._applied_passes = []
        for n in self.names:
            fn = _PASS_REGISTRY.get(n)
            if fn is not None:
                if program.build_fn is None:
                    raise RuntimeError(
                        f"pass {n!r} rewrites the captured builder; call "
                        "Program.capture(...) first")
                program.build_fn = fn(program.build_fn)
            applied.append(n)
        return program


def apply_build_strategy(main_program, startup_program, build_strategy,
                         pass_attrs=None):
    """``paddle.static.apply_build_strategy`` parity: map the strategy's
    enabled fusions onto the pass manager (all XLA-delegated)."""
    names = [n for n in ("fuse_elewise_add_act", "fuse_bn_act",
                         "fuse_bn_add_act", "fuse_gemm_epilogue")
             if getattr(build_strategy, n, False)]
    return PassManager(names).apply(main_program)
