"""Minimal static-graph surface: Program / Executor / data
(python/paddle/static/ parity, UNVERIFIED).

Static-graph programs are *deferred dygraph*: ops executed between
``program_guard`` boundaries are recorded as a python callable over named
feeds, then ``Executor.run`` jit-executes it against the feed dict. This
covers the common OpTest static-mode pattern (build net of placeholders →
run(feed, fetch_list)) without a separate IR — the jaxpr XLA traces IS the
IR (SURVEY.md §2.1 PIR row)."""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..framework.core import Tensor, to_jax_dtype
from ..jit.input_spec import InputSpec

__all__ = ["Program", "default_main_program", "default_startup_program",
           "program_guard", "data", "Executor", "InputSpec", "name_scope"]


class _DataPlaceholder(Tensor):
    """A named feed slot; holds zeros until fed."""

    def __init__(self, name, shape, dtype):
        shape = tuple(1 if (s is None or s < 0) else int(s) for s in shape)
        super().__init__(jnp.zeros(shape, to_jax_dtype(dtype)))
        self.name = name
        self.persistable = False
        self._is_data = True


class Program:
    def __init__(self):
        self.placeholders: dict[str, _DataPlaceholder] = {}
        self.build_fn = None  # callable feed_dict -> outputs (lazily set)
        self._recorded = []
        # static.nn layer slots: layers are identified by call order, so
        # re-running a captured builder reuses (not re-inits) parameters —
        # the static-graph "parameters live in the Program" semantics
        self._layer_slots: list = []
        self._slot_idx = 0
        self._has_run = False

    def _next_layer(self, factory):
        i = self._slot_idx
        if i < len(self._layer_slots):
            layer = self._layer_slots[i]
        else:
            layer = factory()
            self._layer_slots.append(layer)
        self._slot_idx = i + 1
        return layer

    def capture(self, fn):
        """Register a builder ``fn(feed: dict[str, Tensor]) -> dict`` that
        Executor.run replays per call under this program (static.nn layers
        inside keep their parameters across runs). Re-capturing a
        different builder resets the layer slots — slot reuse is only
        valid for the same call sequence."""
        if self.build_fn is not None and \
                getattr(self, "_captured_fn", None) is not fn:
            self._layer_slots = []
            self._has_run = False

        def build(feed):
            self._slot_idx = 0
            self._building = True
            tensors = {k: (v if isinstance(v, Tensor)
                           else Tensor(jnp.asarray(v)))
                       for k, v in feed.items()}
            try:
                with program_guard(self):
                    out = fn(tensors)
            finally:
                self._building = False
            self._has_run = True
            return out
        self.build_fn = build
        self._captured_fn = fn
        return self

    def parameters(self):
        if self.build_fn is not None and not self._has_run:
            raise RuntimeError(
                "Program.parameters() before the first Executor.run: "
                "static.nn layers are created on the first replay, so "
                "there are no parameters yet — run once, then build the "
                "optimizer")
        params = []
        for layer in self._layer_slots:
            params.extend(layer.parameters())
        return params

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    def random_seed(self, *_):
        return 0


_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    prev_m, prev_s = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = prev_m, prev_s


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def data(name, shape, dtype="float32", lod_level=0) -> _DataPlaceholder:
    ph = _DataPlaceholder(name, shape, dtype)
    _main_program.placeholders[name] = ph
    return ph


class Executor:
    """Runs feed→fetch over placeholder graphs.

    Static-mode tests express the net as eager ops over placeholders at
    build time; because our eager ops execute immediately, fetches already
    hold values consistent with zero feeds. ``run`` re-executes the net by
    rebinding placeholder data and replaying the recorded closures when the
    net was built inside ``Program.capture``; for nets built directly with
    eager ops, users should prefer dygraph or ``paddle_tpu.jit.to_static``.
    """

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        program = program or _main_program
        feed = feed or {}
        if callable(program.build_fn):
            outs = program.build_fn(feed)
        else:
            # rebind placeholders and ask caller-registered builder
            raise RuntimeError(
                "Executor.run requires Program.capture(build_fn) in "
                "paddle_tpu; use dygraph or jit.to_static for new code "
                "(static Program replay is deliberate-minimal, see "
                "SURVEY.md §7 design stance)")
        import numpy as np
        result = []
        for f in (fetch_list or []):
            v = outs[f.name if hasattr(f, "name") else f]
            result.append(np.asarray(v._data) if return_numpy else v)
        return result
