"""paddle.static.nn — legacy static-graph layer functions (upstream
``python/paddle/static/nn/``, UNVERIFIED; see SURVEY.md provenance
warning).

These are function-style layers used by static-graph user code
(``fc(x, size)`` creates parameters on first call inside the current
Program). Here they desugar to the dygraph layers: each call creates the
layer, registers it on the current Program so its parameters persist, and
applies it — traced Programs then compile exactly like dygraph code.
"""

from __future__ import annotations

from .. import nn as dynn
from ..framework.core import Tensor
from .program import default_main_program

__all__ = ["cond", "while_loop", "case", "switch_case",
           "fc", "conv2d", "conv3d", "batch_norm", "embedding",
           "layer_norm", "conv2d_transpose", "sequence_expand", "prelu",
           "group_norm", "instance_norm", "data_norm", "spectral_norm",
           "deform_conv2d", "sparse_embedding", "row_conv",
           "sequence_concat", "nce", "static_pylayer"]


def _register(layer_factory):
    """Get this call site's layer from the current Program's slot list
    (created on first execution, reused on replays — see
    Program._next_layer)."""
    return default_main_program()._next_layer(layer_factory)


def _unwrap_wn(attr):
    """Split a possible WeightNormParamAttr into (plain attr, wn dim):
    static layers consume it by wrapping their created layer with
    nn.utils.weight_norm."""
    from .extras import WeightNormParamAttr
    if isinstance(attr, WeightNormParamAttr):
        return attr._attr, (attr.dim if attr.dim is not None else 0)
    return attr, None


def _maybe_weight_norm(layer, wn_dim):
    if wn_dim is not None:
        from ..nn.utils import weight_norm
        weight_norm(layer, name="weight", dim=wn_dim)
    return layer


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= int(s)
    weight_attr, wn_dim = _unwrap_wn(weight_attr)
    layer = _register(lambda: _maybe_weight_norm(
        dynn.Linear(in_features, size, weight_attr=weight_attr,
                    bias_attr=bias_attr), wn_dim))
    from ..ops.manipulation import flatten
    out = layer(flatten(x, num_flatten_dims) if len(x.shape) >
                num_flatten_dims + 1 else x)
    if activation:
        out = getattr(dynn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    in_ch = int(input.shape[1 if data_format == "NCHW" else -1])
    param_attr, wn_dim = _unwrap_wn(param_attr)
    layer = _register(lambda: _maybe_weight_norm(
        dynn.Conv2D(in_ch, num_filters, filter_size,
                    stride=stride, padding=padding,
                    dilation=dilation, groups=groups,
                    weight_attr=param_attr,
                    bias_attr=bias_attr,
                    data_format=data_format), wn_dim))
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCDHW"):
    in_ch = int(input.shape[1 if data_format == "NCDHW" else -1])
    layer = _register(lambda: dynn.Conv3D(in_ch, num_filters, filter_size,
                                          stride=stride, padding=padding,
                                          dilation=dilation, groups=groups,
                                          weight_attr=param_attr,
                                          bias_attr=bias_attr,
                                          data_format=data_format))
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def conv2d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, groups=1, param_attr=None,
                     bias_attr=None, act=None, name=None,
                     data_format="NCHW"):
    if filter_size is None:
        if output_size is None:
            raise ValueError(
                "conv2d_transpose needs filter_size or output_size")
        # derive the kernel from the requested output extent (upstream
        # legacy rule, dilation 1): k = out - (in - 1) * stride + 2 * pad
        hw = (input.shape[2:4] if data_format == "NCHW"
              else input.shape[1:3])
        out_hw = ([output_size] * 2 if isinstance(output_size, int)
                  else list(output_size))
        st = [stride] * 2 if isinstance(stride, int) else list(stride)
        pd = [padding] * 2 if isinstance(padding, int) else list(padding)
        filter_size = [int(o) - (int(i) - 1) * s + 2 * p
                       for o, i, s, p in zip(out_hw, hw, st, pd)]
        if min(filter_size) < 1:
            raise ValueError(
                f"conv2d_transpose: derived kernel {filter_size} from "
                f"output_size {out_hw} is invalid for input {list(hw)}, "
                f"stride {st}, padding {pd}")
    in_ch = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = _register(
        lambda: dynn.Conv2DTranspose(in_ch, num_filters, filter_size,
                                     stride=stride, padding=padding,
                                     groups=groups, weight_attr=param_attr,
                                     bias_attr=bias_attr,
                                     data_format=data_format))
    out = layer(input, output_size=output_size)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, name=None):
    first_layout = data_layout in ("NCHW", "NCL", "NCDHW")
    ch = int(input.shape[1 if first_layout else -1])
    rank = len(input.shape)
    cls = {5: dynn.BatchNorm3D, 4: dynn.BatchNorm2D}.get(rank,
                                                         dynn.BatchNorm1D)
    # the BatchNorm layers use paddle layout names per rank
    fmt = {dynn.BatchNorm3D: "NCDHW" if first_layout else "NDHWC",
           dynn.BatchNorm2D: "NCHW" if first_layout else "NHWC",
           dynn.BatchNorm1D: "NCL" if first_layout else "NLC"}[cls]
    layer = _register(lambda: cls(ch, momentum=momentum, epsilon=epsilon,
                                  weight_attr=param_attr,
                                  bias_attr=bias_attr, data_format=fmt))
    # mode is per-call (slot layers are shared across replays)
    layer.eval() if is_test else layer.train()
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    layer = _register(lambda: dynn.LayerNorm(shape, epsilon=epsilon,
                                     weight_attr=param_attr,
                                     bias_attr=bias_attr))
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    layer = _register(lambda: dynn.Embedding(size[0], size[1],
                                     padding_idx=padding_idx,
                                     weight_attr=param_attr))
    return layer(input)


class _ElementPReLU(dynn.Layer):
    """prelu mode='element': one learned alpha per (non-batch) element."""

    def __init__(self, elem_shape, weight_attr=None):
        super().__init__()
        from ..nn import initializer as I
        self.alpha = self.create_parameter(
            list(elem_shape), attr=weight_attr,
            default_initializer=I.Constant(0.25))

    def forward(self, x):
        import paddle_tpu as paddle
        z = paddle.zeros_like(x)
        return paddle.maximum(x, z) + self.alpha * paddle.minimum(x, z)


def prelu(x, mode="all", param_attr=None, name=None):
    if mode == "all":
        num = 1
    elif mode == "channel":
        num = int(x.shape[1])
    elif mode == "element":
        elem_shape = [int(s) for s in x.shape[1:]]
        layer = _register(lambda: _ElementPReLU(elem_shape,
                                                weight_attr=param_attr))
        return layer(x)
    else:
        raise ValueError(f"prelu: unknown mode {mode!r}")
    layer = _register(lambda: dynn.PReLU(num_parameters=num,
                                         weight_attr=param_attr))
    return layer(x)


def sequence_expand(x, y, ref_level=-1, name=None):
    raise NotImplementedError(
        "LoD sequence ops are a parameter-server/CPU-era feature and out "
        "of TPU scope (see PARITY.md known gaps)")


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    ch = int(input.shape[1 if data_layout == "NCHW" else -1])
    layer = _register(lambda: dynn.GroupNorm(groups, ch, epsilon=epsilon,
                                             weight_attr=param_attr,
                                             bias_attr=bias_attr,
                                             data_format=data_layout))
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    rank = len(input.shape)
    cls = {5: dynn.InstanceNorm3D, 4: dynn.InstanceNorm2D}.get(
        rank, dynn.InstanceNorm1D)
    ch = int(input.shape[1])
    layer = _register(lambda: cls(ch, epsilon=epsilon,
                                  weight_attr=param_attr,
                                  bias_attr=bias_attr))
    return layer(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """CTR-style data normalization: normalize by ACCUMULATED batch
    statistics (batch_size / batch_sum / batch_square_sum), which are
    updated per train-mode call — the reference's PS-era op, TPU-side."""
    ch = int(input.shape[-1])
    layer = _register(lambda: _DataNorm(ch, epsilon,
                                        enable_scale_and_shift,
                                        param_attr))
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


class _DataNorm(dynn.Layer):
    def __init__(self, ch, epsilon, scale_shift, param_attr):
        super().__init__()
        import jax.numpy as jnp
        self.epsilon = epsilon
        self.register_buffer("batch_size", Tensor(
            jnp.full((ch,), 1e4, jnp.float32)))
        self.register_buffer("batch_sum", Tensor(
            jnp.zeros((ch,), jnp.float32)))
        self.register_buffer("batch_square_sum", Tensor(
            jnp.full((ch,), 1e4, jnp.float32)))
        self.scale_shift = scale_shift
        if scale_shift:
            from ..nn import initializer as I
            self.scale_w = self.create_parameter(
                [ch], attr=param_attr, default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter(
                [ch], is_bias=True, default_initializer=I.Constant(0.0))

    def forward(self, x):
        import paddle_tpu as paddle
        bs = self.batch_size
        mean = self.batch_sum / bs
        var = self.batch_square_sum / bs - mean * mean
        scale = paddle.rsqrt(var + self.epsilon)
        out = (x - mean) * scale
        if self.scale_shift:
            out = out * self.scale_w + self.bias
        if self.training:
            n = float(x.shape[0])
            self.batch_size._inplace_update(
                (bs + n)._data)
            self.batch_sum._inplace_update(
                (self.batch_sum + x.sum(axis=0))._data)
            self.batch_square_sum._inplace_update(
                (self.batch_square_sum + (x * x).sum(axis=0))._data)
        return out


class _BilinearTP(dynn.Layer):
    """Legacy fluid bilinear_tensor_product:
    out[b, k] = x[b]^T W_k y[b] + bias_k."""

    def __init__(self, dx, dy, size, param_attr=None, bias_attr=None):
        super().__init__()
        self.weight = self.create_parameter([size, dx, dy],
                                            attr=param_attr)
        self.bias = self.create_parameter([size], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x, y):
        from ..framework.core import apply as _apply
        import jax.numpy as _jnp

        def fn(xx, yy, ww, bb):
            return _jnp.einsum("bi,kij,bj->bk", xx, ww, yy) + bb

        return _apply(fn, x, y, self.weight, self.bias,
                      name="bilinear_tensor_product")


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """out[b, k] = x[b]^T W_k y[b] + bias_k (legacy fluid layer); the
    per-call-site parameters live in the current Program's slot list
    like every other static.nn layer."""
    dx, dy = int(x.shape[-1]), int(y.shape[-1])
    layer = _register(lambda: _BilinearTP(dx, dy, size, param_attr,
                                          bias_attr))
    out = layer(x, y)
    if act is not None:
        from ..nn import functional as _F
        out = getattr(_F, act)(out)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Returns the spectrally-normalized weight (σ-max estimated by power
    iteration; the u/v state persists on the Program slot layer)."""
    shape = [int(s) for s in weight.shape]
    layer = _register(lambda: dynn.SpectralNorm(shape, axis=dim,
                                                power_iters=power_iters,
                                                epsilon=eps))
    return layer(weight)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    from ..vision.ops import DeformConv2D
    in_ch = int(x.shape[1])
    layer = _register(lambda: DeformConv2D(
        in_ch, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, deformable_groups=deformable_groups,
        groups=groups, weight_attr=param_attr, bias_attr=bias_attr))
    return layer(x, offset, mask)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None,
                     name=None):
    """Parameter-server sparse embedding → dense Embedding on TPU (the
    distributed sparse table is PS-scope; see PARITY.md known gaps)."""
    return embedding(input, size, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """Lookahead row convolution (DeepSpeech2): out[t] = sum_{i<=fc}
    w[i] * x[t+i] over a [B, T, D] input."""
    d = int(input.shape[-1])
    layer = _register(lambda: _RowConv(d, future_context_size, param_attr))
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


class _RowConv(dynn.Layer):
    def __init__(self, d, future_context_size, param_attr):
        super().__init__()
        from ..nn import initializer as I
        self.fc = int(future_context_size)
        self.weight = self.create_parameter(
            [self.fc + 1, d], attr=param_attr,
            default_initializer=I.Constant(0.0))

    def forward(self, x):
        from ..framework.core import apply
        import jax.numpy as jnp
        fc = self.fc

        def fn(a, w):
            t = a.shape[1]
            out = jnp.zeros_like(a)
            for i in range(fc + 1):
                seg = a[:, i:t, :] * w[i]
                out = out.at[:, :t - i, :].add(seg)
            return out
        return apply(fn, x, self.weight, name="row_conv")


def sequence_concat(input, name=None):
    raise NotImplementedError(
        "LoD sequence ops are a parameter-server/CPU-era feature and out "
        "of TPU scope (see PARITY.md known gaps)")


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss with uniform negative sampling:
    -log σ(s_y) - Σ_neg log σ(-s_k) per example."""
    d = int(input.shape[-1])
    layer = _register(lambda: _NCE(d, num_total_classes, num_neg_samples,
                                   param_attr, bias_attr))
    return layer(input, label)


class _NCE(dynn.Layer):
    def __init__(self, d, num_classes, num_neg, param_attr, bias_attr):
        super().__init__()
        from ..nn import initializer as I
        self.num_classes = num_classes
        self.num_neg = num_neg
        self.weight = self.create_parameter(
            [num_classes, d], attr=param_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            [num_classes], attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))

    def forward(self, x, label):
        from ..framework.core import apply
        from ..framework import random as framework_random
        import jax
        import jax.numpy as jnp
        key = framework_random.default_generator.next_key()
        num_neg, num_classes = self.num_neg, self.num_classes

        def fn(a, lab, w, b):
            lab = lab.reshape(-1)
            pos = jnp.sum(a * w[lab], -1) + b[lab]
            neg_ids = jax.random.randint(
                key, (a.shape[0], num_neg), 0, num_classes)
            neg = jnp.einsum("bd,bkd->bk", a, w[neg_ids]) + b[neg_ids]
            loss = -jax.nn.log_sigmoid(pos) \
                - jnp.sum(jax.nn.log_sigmoid(-neg), -1)
            return loss[:, None]
        return apply(fn, x, label, self.weight, self.bias, name="nce")


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """paddle.static.nn.static_pylayer parity: run ``forward_fn`` with a
    custom backward. Desugars to autograd.PyLayer (the dygraph custom-vjp
    machinery IS the static one here — programs are captured replays)."""
    from ..autograd import PyLayer

    if backward_fn is None:
        outs = forward_fn(*inputs)
        outs_t = outs if isinstance(outs, (list, tuple)) else (outs,)
        detached = [o.detach() if hasattr(o, "detach") else o
                    for o in outs_t]
        return detached if isinstance(outs, (list, tuple)) else detached[0]

    class _StaticPyLayer(PyLayer):
        @staticmethod
        def forward(ctx, *args):
            return forward_fn(*args)

        @staticmethod
        def backward(ctx, *grads):
            return backward_fn(*grads)

    return _StaticPyLayer.apply(*inputs)


# ---- control flow (reference ``paddle.static.nn.cond/while_loop/...``:
# C++-executor ops building ProgramDesc sub-blocks; here the eager value
# drives a Python branch, and under ``to_static`` the framework's
# guarded branch specialization keeps the step compiled — SURVEY.md
# §3.5's SOT role) -----------------------------------------------------

def cond(pred, true_fn=None, false_fn=None, name=None,
         return_names=None):
    """Run ``true_fn()`` when pred (a scalar bool Tensor or python
    bool) is truthy, else ``false_fn()``."""
    from ..framework.core import Tensor
    p = bool(pred.item()) if isinstance(pred, Tensor) else bool(pred)
    if p:
        return true_fn() if true_fn is not None else None
    return false_fn() if false_fn is not None else None


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """``static.nn.while_loop`` parity: iterate ``body(*vars)`` while
    ``cond(*vars)`` holds; shapes/dtypes of loop_vars must be stable
    (the same contract the reference's while op enforces)."""
    from ..framework.core import Tensor
    vars_ = list(loop_vars)
    while True:
        c = cond(*vars_)
        if not (bool(c.item()) if isinstance(c, Tensor) else bool(c)):
            return vars_
        out = body(*vars_)
        vars_ = list(out) if isinstance(out, (list, tuple)) else [out]


def case(pred_fn_pairs, default=None, name=None):
    """First (pred, fn) pair whose pred is truthy wins; else default."""
    from ..framework.core import Tensor
    for pred, fn in pred_fn_pairs:
        p = bool(pred.item()) if isinstance(pred, Tensor) else bool(pred)
        if p:
            return fn()
    if default is not None:
        return default()
    # reference semantics: no default -> last branch's fn
    return pred_fn_pairs[-1][1]()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Dispatch on an integer index: ``branch_fns`` is a dict
    {index: fn} or list of (index, fn) pairs."""
    from ..framework.core import Tensor
    idx = int(branch_index.item()) if isinstance(branch_index, Tensor) \
        else int(branch_index)
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) \
        else branch_fns
    if idx in fns:
        return fns[idx]()
    if default is not None:
        return default()
    # reference semantics: with no default, an unmatched index
    # dispatches to the max-index branch
    return fns[max(fns)]()
