"""paddle.static.nn — legacy static-graph layer functions (upstream
``python/paddle/static/nn/``, UNVERIFIED; see SURVEY.md provenance
warning).

These are function-style layers used by static-graph user code
(``fc(x, size)`` creates parameters on first call inside the current
Program). Here they desugar to the dygraph layers: each call creates the
layer, registers it on the current Program so its parameters persist, and
applies it — traced Programs then compile exactly like dygraph code.
"""

from __future__ import annotations

from .. import nn as dynn
from ..framework.core import Tensor
from .program import default_main_program

__all__ = ["fc", "conv2d", "conv3d", "batch_norm", "embedding",
           "layer_norm", "conv2d_transpose", "sequence_expand", "prelu"]


def _register(layer_factory):
    """Get this call site's layer from the current Program's slot list
    (created on first execution, reused on replays — see
    Program._next_layer)."""
    return default_main_program()._next_layer(layer_factory)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= int(s)
    layer = _register(lambda: dynn.Linear(in_features, size,
                                  weight_attr=weight_attr,
                                  bias_attr=bias_attr))
    from ..ops.manipulation import flatten
    out = layer(flatten(x, num_flatten_dims) if len(x.shape) >
                num_flatten_dims + 1 else x)
    if activation:
        out = getattr(dynn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    in_ch = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = _register(lambda: dynn.Conv2D(in_ch, num_filters, filter_size,
                                  stride=stride, padding=padding,
                                  dilation=dilation, groups=groups,
                                  weight_attr=param_attr,
                                  bias_attr=bias_attr,
                                  data_format=data_format))
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCDHW"):
    in_ch = int(input.shape[1 if data_format == "NCDHW" else -1])
    layer = _register(lambda: dynn.Conv3D(in_ch, num_filters, filter_size,
                                          stride=stride, padding=padding,
                                          dilation=dilation, groups=groups,
                                          weight_attr=param_attr,
                                          bias_attr=bias_attr,
                                          data_format=data_format))
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def conv2d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, groups=1, param_attr=None,
                     bias_attr=None, act=None, name=None,
                     data_format="NCHW"):
    if filter_size is None:
        if output_size is None:
            raise ValueError(
                "conv2d_transpose needs filter_size or output_size")
        # derive the kernel from the requested output extent (upstream
        # legacy rule, dilation 1): k = out - (in - 1) * stride + 2 * pad
        hw = (input.shape[2:4] if data_format == "NCHW"
              else input.shape[1:3])
        out_hw = ([output_size] * 2 if isinstance(output_size, int)
                  else list(output_size))
        st = [stride] * 2 if isinstance(stride, int) else list(stride)
        pd = [padding] * 2 if isinstance(padding, int) else list(padding)
        filter_size = [int(o) - (int(i) - 1) * s + 2 * p
                       for o, i, s, p in zip(out_hw, hw, st, pd)]
        if min(filter_size) < 1:
            raise ValueError(
                f"conv2d_transpose: derived kernel {filter_size} from "
                f"output_size {out_hw} is invalid for input {list(hw)}, "
                f"stride {st}, padding {pd}")
    in_ch = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = _register(
        lambda: dynn.Conv2DTranspose(in_ch, num_filters, filter_size,
                                     stride=stride, padding=padding,
                                     groups=groups, weight_attr=param_attr,
                                     bias_attr=bias_attr,
                                     data_format=data_format))
    out = layer(input, output_size=output_size)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, name=None):
    first_layout = data_layout in ("NCHW", "NCL", "NCDHW")
    ch = int(input.shape[1 if first_layout else -1])
    rank = len(input.shape)
    cls = {5: dynn.BatchNorm3D, 4: dynn.BatchNorm2D}.get(rank,
                                                         dynn.BatchNorm1D)
    # the BatchNorm layers use paddle layout names per rank
    fmt = {dynn.BatchNorm3D: "NCDHW" if first_layout else "NDHWC",
           dynn.BatchNorm2D: "NCHW" if first_layout else "NHWC",
           dynn.BatchNorm1D: "NCL" if first_layout else "NLC"}[cls]
    layer = _register(lambda: cls(ch, momentum=momentum, epsilon=epsilon,
                                  weight_attr=param_attr,
                                  bias_attr=bias_attr, data_format=fmt))
    # mode is per-call (slot layers are shared across replays)
    layer.eval() if is_test else layer.train()
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    layer = _register(lambda: dynn.LayerNorm(shape, epsilon=epsilon,
                                     weight_attr=param_attr,
                                     bias_attr=bias_attr))
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    layer = _register(lambda: dynn.Embedding(size[0], size[1],
                                     padding_idx=padding_idx,
                                     weight_attr=param_attr))
    return layer(input)


class _ElementPReLU(dynn.Layer):
    """prelu mode='element': one learned alpha per (non-batch) element."""

    def __init__(self, elem_shape, weight_attr=None):
        super().__init__()
        from ..nn import initializer as I
        self.alpha = self.create_parameter(
            list(elem_shape), attr=weight_attr,
            default_initializer=I.Constant(0.25))

    def forward(self, x):
        import paddle_tpu as paddle
        z = paddle.zeros_like(x)
        return paddle.maximum(x, z) + self.alpha * paddle.minimum(x, z)


def prelu(x, mode="all", param_attr=None, name=None):
    if mode == "all":
        num = 1
    elif mode == "channel":
        num = int(x.shape[1])
    elif mode == "element":
        elem_shape = [int(s) for s in x.shape[1:]]
        layer = _register(lambda: _ElementPReLU(elem_shape,
                                                weight_attr=param_attr))
        return layer(x)
    else:
        raise ValueError(f"prelu: unknown mode {mode!r}")
    layer = _register(lambda: dynn.PReLU(num_parameters=num,
                                         weight_attr=param_attr))
    return layer(x)


def sequence_expand(x, y, ref_level=-1, name=None):
    raise NotImplementedError(
        "LoD sequence ops are a parameter-server/CPU-era feature and out "
        "of TPU scope (see PARITY.md known gaps)")
