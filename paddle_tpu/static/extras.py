"""``paddle.static`` long tail: scopes, places, py_func, gradients,
inference-model save/load (python/paddle/static/ parity, UNVERIFIED —
reference mount empty).

Design notes (TPU-native): a "scope" is a plain name→Tensor dict (the
C++ Scope exists to own variables across executor runs; here Tensors own
themselves), ``py_func`` lowers to ``jax.pure_callback`` so host python
runs inside compiled programs, and the inference-model pair delegates to
``paddle.jit.save/load`` (StableHLO export) with the feed/fetch wrapper
the legacy API promises."""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply
from ..framework.device import CPUPlace, CUDAPlace
from .program import Program, default_main_program

__all__ = [
    "create_global_var", "ipu_shard_guard", "accuracy", "auc","Variable", "Scope", "global_scope", "scope_guard",
           "cpu_places", "cuda_places", "device_guard", "py_func",
           "gradients", "append_backward", "normalize_program",
           "save_inference_model", "load_inference_model"]

#: static-mode variables ARE Tensors in paddle_tpu (no VarDesc layer)
Variable = Tensor


class Scope:
    """Name → variable map (the role of the C++ ``Scope``)."""

    def __init__(self):
        self._vars: dict[str, Tensor] = {}

    def var(self, name):
        if name not in self._vars:
            self._vars[name] = Tensor(jnp.zeros((), jnp.float32))
            self._vars[name].name = name
        return self._vars[name]

    def find_var(self, name):
        return self._vars.get(name)

    def erase(self, names):
        for n in names:
            self._vars.pop(n, None)


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope() -> Scope:
    return _scope_stack[-1]


@contextlib.contextmanager
def scope_guard(scope: Scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


def cpu_places(device_count=None):
    import os
    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """Device places for the accelerator — TPU chips here (the name is
    API parity; there is no CUDA)."""
    ids = device_ids if device_ids is not None else \
        range(len(jax.devices()))
    return [CUDAPlace(i) for i in ids]


@contextlib.contextmanager
def device_guard(device=None):
    """Op-placement hint. XLA owns placement on TPU; the guard exists for
    source parity and records nothing."""
    yield


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Run a host python function as an op. Eager: direct call; under a
    trace it lowers to ``jax.pure_callback`` with ``out``'s shape/dtype
    as the result contract. ``backward_func`` is accepted for parity; the
    op is non-differentiable (matching py_func's host boundary)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    templates = [jax.ShapeDtypeStruct(tuple(o.shape), o._data.dtype)
                 for o in outs]

    def fn(*arrays):
        def host(*np_arrays):
            r = func(*np_arrays)
            rs = r if isinstance(r, (list, tuple)) else [r]
            packed = tuple(np.asarray(v, dtype=t.dtype).reshape(t.shape)
                           for v, t in zip(rs, templates))
            return packed if len(templates) > 1 else packed[0]
        out_tmpl = tuple(templates) if len(templates) > 1 else templates[0]
        if any(isinstance(a, jax.core.Tracer) for a in arrays):
            return jax.pure_callback(host, out_tmpl, *arrays)
        return host(*[np.asarray(a) for a in arrays])

    result = apply(fn, *xs, n_outputs=len(templates), name="py_func",
                   differentiable=False)
    if len(templates) == 1:
        return result[0] if isinstance(result, tuple) else result
    return list(result)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """d(sum targets)/d(inputs) — static API over the eager autograd."""
    from ..autograd import grad as _grad
    tl = targets if isinstance(targets, (list, tuple)) else [targets]
    il = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    gl = None
    if target_gradients is not None:
        gl = target_gradients if isinstance(target_gradients, (list, tuple)) \
            else [target_gradients]
    return _grad(tl, il, grad_outputs=gl, allow_unused=True)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Run backward from ``loss``; returns [(param, grad)] like the
    reference (which appends grad ops to the program — here the tape IS
    the program)."""
    loss.backward()
    params = parameter_list
    if params is None:
        prog = default_main_program()
        try:
            params = prog.parameters()
        except RuntimeError:
            params = []
    return [(p, p.grad) for p in params if p.grad is not None]


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Prune/normalize for export. The captured-replay Program is already
    minimal (the jaxpr XLA traces is the pruned graph); returns it."""
    return program


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kwargs):
    """Export a captured Program as an inference model via jit.save
    (StableHLO `.pdmodel` + params). feed_vars order defines the input
    signature."""
    from ..jit import save as jit_save
    from ..jit.input_spec import InputSpec

    program = program or default_main_program()
    if not callable(program.build_fn):
        raise RuntimeError(
            "save_inference_model needs Program.capture(build_fn) "
            "(paddle_tpu static programs are captured replays)")
    feeds = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetches = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    feed_names = [f.name for f in feeds]
    fetch_names = [f.name if hasattr(f, "name") else str(f)
                   for f in fetches]

    def fn(*xs):
        outs = program.build_fn(dict(zip(feed_names, xs)))
        return tuple(outs[n] for n in fetch_names)

    spec = [InputSpec(list(f.shape), str(f._data.dtype), f.name)
            for f in feeds]
    jit_save(fn, path_prefix, input_spec=spec)
    import pickle
    with open(path_prefix + ".pdnames", "wb") as fh:
        pickle.dump({"feed": feed_names, "fetch": fetch_names}, fh)


def load_inference_model(path_prefix, executor, **kwargs):
    """Returns [program, feed_names, fetch_names]: the loaded callable
    wrapped back into a captured Program so Executor.run drives it."""
    import os
    import pickle

    from ..jit import load as jit_load

    loaded = jit_load(path_prefix)
    names = {"feed": [], "fetch": []}
    if os.path.exists(path_prefix + ".pdnames"):
        with open(path_prefix + ".pdnames", "rb") as fh:
            names = pickle.load(fh)

    prog = Program()

    def build(feed):
        xs = [feed[n] for n in names["feed"]]
        outs = loaded(*xs)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        return dict(zip(names["fetch"], outs))

    prog.build_fn = build
    return [prog, names["feed"], names["fetch"]]


def ipu_places(device_count=None):
    """API-parity stub: there are no IPUs in a TPU build."""
    return []


def npu_places(device_ids=None):
    return []


def xpu_places(device_ids=None):
    return []


class WeightNormParamAttr:
    """paddle.static.WeightNormParamAttr parity: a ParamAttr that asks for
    weight normalization along ``dim``. The dygraph surface applies WN via
    ``nn.utils.weight_norm``; static layers consume this attr by wrapping
    their created layer the same way."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        from ..nn.param_attr import ParamAttr
        self.dim = dim
        self._attr = ParamAttr(name=name, initializer=initializer,
                               learning_rate=learning_rate,
                               regularizer=regularizer, trainable=trainable,
                               do_model_average=do_model_average,
                               need_clip=need_clip)

    def __getattr__(self, item):
        return getattr(self._attr, item)


def load_program_state(model_path, var_list=None):
    """Read a ``static.save`` checkpoint into a name→ndarray dict
    (upstream load_program_state parity)."""
    import pickle

    with open(model_path + ".pdparams", "rb") as fh:
        state = pickle.load(fh)
    if var_list is not None:
        names = {getattr(v, "name", v) for v in var_list}
        state = {k: v for k, v in state.items() if k in names}
    return state


def _program_params(program):
    params = program.parameters()
    # stable unique names: layer-slot order, parameter name de-duped
    out, seen = {}, {}
    for p in params:
        name = getattr(p, "name", None) or "param"
        n = seen.get(name, 0)
        seen[name] = n + 1
        out[f"{name}.{n}" if n else name] = p
    return out


def set_program_state(program, state_dict):
    """Assign a name→ndarray dict onto a Program's parameters
    (upstream set_program_state parity)."""
    params = _program_params(program)
    for name, value in state_dict.items():
        p = params.get(name)
        if p is None:
            continue
        p._inplace_update(jnp.asarray(np.asarray(value),
                                      p._data.dtype))


def save(program, model_path, protocol=4, **configs):
    """paddle.static.save parity: parameters → ``model_path.pdparams``
    (pickle of name→ndarray, same container as paddle.save)."""
    import pickle

    state = {name: np.asarray(p.numpy())
             for name, p in _program_params(program).items()}
    with open(model_path + ".pdparams", "wb") as fh:
        pickle.dump(state, fh, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """paddle.static.load parity: restore ``static.save`` output into the
    program's parameters."""
    set_program_state(program, load_program_state(model_path,
                                                  var_list=var_list))

# ---- legacy fluid static surface -----------------------------------------

def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Legacy fluid global variable: a persistable Tensor in the global
    scope, initialized to ``value``."""
    import jax.numpy as _jnp
    from ..framework.core import to_jax_dtype
    t = Tensor(_jnp.full(tuple(int(x) for x in shape), value,
                         dtype=to_jax_dtype(dtype)))
    t.persistable = bool(persistable)
    if name:
        t.name = name
        global_scope()._vars[name] = t
    return t


def ipu_shard_guard(index=-1, stage=-1):
    """IPU-only sharding annotation in the reference; a no-op context
    for API parity (no IPU backend on TPU builds)."""
    import contextlib
    return contextlib.nullcontext()


# top-k accuracy: the dynamic metric op IS the static op's semantics
from ..metric import accuracy  # noqa: F401,E402


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, stat_pos=None, stat_neg=None):
    """Legacy static AUC op: returns (auc_out, batch_auc, states).

    The reference accumulates in persistable state variables; here the
    accumulation travels through the returned ``states`` — pass the
    previous call's states back via ``stat_pos``/``stat_neg`` and
    ``auc_out`` covers everything seen so far while ``batch_auc``
    covers only this batch.
    """
    import numpy as _np
    import jax.numpy as _jnp
    from ..metric import Auc as _Auc
    pred = _np.asarray(input.numpy() if hasattr(input, "numpy")
                       else input)
    lab = _np.asarray(label.numpy() if hasattr(label, "numpy")
                      else label)
    batch = _Auc(curve=curve, num_thresholds=num_thresholds)
    batch.update(pred, lab)
    # cumulative stats = prior states + this batch's bins (numpy adds —
    # the per-sample binning loop runs once, not twice)
    cum = _Auc(curve=curve, num_thresholds=num_thresholds)
    cum._stat_pos = batch._stat_pos.copy()
    cum._stat_neg = batch._stat_neg.copy()
    if stat_pos is not None:
        cum._stat_pos += _np.asarray(
            stat_pos.numpy() if hasattr(stat_pos, "numpy")
            else stat_pos).astype(cum._stat_pos.dtype)
    if stat_neg is not None:
        cum._stat_neg += _np.asarray(
            stat_neg.numpy() if hasattr(stat_neg, "numpy")
            else stat_neg).astype(cum._stat_neg.dtype)
    auc_out = Tensor(_jnp.asarray(float(cum.accumulate()), _jnp.float32))
    batch_auc = Tensor(_jnp.asarray(float(batch.accumulate()),
                                    _jnp.float32))
    states = [Tensor(_jnp.asarray(cum._stat_pos)),
              Tensor(_jnp.asarray(cum._stat_neg))]
    return auc_out, batch_auc, states

