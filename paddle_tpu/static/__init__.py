"""``paddle.static`` — graph-mode API (SURVEY.md §1 L6).

TPU-native: a ``Program`` is a captured jittable python callable (jaxpr
underneath) rather than a ProgramDesc; ``Executor.run`` jit-executes it. The
dygraph API is the primary surface; this module provides source-level parity
for static-graph user code."""

from .mode import enable_static, disable_static, in_dynamic_mode
from .program import (Program, default_main_program, default_startup_program,
                      program_guard, data, Executor, InputSpec, name_scope)
from .passes import (PassManager, register_pass, apply_build_strategy,
                     XLA_DELEGATED_PASSES)
from .extras import (create_global_var, ipu_shard_guard,
                     accuracy, auc,
                     Variable, Scope, global_scope, scope_guard,
                     cpu_places, cuda_places, device_guard, py_func,
                     gradients, append_backward, normalize_program,
                     save_inference_model, load_inference_model,
                     ipu_places, npu_places, xpu_places,
                     WeightNormParamAttr, load_program_state,
                     set_program_state, save, load)
from . import nn  # noqa: F401
from . import amp  # noqa: F401

__all__ = ["enable_static", "disable_static", "in_dynamic_mode", "Program",
           "create_global_var", "ipu_shard_guard", "accuracy", "auc",
           "default_main_program", "default_startup_program",
           "program_guard", "data", "Executor", "InputSpec", "name_scope",
           "nn", "PassManager", "register_pass", "apply_build_strategy",
           "XLA_DELEGATED_PASSES", "Variable", "Scope", "global_scope",
           "scope_guard", "cpu_places", "cuda_places", "device_guard",
           "py_func", "gradients", "append_backward", "normalize_program",
           "save_inference_model", "load_inference_model", "ipu_places",
           "npu_places", "xpu_places", "WeightNormParamAttr",
           "load_program_state", "set_program_state", "save", "load",
           "amp"]
