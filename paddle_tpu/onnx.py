"""paddle.onnx — model export.

Upstream (``python/paddle/onnx/export.py``, UNVERIFIED) delegates to the
external ``paddle2onnx`` package. The TPU-native serialized form is
StableHLO (portable across XLA runtimes), produced by
``paddle_tpu.jit.save`` / ``paddle_tpu.inference``; ONNX proper would need
``onnx``/``paddle2onnx`` wheels, which are not in this image. ``export``
therefore emits StableHLO next to the requested path and raises only if the
caller demands a real .onnx protobuf (``format='onnx'``).
"""

from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, format="stablehlo",
           **configs):
    if format == "onnx":
        raise RuntimeError(
            "ONNX protobuf export requires the paddle2onnx/onnx packages, "
            "which are unavailable in this environment. Use the default "
            "format='stablehlo' — a portable XLA program with the same "
            "deploy-elsewhere role.")
    from . import jit
    base = path[:-len(".stablehlo")] if path.endswith(".stablehlo") else path
    jit.save(layer, base, input_spec=input_spec)
    return base + ".pdmodel"  # StableHLO text emitted by jit.save


__all__ = ["export"]
