"""Version info (paddle.version parity)."""

full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
commit = "unknown"
cuda_version = "False"
cudnn_version = "False"
tpu = True


def show():
    print(f"paddle_tpu {full_version} (TPU-native, jax-based)")


def cuda():
    return False


def cudnn():
    return False
