"""paddle.utils.unique_name — process-wide unique name generator
(upstream ``python/paddle/utils/unique_name.py``, UNVERIFIED)."""

from __future__ import annotations

import contextlib
import threading


class _Generator:
    def __init__(self):
        self._lock = threading.Lock()
        self._ids: dict[str, int] = {}

    def __call__(self, key: str) -> str:
        with self._lock:
            i = self._ids.get(key, 0)
            self._ids[key] = i + 1
        return f"{key}_{i}"


_generator = _Generator()
_guard_stack: list[str] = []


def generate(key: str) -> str:
    prefix = "".join(_guard_stack)
    return _generator(prefix + key)


@contextlib.contextmanager
def guard(new_prefix=None):
    """Namespace subsequent generate() calls under a prefix."""
    _guard_stack.append(new_prefix or "")
    try:
        yield
    finally:
        _guard_stack.pop()


def switch(new_generator=None):
    global _generator
    old = _generator
    _generator = new_generator or _Generator()
    return old


__all__ = ["generate", "guard", "switch"]
