"""paddle.utils — misc utilities (upstream ``python/paddle/utils/``,
UNVERIFIED paths; see SURVEY.md provenance warning).

Provides: ``deprecated``, ``try_import``, ``run_check``, ``unique_name``,
``dlpack`` (zero-copy jax interop), ``flatten``/``pack_sequence_as`` pytree
helpers, a ``download`` shim (offline environment — local cache only),
and ``retry`` (bounded exponential backoff for transient I/O faults).
"""

from __future__ import annotations

import functools
import importlib
import warnings

from . import unique_name  # noqa: F401
from . import dlpack  # noqa: F401
from . import download  # noqa: F401
from . import cpp_extension  # noqa: F401
from . import monitor  # noqa: F401
from . import retry  # noqa: F401
from .retry import retry_call, retryable  # noqa: F401


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (paddle.utils.deprecated)."""
    def wrapper(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            msg = f"API '{fn.__module__}.{fn.__name__}' is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use '{update_to}' instead"
            if reason:
                msg += f". Reason: {reason}"
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return inner
    return wrapper


def try_import(module_name, err_msg=None):
    """Import a module, raising a friendly error if missing."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"Failed to import {module_name}: {e}. "
            "This environment is offline; the dependency must be "
            "pre-installed.") from e


def run_check():
    """paddle.utils.run_check — verify the install can compile and run a
    matmul on the available device, and (if >1 device) a psum over a mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    x = jnp.ones((128, 128), jnp.float32)
    y = jax.jit(lambda a: a @ a)(x)
    np.testing.assert_allclose(np.asarray(y[0, 0]), 128.0, rtol=1e-5)
    print(f"paddle_tpu is installed successfully! "
          f"{len(devs)} {devs[0].platform} device(s) available.")
    if len(devs) > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        mesh = Mesh(np.array(devs), ("x",))
        s = jax.device_put(jnp.arange(len(devs), dtype=jnp.float32),
                           NamedSharding(mesh, PartitionSpec("x")))
        total = jax.jit(jnp.sum)(s)
        np.testing.assert_allclose(np.asarray(total),
                                   sum(range(len(devs))))
        print(f"paddle_tpu works well on {len(devs)} devices (mesh check).")


def flatten(nest):
    """Flatten a nested structure into a flat list (paddle.utils.flatten)."""
    import jax
    return jax.tree_util.tree_leaves(nest)


def pack_sequence_as(structure, flat_sequence):
    """Inverse of flatten given a template structure."""
    import jax
    treedef = jax.tree_util.tree_structure(structure)
    return jax.tree_util.tree_unflatten(treedef, flat_sequence)


def to_list(value):
    if value is None:
        return value
    if isinstance(value, (list, tuple)):
        return list(value)
    return [value]


__all__ = ["deprecated", "try_import", "run_check", "unique_name", "dlpack",
           "download", "cpp_extension", "flatten", "pack_sequence_as",
           "to_list"]


def require_version(min_version, max_version=None):
    """paddle.utils.require_version — assert the installed framework
    version falls in [min_version, max_version]."""
    from .. import version as _v

    def parse(s):
        return tuple(int(x) for x in str(s).split(".")[:3] if x.isdigit())

    cur = parse(_v.full_version)
    if parse(min_version) > cur:
        raise Exception(
            f"paddle_tpu version {_v.full_version} < required "
            f"{min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"paddle_tpu version {_v.full_version} > allowed "
            f"{max_version}")
    return True


__all__ += ["require_version"]
