"""Step-metrics hook API + scalar log writer (SURVEY.md §5
metrics/logging/observability row).

The reference surfaces training metrics through VisualDL's LogWriter and
per-component hooks; TPU-native equivalent: a process-wide hook registry
that training loops (``hapi.Model.fit``, ``Optimizer.step``, user code)
emit into, plus a dependency-free JSONL scalar writer a dashboard (or the
launcher) can tail.

    from paddle_tpu.utils import monitor

    writer = monitor.ScalarWriter("runs/exp1")       # metrics.jsonl
    remove = monitor.register_step_metrics_hook(writer)
    ...
    monitor.emit_step_metrics(step=i, loss=float(loss), lr=lr)
    remove(); writer.close()
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable

__all__ = ["register_step_metrics_hook", "emit_step_metrics",
           "ScalarWriter", "global_step"]

_lock = threading.Lock()
_hooks: dict[int, Callable] = {}
_next_id = 0
_step = 0


def register_step_metrics_hook(fn: Callable) -> Callable[[], None]:
    """Register ``fn(metrics: dict)``; returns a remover callable."""
    global _next_id
    with _lock:
        hid = _next_id
        _next_id += 1
        _hooks[hid] = fn

    def remove():
        with _lock:
            _hooks.pop(hid, None)
    return remove


def global_step() -> int:
    """Steps emitted so far (auto-incremented when no explicit step)."""
    return _step


def emit_step_metrics(**metrics) -> None:
    """Fan metrics out to every registered hook. Cheap when no hooks are
    registered (the fast-path check is one dict-empty test)."""
    global _step
    if not _hooks:
        return
    if "step" not in metrics:
        with _lock:
            _step += 1
            metrics["step"] = _step
    else:
        _step = int(metrics["step"])
    metrics.setdefault("time", time.time())
    with _lock:
        hooks = list(_hooks.values())
    for fn in hooks:
        fn(metrics)


class ScalarWriter:
    """JSONL scalar sink (the LogWriter role, dependency-free): one line
    per emit, tail-able while training. Callable, so it can be passed
    straight to ``register_step_metrics_hook``."""

    def __init__(self, logdir: str, filename: str = "metrics.jsonl"):
        os.makedirs(logdir, exist_ok=True)
        self.path = os.path.join(logdir, filename)
        self._f = open(self.path, "a", buffering=1)

    def __call__(self, metrics: dict) -> None:
        self._f.write(json.dumps(
            {k: (float(v) if hasattr(v, "__float__") and
                 not isinstance(v, (str, bool)) else v)
             for k, v in metrics.items()}) + "\n")

    add_record = __call__

    def add_scalar(self, tag, value, step=None):
        rec = {"tag": tag, "value": float(value)}
        if step is not None:
            rec["step"] = int(step)
        self.__call__(rec)

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---- operator-call statistics (paddle.amp.debugging op stats role) --------

op_stats: dict = {}


def _record_op(name: str, dtype: str) -> None:
    key = (name or "op", dtype)
    op_stats[key] = op_stats.get(key, 0) + 1


def enable_op_stats() -> None:
    """Count every ``apply``-dispatched op by (name, input dtype) —
    the amp.debugging operator-stats role. One hook-pointer check per op
    when disabled."""
    from ..framework import core
    core._op_stat_hook = _record_op


def disable_op_stats() -> None:
    from ..framework import core
    core._op_stat_hook = None


def op_stats_summary(reset=True) -> dict:
    out = {f"{n}[{d}]": c for (n, d), c in sorted(op_stats.items())}
    if reset:
        op_stats.clear()
    return out
