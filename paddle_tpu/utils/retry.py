"""Bounded exponential-backoff retry for I/O paths.

Checkpoint writes, the LocalFS client, and the download cache all hit
the same failure class: transient filesystem errors (EIO on a flaky
NFS mount, ENOSPC that a retention GC or operator frees, EAGAIN /
EBUSY under contention). ``retry_call`` retries exactly that class —
a bounded number of attempts with exponential backoff capped at
``max_delay`` — and re-raises the last exception unchanged, so
callers keep their original error semantics when the fault is real.

Non-transient errors (ENOENT, EACCES, ENOTDIR, ValueError, ...) are
never retried: retrying a checkpoint write to a path that does not
exist only delays the real diagnostic.
"""

from __future__ import annotations

import errno
import functools
import random
import time

__all__ = ["retry_call", "retryable", "is_transient_oserror",
           "TRANSIENT_OS_ERRNOS"]

#: errnos worth retrying: contention / flaky-media faults that a
#: short wait can clear. ENOSPC is included deliberately — on the
#: checkpoint path a concurrent retention GC (or an operator) frees
#: space, and the alternative is losing the step's state entirely.
TRANSIENT_OS_ERRNOS = frozenset({
    errno.EIO, errno.EAGAIN, errno.EBUSY, errno.EINTR, errno.ENOSPC,
    errno.ESTALE, errno.ETIMEDOUT, errno.ECONNRESET,
})


def is_transient_oserror(exc):
    """True for OSErrors whose errno is plausibly transient."""
    return isinstance(exc, OSError) and exc.errno in TRANSIENT_OS_ERRNOS


def _default_should_retry(exc):
    return isinstance(exc, TimeoutError) or is_transient_oserror(exc)


def retry_call(fn, *args, retries=3, base_delay=0.05, max_delay=1.0,
               jitter=0.25, should_retry=None, on_retry=None,
               sleep=time.sleep, **kwargs):
    """Call ``fn(*args, **kwargs)``; on a transient failure retry up to
    ``retries`` more times with bounded exponential backoff
    (``base_delay * 2**attempt`` capped at ``max_delay``, plus up to
    ``jitter`` fraction of random spread so herds of ranks don't
    retry in lockstep). Re-raises the last exception when attempts are
    exhausted or the failure is not retryable.

    ``should_retry(exc) -> bool`` overrides the default policy
    (transient OSErrors + TimeoutError). ``on_retry(exc, attempt,
    delay)`` observes each retry (logging/metrics hooks).
    """
    should_retry = should_retry or _default_should_retry
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — policy decides, below
            if attempt >= retries or not should_retry(e):
                raise
            delay = min(max_delay, base_delay * (2 ** attempt))
            if jitter:
                delay *= 1.0 + jitter * random.random()
            if on_retry is not None:
                on_retry(e, attempt, delay)
            sleep(delay)
            attempt += 1


def retryable(**cfg):
    """Decorator form of :func:`retry_call`; ``cfg`` is its keyword
    configuration (``retries=``, ``base_delay=``, ...)."""
    def deco(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            return retry_call(fn, *args, **cfg, **kwargs)
        return inner
    return deco
