"""paddle.utils.download — weight/dataset fetch shim.

Upstream (``python/paddle/utils/download.py``, UNVERIFIED) downloads from
bj.bcebos.com with md5 checks. This environment has zero egress, so the
resolver is cache-only: it serves files already present under
``$PADDLE_TPU_HOME/weights`` (default ``~/.cache/paddle_tpu``) and raises a
clear error otherwise — the same API surface, minus the network.
"""

from __future__ import annotations

import hashlib
import os
import os.path as osp

WEIGHTS_HOME = os.environ.get(
    "PADDLE_TPU_HOME", osp.join(osp.expanduser("~"), ".cache", "paddle_tpu"))


def _md5check(path, md5sum=None):
    if md5sum is None:
        return True
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == md5sum


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True):
    root_dir = root_dir or WEIGHTS_HOME
    fname = osp.split(url)[-1]
    path = osp.join(root_dir, fname)
    if osp.exists(path) and (not check_exist or _md5check(path, md5sum)):
        return path
    raise RuntimeError(
        f"'{fname}' not found in local cache ({root_dir}) and this "
        f"environment has no network access. Place the file there manually "
        f"to use it (requested url: {url}).")


def get_weights_path_from_url(url, md5sum=None):
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)


__all__ = ["get_path_from_url", "get_weights_path_from_url", "WEIGHTS_HOME"]
