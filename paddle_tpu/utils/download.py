"""paddle.utils.download — weight/dataset fetch shim.

Upstream (``python/paddle/utils/download.py``, UNVERIFIED) downloads from
bj.bcebos.com with md5 checks. This environment has zero egress, so the
resolver is cache-only: it serves files already present under
``$PADDLE_TPU_HOME/weights`` (default ``~/.cache/paddle_tpu``) and raises a
clear error otherwise — the same API surface, minus the network.
"""

from __future__ import annotations

import hashlib
import os
import os.path as osp

from .retry import retry_call

WEIGHTS_HOME = os.environ.get(
    "PADDLE_TPU_HOME", osp.join(osp.expanduser("~"), ".cache", "paddle_tpu"))


class CorruptCacheError(RuntimeError):
    """A cached file exists but fails its md5 check — distinct from
    "not found" so the user knows to delete the corrupt copy rather
    than hunt for a missing one."""

    def __init__(self, path, expected, actual):
        self.path = path
        self.expected = expected
        self.actual = actual
        super().__init__(
            f"cached file '{path}' is corrupt: md5 mismatch (expected "
            f"{expected}, got {actual}). Delete it and place a good "
            f"copy there (this environment has no network access).")


def _md5(path):
    def _read():
        h = hashlib.md5()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()
    return retry_call(_read)


def _md5check(path, md5sum=None):
    if md5sum is None:
        return True
    return _md5(path) == md5sum


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True):
    root_dir = root_dir or WEIGHTS_HOME
    fname = osp.split(url)[-1]
    path = osp.join(root_dir, fname)
    if osp.exists(path):
        if not check_exist or md5sum is None:
            return path
        actual = _md5(path)
        if actual == md5sum:
            return path
        raise CorruptCacheError(path, md5sum, actual)
    raise RuntimeError(
        f"'{fname}' not found in local cache ({root_dir}) and this "
        f"environment has no network access. Place the file there manually "
        f"to use it (requested url: {url}).")


def get_weights_path_from_url(url, md5sum=None):
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)


__all__ = ["get_path_from_url", "get_weights_path_from_url",
           "WEIGHTS_HOME", "CorruptCacheError"]
