"""paddle.utils.cpp_extension — custom-op build helper.

Upstream (``python/paddle/utils/cpp_extension/``, UNVERIFIED) compiles C++/
CUDA custom operators against libpaddle. The TPU-native equivalent of a
"custom op" is (a) a Pallas kernel registered through ``paddle_tpu.ops``,
or (b) a C extension built with setuptools (pybind11 is not available in
this image; the native runtime under ``paddle_tpu/native`` uses the raw
CPython C API + ctypes). ``CppExtension``/``load`` here drive a plain
setuptools build for host-side native code and document the Pallas path for
device code.
"""

from __future__ import annotations

import os
import subprocess
import sysconfig
import tempfile


def CppExtension(sources, *args, **kwargs):
    from setuptools import Extension
    include_dirs = kwargs.pop("include_dirs", [])
    include_dirs.append(sysconfig.get_paths()["include"])
    return Extension(kwargs.pop("name", "custom_ext"), sources,
                     include_dirs=include_dirs, language="c++",
                     extra_compile_args=["-std=c++17", "-O3"], **kwargs)


def CUDAExtension(*args, **kwargs):
    raise RuntimeError(
        "CUDAExtension is not supported on the TPU build: device kernels "
        "are written in Pallas (see /opt/skills/guides/pallas_guide.md and "
        "paddle_tpu/ops/pallas_kernels.py). Host-side native code can use "
        "CppExtension.")


def load(name, sources, extra_cxx_cflags=None, build_directory=None,
         verbose=False, **kwargs):
    """JIT-compile a C++ source list into a shared library and dlopen it via
    ctypes. Returns the ctypes CDLL (call exported C symbols directly)."""
    import ctypes

    build_directory = build_directory or tempfile.mkdtemp(prefix="pd_ext_")
    out = os.path.join(build_directory, f"{name}.so")
    cmd = ["g++", "-shared", "-fPIC", "-O3", "-std=c++17",
           "-I", sysconfig.get_paths()["include"]]
    cmd += list(extra_cxx_cflags or [])
    cmd += list(sources) + ["-o", out]
    if verbose:
        print(" ".join(cmd))
    subprocess.check_call(cmd)
    return ctypes.CDLL(out)


def setup(**kwargs):
    from setuptools import setup as _setup
    return _setup(**kwargs)


__all__ = ["CppExtension", "CUDAExtension", "load", "setup"]
