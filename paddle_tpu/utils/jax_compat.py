"""jax API compatibility shims.

``shard_map`` moved namespaces and grew a new partial-manual spelling
across jax releases: newer jax exposes ``jax.shard_map`` at the root
with an ``axis_names=`` parameter (the axes the region binds
manually), while 0.4.x only has
``jax.experimental.shard_map.shard_map`` whose partial-manual knob is
the COMPLEMENT set ``auto=`` (the axes left automatic). Every call
site imports :func:`shard_map` from here and writes the new-style
``axis_names=``; the shim translates for old jax.

tests/test_context_parallel.py carried the namespace fallback locally
since PR 7; the library modules (distributed/zero_bubble.py,
distributed/pipeline.py, fleet context_parallel, the EP MoE layer)
hit the root-attribute AttributeError at runtime, which was 2 of the
6 pre-existing tier-1 failures (test_zero_bubble).
"""

from __future__ import annotations

import inspect

__all__ = ["shard_map"]

try:
    from jax import shard_map as _impl  # type: ignore[attr-defined]
except (ImportError, AttributeError):
    from jax.experimental.shard_map import shard_map as _impl

_HAS_AXIS_NAMES = "axis_names" in inspect.signature(_impl).parameters


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None, **kw):
    """``jax.shard_map`` with new-style ``axis_names`` on every jax.

    ``axis_names=None`` (bind every mesh axis) passes straight
    through. On old jax a partial set becomes ``auto = mesh axes -
    axis_names``."""
    if axis_names is None:
        return _impl(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **kw)
    if _HAS_AXIS_NAMES:
        return _impl(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, axis_names=set(axis_names),
                     **kw)
    auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    if auto:
        kw["auto"] = auto
    return _impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 **kw)
