"""paddle.utils.dlpack — zero-copy tensor interchange.

Upstream (``python/paddle/utils/dlpack.py``, UNVERIFIED) converts between
paddle.Tensor and DLPack capsules. Here the device runtime is jax/PJRT,
which speaks the modern DLPack *protocol* (``__dlpack__``/
``__dlpack_device__``): ``to_dlpack`` returns a protocol-conforming object
(the device array itself) that numpy/torch/cupy ``from_dlpack`` all accept,
and ``from_dlpack`` accepts either a protocol object or a legacy raw
capsule.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor


class _CapsuleHolder:
    """Adapts a legacy raw DLPack capsule to the modern protocol (host
    memory only — a raw capsule carries no device handle jax can adopt)."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, stream=None, **kwargs):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # kDLCPU


def to_dlpack(x):
    """Export a Tensor as a DLPack-protocol object (zero-copy where the
    consumer shares the device)."""
    return x.jax() if isinstance(x, Tensor) else jnp.asarray(x)


def from_dlpack(ext):
    """Import a DLPack-protocol object (or legacy capsule) as a Tensor."""
    if not hasattr(ext, "__dlpack__"):
        ext = _CapsuleHolder(ext)
    return Tensor(jax.dlpack.from_dlpack(ext))


__all__ = ["to_dlpack", "from_dlpack"]
