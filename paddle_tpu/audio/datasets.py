"""``paddle.audio.datasets`` — TESS / ESC50 (python/paddle/audio/datasets
parity, UNVERIFIED). Offline-gated like the text datasets: point at a
local extracted archive, or ``backend='generate'`` for a synthetic split
with the real item shape (waveform [T] float32, label int)."""

from __future__ import annotations

import os

import numpy as np

from ..io import Dataset
from ..utils.download import WEIGHTS_HOME

__all__ = ["TESS", "ESC50"]


def _missing(name, path):
    raise RuntimeError(
        f"{name}: dataset archive not found at {path}. This environment "
        "has no network access — place the extracted dataset there, "
        "or pass backend='generate' for a synthetic offline split.")


class _SynthAudio(Dataset):
    n_classes = 2

    def __init__(self, mode, n, sample_rate=16000, seconds=1):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        t = int(sample_rate * seconds)
        self.data = []
        for i in range(n):
            label = i % self.n_classes
            freq = 220.0 * (label + 1)
            x = np.sin(2 * np.pi * freq * np.arange(t) / sample_rate)
            x = (x + 0.05 * rng.randn(t)).astype("float32")
            self.data.append((x, np.int64(label)))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]


class TESS(_SynthAudio):
    """Toronto Emotional Speech Set (7 emotion classes)."""

    n_classes = 7

    def __init__(self, mode="train", n_shards=3, shard_id=0,
                 sample_rate=16000, archive=None, backend=None,
                 **kwargs):
        if backend == "generate":
            super().__init__(mode, 70 if mode == "train" else 21,
                             sample_rate)
            return
        path = archive or os.path.join(WEIGHTS_HOME, "TESS")
        if not os.path.isdir(path):
            _missing("TESS", path)
        from .backends import load as _load
        self.data = []
        # directories only: a stray README/.DS_Store must not consume a
        # class id
        emotions = sorted(e for e in os.listdir(path)
                          if os.path.isdir(os.path.join(path, e)))
        for li, emo in enumerate(emotions):
            d = os.path.join(path, emo)
            for fn in sorted(os.listdir(d)):
                if fn.endswith(".wav"):
                    wav, _sr = _load(os.path.join(d, fn))
                    self.data.append((np.asarray(wav.numpy())[0],
                                      np.int64(li)))


class ESC50(_SynthAudio):
    """Environmental Sound Classification (50 classes, 5 folds)."""

    n_classes = 50

    def __init__(self, mode="train", split=1, sample_rate=16000,
                 archive=None, backend=None, **kwargs):
        if backend == "generate":
            super().__init__(mode, 100 if mode == "train" else 50,
                             sample_rate)
            return
        path = archive or os.path.join(WEIGHTS_HOME, "ESC-50")
        if not os.path.isdir(path):
            _missing("ESC50", path)
        import csv
        from .backends import load as _load
        meta = os.path.join(path, "meta", "esc50.csv")
        audio_dir = os.path.join(path, "audio")
        self.data = []
        with open(meta) as f:
            for row in csv.DictReader(f):
                fold = int(row["fold"])
                is_test = fold == int(split)
                if (mode == "train") == (not is_test):
                    wav, _sr = _load(os.path.join(audio_dir,
                                                  row["filename"]))
                    self.data.append((np.asarray(wav.numpy())[0],
                                      np.int64(row["target"])))
