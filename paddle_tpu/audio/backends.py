"""``paddle.audio.backends`` — wave I/O (python/paddle/audio/backends
parity, UNVERIFIED). The reference dispatches to soundfile; this image is
offline/dependency-free, so the built-in backend handles WAV (PCM 16/32
and float32) via the stdlib ``wave`` module."""

from __future__ import annotations

import wave
from dataclasses import dataclass

import numpy as np

__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]

_BACKEND = "wave"


def list_available_backends():
    return ["wave"]


def get_current_backend():
    return _BACKEND


def set_backend(backend_name):
    if backend_name not in list_available_backends():
        raise ValueError(
            f"unknown audio backend {backend_name!r}; available: "
            f"{list_available_backends()} (soundfile is not shipped on "
            "this image)")


@dataclass
class AudioInfo:
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str


def info(filepath, format=None) -> AudioInfo:
    with wave.open(str(filepath), "rb") as w:
        return AudioInfo(sample_rate=w.getframerate(),
                         num_samples=w.getnframes(),
                         num_channels=w.getnchannels(),
                         bits_per_sample=w.getsampwidth() * 8,
                         encoding=f"PCM_{w.getsampwidth() * 8}")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True, format=None):
    """Returns (waveform [C, T] float32 paddle Tensor, sample_rate)."""
    with wave.open(str(filepath), "rb") as w:
        sr = w.getframerate()
        nch = w.getnchannels()
        width = w.getsampwidth()
        w.setpos(int(frame_offset))
        n = w.getnframes() - int(frame_offset) if num_frames in (-1, None) \
            else int(num_frames)
        raw = w.readframes(n)
    if width == 1:
        # WAV stores 8-bit PCM UNSIGNED (silence at 128)
        data = (np.frombuffer(raw, np.uint8).astype(np.int16)
                - 128).reshape(-1, nch)
    else:
        dtype = {2: np.int16, 4: np.int32}[width]
        data = np.frombuffer(raw, dtype=dtype).reshape(-1, nch)
    if normalize:
        data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    arr = data.T if channels_first else data
    from ..framework.core import Tensor
    import jax.numpy as jnp
    return Tensor(jnp.asarray(np.ascontiguousarray(arr))), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16, format=None):
    """Write a waveform Tensor/array ([C, T] by default) as PCM WAV."""
    a = np.asarray(src.numpy() if hasattr(src, "numpy") else src)
    if a.ndim == 1:
        a = a[None, :]
    if channels_first:
        a = a.T                                  # -> [T, C]
    width = int(bits_per_sample) // 8
    if a.dtype.kind == "f":
        peak = float(2 ** (8 * width - 1) - 1)
        a = np.clip(a, -1.0, 1.0) * peak
    if width == 1:
        a = (a.astype(np.int16) + 128).astype(np.uint8)  # unsigned 8-bit
    else:
        a = a.astype({2: np.int16, 4: np.int32}[width])
    with wave.open(str(filepath), "wb") as w:
        w.setnchannels(a.shape[1])
        w.setsampwidth(width)
        w.setframerate(int(sample_rate))
        w.writeframes(np.ascontiguousarray(a).tobytes())
