"""paddle.audio.functional — windows, mel scale, filterbanks, dct
(python/paddle/audio/functional/ parity, UNVERIFIED)."""

from __future__ import annotations

import math

import numpy as np

import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
           "fft_frequencies", "compute_fbank_matrix", "power_to_db",
           "create_dct"]


def get_window(window, win_length, fftbins=True, dtype="float64"):
    """'hann' | 'hamming' | 'blackman' | ('gaussian', std) | 'bohman' |
    'triang' | 'rect'/'ones' — periodic (fftbins=True) or symmetric."""
    if isinstance(window, tuple):
        name, *args = window
    else:
        name, args = window, []
    n = win_length + (1 if fftbins else 0)  # periodic = sym of n+1
    k = np.arange(n)
    if name in ("rect", "ones", "boxcar"):
        w = np.ones(n)
    elif name == "hann":
        w = 0.5 - 0.5 * np.cos(2 * np.pi * k / (n - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * k / (n - 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * k / (n - 1))
             + 0.08 * np.cos(4 * np.pi * k / (n - 1)))
    elif name == "bohman":
        x = np.abs(np.linspace(-1, 1, n))
        w = (1 - x) * np.cos(np.pi * x) + np.sin(np.pi * x) / np.pi
    elif name == "triang":
        w = 1 - np.abs((k - (n - 1) / 2) / ((n - 1) / 2))
    elif name == "gaussian":
        std = args[0] if args else 7.0
        w = np.exp(-0.5 * ((k - (n - 1) / 2) / std) ** 2)
    else:
        raise ValueError(f"unknown window '{name}'")
    if fftbins:
        w = w[:-1]
    return Tensor(jnp.asarray(w.astype(dtype)))


def hz_to_mel(freq, htk=False):
    scalar = not isinstance(freq, (np.ndarray, jnp.ndarray))
    f = np.asarray(freq, np.float64)
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        # Slaney formula (librosa/paddle default)
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep,
                       mel)
    return float(mel) if scalar else mel


def mel_to_hz(mel, htk=False):
    scalar = not isinstance(mel, (np.ndarray, jnp.ndarray))
    m = np.asarray(mel, np.float64)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = np.where(m >= min_log_mel,
                      min_log_hz * np.exp(logstep * (m - min_log_mel)),
                      hz)
    return float(hz) if scalar else hz


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                       n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr, n_fft):
    return np.linspace(0, sr / 2, 1 + n_fft // 2)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """[n_mels, 1 + n_fft//2] triangular mel filterbank."""
    f_max = f_max or sr / 2
    fft_f = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1][:, None]
    upper = ramps[2:] / fdiff[1:][:, None]
    fb = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb.astype(dtype)))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    def fn(x):
        log_spec = 10.0 * jnp.log10(jnp.maximum(x, amin))
        log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec
    if isinstance(spect, Tensor):
        from ..framework.core import apply
        return apply(fn, spect, name="power_to_db")
    return fn(jnp.asarray(spect))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """[n_mels, n_mfcc] DCT-II basis (feature @ basis -> mfcc)."""
    k = np.arange(n_mels)
    basis = np.cos(np.pi / n_mels * (k[:, None] + 0.5)
                   * np.arange(n_mfcc)[None, :])
    if norm == "ortho":
        basis[:, 0] *= 1.0 / math.sqrt(n_mels)
        basis[:, 1:] *= math.sqrt(2.0 / n_mels)
    else:
        basis *= 2.0
    return Tensor(jnp.asarray(basis.astype(dtype)))
