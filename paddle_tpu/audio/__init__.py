"""``paddle.audio`` — audio feature extraction (python/paddle/audio/
parity, UNVERIFIED): window functions, mel filterbanks, Spectrogram /
MelSpectrogram / LogMelSpectrogram / MFCC feature layers built on
``paddle.signal.stft`` (all-XLA, differentiable)."""

from . import functional
from . import features

__all__ = ["functional", "features"]
