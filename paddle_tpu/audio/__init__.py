"""``paddle.audio`` — audio feature extraction (python/paddle/audio/
parity, UNVERIFIED): window functions, mel filterbanks, Spectrogram /
MelSpectrogram / LogMelSpectrogram / MFCC feature layers built on
``paddle.signal.stft`` (all-XLA, differentiable)."""

from . import functional
from . import features
from . import backends
from . import datasets
from .backends import load, save, info

__all__ = ["functional", "features", "backends", "datasets", "load",
           "save", "info"]
