"""paddle.audio.features — Spectrogram / MelSpectrogram /
LogMelSpectrogram / MFCC layers (python/paddle/audio/features/ parity,
UNVERIFIED)."""

from __future__ import annotations

from .. import nn
from ..framework.core import Tensor
from ..ops.linalg import matmul
from ..ops.manipulation import transpose as _transpose
from ..signal import stft
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(nn.Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True,
                 pad_mode="reflect", dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = AF.get_window(window, self.win_length,
                                    dtype=dtype)

    def forward(self, x):
        spec = stft(x, self.n_fft, self.hop_length, self.win_length,
                    self.window, center=self.center,
                    pad_mode=self.pad_mode)
        mag = spec.abs()
        if self.power != 1.0:
            mag = mag.pow(self.power)
        return mag


class MelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.n_mels = n_mels
        self.fbank = AF.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype)

    def forward(self, x):
        spec = self._spectrogram(x)  # [..., freq, time]
        return matmul(self.fbank, spec)  # [..., n_mels, time]


class LogMelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                   window, power, center, pad_mode,
                                   n_mels, f_min, f_max, htk, norm,
                                   dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self._mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(nn.Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_mel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct = AF.create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        log_mel = self._log_mel(x)  # [..., n_mels, time]
        # [..., time, n_mfcc] -> [..., n_mfcc, time]
        perm = list(range(len(log_mel.shape)))
        perm[-2], perm[-1] = perm[-1], perm[-2]
        out = matmul(_transpose(log_mel, perm), self.dct)
        return _transpose(out, perm)
