"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built from scratch on jax/XLA/Pallas (NOT a port).

The public surface mirrors ``import paddle`` (SURVEY.md §1 L10): tensors +
~2000 ops, ``nn.Layer``, optimizers, DataLoader, autograd, AMP, ``jit``
trace-and-compile (the to_static role, with XLA playing CINN), and a full
distributed stack over a named TPU mesh (DP / ZeRO sharding 1-3 / TP / PP /
SP / ring+Ulysses context parallel / MoE expert parallel).
"""

from __future__ import annotations

import jax as _jax

# Paddle dtype semantics need real 64-bit types (int64 indices, optional
# float64 math). Creation APIs still default python floats to float32
# (paddle behavior) via framework.core coercion, so the compute path stays
# fp32/bf16 — x64 only stops jax from silently truncating explicit 64-bit
# requests.
_jax.config.update("jax_enable_x64", True)

import jax.numpy as _jnp

# ---- dtypes (paddle.float32 etc.) ----------------------------------------
float16 = _jnp.float16
float32 = _jnp.float32
float64 = _jnp.float64
bfloat16 = _jnp.bfloat16
int8 = _jnp.int8
int16 = _jnp.int16
int32 = _jnp.int32
int64 = _jnp.int64
uint8 = _jnp.uint8
bool = _jnp.bool_
complex64 = _jnp.complex64
complex128 = _jnp.complex128
float8_e4m3fn = _jnp.float8_e4m3fn
float8_e5m2 = _jnp.float8_e5m2

from .framework.core import (Tensor, no_grad, enable_grad, is_grad_enabled,
                             set_grad_enabled)
from .framework import random as _random
from .framework.random import seed, get_rng_state, set_rng_state
from .framework.device import (CPUPlace, TPUPlace, CUDAPlace, XPUPlace,
                               CustomPlace, set_device, get_device,
                               device_count, is_compiled_with_cuda,
                               is_compiled_with_rocm, is_compiled_with_xpu,
                               is_compiled_with_tpu)
from .framework.flags import get_flags, set_flags
from .framework.io import save, load
from .framework.default_dtype import (get_default_dtype, set_default_dtype,
                                      set_printoptions)

from .ops import *  # noqa: F401,F403  (creation/math/manip/linalg/... ops)
from .ops import creation as _creation
from .autograd import grad, backward  # noqa: F401
from .framework.core import Parameter  # noqa: F401
from .nn.param_attr import ParamAttr  # noqa: F401

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import autograd  # noqa: F401
from . import amp  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import distributed  # noqa: F401
from . import distribution  # noqa: F401
from . import metric  # noqa: F401
from . import incubate  # noqa: F401
from . import profiler  # noqa: F401
from . import tuner  # noqa: F401
from . import device  # noqa: F401
from . import vision  # noqa: F401
from . import base  # noqa: F401  (the reference's renamed fluid)
from . import sparse  # noqa: F401
from . import version  # noqa: F401
from . import models  # noqa: F401
from . import inference  # noqa: F401
from . import quantization  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import audio  # noqa: F401
# the ops wildcard above bound ``linalg`` to ops.linalg; rebind to the
# full paddle.linalg namespace module
import importlib as _importlib
linalg = _importlib.import_module(".linalg", __name__)
from . import utils  # noqa: F401
from . import regularizer  # noqa: F401
from . import hub  # noqa: F401
from . import sysconfig  # noqa: F401
from . import onnx  # noqa: F401
from .hapi import callbacks  # noqa: F401
# make `import paddle_tpu.callbacks` (module-path form) resolve too —
# upstream paddle.callbacks is a real submodule
import sys as _sys
_sys.modules[__name__ + ".callbacks"] = callbacks
from . import geometric  # noqa: F401
from . import text  # noqa: F401
from .regularizer import L1Decay, L2Decay  # noqa: F401


def disable_signal_handler():
    """Reference parity (``paddle.disable_signal_handler``): upstream
    uninstalls its C++ crash-dump signal handlers so other frameworks'
    handlers win. This runtime installs none (Python exceptions + jax
    debug callbacks play that role — SURVEY.md §2.1 enforce row), so
    there is nothing to uninstall; provided for source compatibility."""


class LazyGuard:
    """Reference parity (``paddle.LazyGuard``): upstream defers
    parameter initialization so huge models can be constructed without
    eagerly allocating host memory, then materialized after placement.
    Here parameter init already IS a lazy device computation — each
    initializer is a jax program whose array materializes on the
    accelerator (sharded, when constructed under a mesh) — so the
    guard's memory-avoidance purpose is the default behavior. A plain
    context manager for source compatibility."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Reference parity (``paddle.create_parameter``): a free-standing
    Parameter with ParamAttr/initializer resolution (the same path
    ``nn.Layer.create_parameter`` uses)."""
    from .nn.layer.layers import Layer

    class _Holder(Layer):
        pass

    p = _Holder().create_parameter(shape, attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)
    if name:
        p.name = name
    return p


def iinfo(dtype):
    """paddle.iinfo — integer dtype machine limits."""
    import numpy as _np
    return _np.iinfo(_np.dtype(str(_jnp.dtype(dtype))))


def finfo(dtype):
    """paddle.finfo — floating dtype machine limits (ml_dtypes-aware, so
    bfloat16/float8 work)."""
    return _jnp.finfo(dtype)

from .hapi.model import Model  # noqa: F401
from .nn.layer.layers import Layer  # noqa: F401  (paddle.nn.Layer shortcut)
from .jit import to_static  # noqa: F401

# paddle.disable_static/enable_static: dygraph is the default; static mode
# switches the ``paddle.static`` program-building API on.
from .static.mode import (enable_static, disable_static,  # noqa: F401
                          in_dynamic_mode)


# paddle.DataParallel — on TPU, data parallelism is mesh-sharded (GSPMD
# inserts the gradient psum); the class exists for source parity
# (isinstance checks, no_sync ctx) and marks the layer for the 'data' axis
from .distributed.parallel import DataParallel  # noqa: F401


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes, input)


__version__ = "0.1.0"
