"""Tunable-surface registry — every searchable knob declares itself.

A :class:`TunableSurface` is the contract between a knob (Pallas tile
sizes, the remat dose, the serving chunk ladder) and the trial engine:
it names the knob's parameters, its default config, the candidate grid
for a given shape signature, a validity predicate, and an optional
static cost model (FLOPs/bytes per candidate) the engine uses for
roofline-based pruning before anything is timed.

Registrations live NEXT TO the knob they tune (each kernel module
registers its own surface at import), not in a central table — the
grid and validity rules are kernel knowledge. This module is
stdlib-only so hot-path modules can import it without weight.

Shape signatures are short stable strings (``"d1024,h1408,E16"``)
produced by each surface's :meth:`TunableSurface.signature`; they are
the cache's per-shape key component (MPK's point: tuned per-shape
schedules beat static defaults).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable

__all__ = ["TunableSurface", "register_surface", "get_surface",
           "list_surfaces", "sig_from_dict"]


def sig_from_dict(shape: dict) -> str:
    """Canonical shape-signature string: ``k1v1,k2v2`` sorted by key."""
    return ",".join(f"{k}{shape[k]}" for k in sorted(shape))


@dataclass
class TunableSurface:
    """One registered tunable surface (see module docstring).

    candidates: ``fn(shape: dict) -> list[dict]`` — the search grid for
      this shape (each dict maps param name -> value). The engine
      always adds ``default`` if missing, so a search can only match
      or beat the static config.
    is_valid: ``fn(config: dict, shape: dict) -> bool`` — structural
      feasibility (alignment, divisibility, VMEM fit); invalid
      candidates are dropped before pruning.
    cost_fn: optional ``fn(config: dict, shape: dict) -> (flops,
      bytes)`` static cost of one trial under this config; feeds the
      engine's roofline lower-bound pruning (engine.py).
    """

    name: str
    params: tuple
    default: dict
    candidates: Callable[[dict], list]
    is_valid: Callable[[dict, dict], bool] = field(
        default=lambda config, shape: True)
    cost_fn: Callable[[dict, dict], tuple] | None = None
    describe: str = ""

    def signature(self, **shape) -> str:
        return sig_from_dict(shape)

    def grid(self, shape: dict) -> list:
        """Valid candidate list for ``shape``, default-first and
        deduplicated (order is otherwise preserved — the engine's
        tie-break prefers earlier candidates)."""
        cands = [dict(c) for c in self.candidates(dict(shape))]
        if self.default not in cands:
            cands.insert(0, dict(self.default))
        else:
            cands.insert(0, cands.pop(cands.index(self.default)))
        seen, out = [], []
        for c in cands:
            if c not in seen and self.is_valid(c, shape):
                seen.append(c)
                out.append(c)
        return out


_lock = threading.Lock()
_registry: dict[str, TunableSurface] = {}


def register_surface(surface: TunableSurface) -> TunableSurface:
    """Register (idempotently replacing) a surface by name."""
    with _lock:
        _registry[surface.name] = surface
    return surface


def get_surface(name: str) -> TunableSurface:
    with _lock:
        try:
            return _registry[name]
        except KeyError:
            raise KeyError(
                f"unknown tunable surface {name!r}; registered: "
                f"{sorted(_registry)}") from None


def list_surfaces() -> list[str]:
    with _lock:
        return sorted(_registry)
