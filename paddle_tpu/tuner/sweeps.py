"""Standalone trial builders for the built-in kernel surfaces.

A *builder* turns a candidate config into a zero-arg callable the
trial engine can time: ``builder(config, shape) -> fn | None``. The
builders here are self-contained (random operands at the requested
shape, fresh ``jax.jit`` per candidate so every candidate compiles its
own variant) and are shared by three consumers:

- the offline CLI (``python -m paddle_tpu.tuner``),
- ``bench.py --autotune`` (sweeps at the bench workload's shapes),
- tune-on-first-call (``incubate.autotune.set_config`` — a cache miss
  for a surface with a builder here triggers one synchronous search).

Surfaces whose trial needs a whole model + workload (``scan_remat``,
``serving_chunks``, ``spec_decode``) have NO standalone builder —
:func:`auto_builder` returns None and the CLI directs users at
``bench.py``, which owns a model (``--autotune``'s cb section sweeps
serving_chunks, its cb-spec section sweeps spec_decode). Their
registered grids/validity still gate what those vehicles may try.

Each trial times forward + backward where the surface has backward
tiles (grouped matmul's ``bd/bh`` only exist in the dw kernel), since
that is the configuration the train hot path runs.
"""

from __future__ import annotations

__all__ = ["ensure_builtin_surfaces", "auto_builder",
           "grouped_matmul_builder", "flash_attention_builder",
           "rms_norm_builder", "ragged_attention_builder",
           "rms_norm_residual_builder", "swiglu_builder",
           "fused_ce_builder", "BENCH_PRESETS"]


def ensure_builtin_surfaces():
    """Import every module that registers a built-in surface (imports
    are the registration mechanism — registrations live next to their
    knobs)."""
    from ..ops import fused_ce  # noqa: F401
    from ..ops.pallas import flash_attention  # noqa: F401
    from ..ops.pallas import grouped_matmul  # noqa: F401
    from ..ops.pallas import ragged_paged_attention  # noqa: F401
    from ..ops.pallas import rms_norm  # noqa: F401
    from ..ops.pallas import swiglu  # noqa: F401
    from ..nn import scan  # noqa: F401
    from ..inference import serving  # noqa: F401


def _trial(step, *operands):
    """Run one trial step with x64 promotion OFF for the whole
    trace+lower+execute: the kernels' internal no_x64 scope covers
    their own trace, but interpret-mode lowering under an outer jit
    happens later — outside it — and mixed i64/i32 loop bounds then
    fail to legalize. Operands carry explicit dtypes, so this changes
    nothing semantically (same argument as ops/pallas/_utils.no_x64)."""
    from ..ops.pallas._utils import no_x64
    with no_x64():
        return step(*operands)


def grouped_matmul_builder(rows=4096, dtype="bfloat16", train=True):
    """Builder for the ``grouped_matmul`` surface: ``rows`` group-
    padded assignment rows through an [E, d, h] bank (shape supplies
    d/h/E), fwd + dx + dw when ``train``."""
    import jax
    import jax.numpy as jnp

    def builder(config, shape):
        from ..ops.pallas.grouped_matmul import grouped_matmul
        d, h, E = int(shape["d"]), int(shape["h"]), int(shape["E"])
        bm = 128
        nr = max(int(rows) // bm, E)
        P = nr * bm
        dt = jnp.dtype(dtype)
        key = jax.random.PRNGKey(0)
        kx, kw = jax.random.split(key)
        x = jax.random.normal(kx, (P, d), jnp.float32).astype(dt)
        w = jax.random.normal(kw, (E, d, h), jnp.float32).astype(dt)
        # contiguous non-decreasing groups, every expert >= 1 tile
        tile_gid = jnp.minimum(
            jnp.arange(nr, dtype=jnp.int32) * E // nr, E - 1)
        bn, bd, bh = (int(config[k]) for k in ("bn", "bd", "bh"))

        if train:
            def loss(x, w):
                return grouped_matmul(x, w, tile_gid, bn=bn, bd=bd,
                                      bh=bh).astype(jnp.float32).sum()
            step = jax.jit(jax.grad(loss, argnums=(0, 1)))
        else:
            step = jax.jit(lambda x, w: grouped_matmul(
                x, w, tile_gid, bn=bn, bd=bd, bh=bh))
        return lambda: _trial(step, x, w)

    return builder


def flash_attention_builder(batch=1, heads=8, dtype="bfloat16",
                            causal=True, train=True):
    """Builder for the ``flash_attention`` surface (shape supplies
    sq/sk/d). Candidates are pinned through ``force_blocks`` — NOT
    ``set_flags``, which would mark the flags user-explicit and defeat
    the override>cache>default precedence afterwards — with a fresh
    jit per candidate so each traces under its own blocks."""
    import jax
    import jax.numpy as jnp

    def builder(config, shape):
        from ..ops.pallas.flash_attention import (flash_attention,
                                                  force_blocks)
        sq, sk, d = int(shape["sq"]), int(shape["sk"]), int(shape["d"])
        dt = jnp.dtype(dtype)
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (batch, sq, heads, d),
                              jnp.float32).astype(dt)
        k = jax.random.normal(kk, (batch, sk, heads, d),
                              jnp.float32).astype(dt)
        v = jax.random.normal(kv, (batch, sk, heads, d),
                              jnp.float32).astype(dt)
        bq, bkv = int(config["block_q"]), int(config["block_kv"])

        if train:
            def loss(q, k, v):
                return flash_attention(
                    q, k, v, causal).astype(jnp.float32).sum()
            step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        else:
            step = jax.jit(lambda q, k, v: flash_attention(q, k, v,
                                                           causal))

        def fn():
            # the force context must cover the first (tracing) call;
            # later calls hit this candidate's own jit cache
            with force_blocks(bq, bkv):
                return _trial(step, q, k, v)
        return fn

    return builder


def rms_norm_builder(rows=4096, dtype="bfloat16", train=True):
    """Builder for the ``rms_norm`` surface (shape supplies d)."""
    import jax
    import jax.numpy as jnp

    def builder(config, shape):
        from ..ops.pallas.rms_norm import force_rows_block, rms_norm
        d = int(shape["d"])
        dt = jnp.dtype(dtype)
        key = jax.random.PRNGKey(0)
        kx, kw = jax.random.split(key)
        x = jax.random.normal(kx, (int(rows), d),
                              jnp.float32).astype(dt)
        w = jax.random.normal(kw, (d,), jnp.float32).astype(dt)
        blk = int(config["block_rows"])

        if train:
            def loss(x, w):
                return rms_norm(x, w).astype(jnp.float32).sum()
            step = jax.jit(jax.grad(loss, argnums=(0, 1)))
        else:
            step = jax.jit(rms_norm)

        def fn():
            with force_rows_block(blk):
                return _trial(step, x, w)
        return fn

    return builder


def rms_norm_residual_builder(rows=4096, dtype="bfloat16", train=True):
    """Builder for the ``rms_norm_residual`` surface (shape supplies
    d): the fused residual-add + norm pair, fwd + the fused dh bwd
    when ``train`` — the configuration the decoder hot path runs."""
    import jax
    import jax.numpy as jnp

    def builder(config, shape):
        from ..ops.pallas.rms_norm import (force_residual_rows_block,
                                           rms_norm_residual)
        d = int(shape["d"])
        dt = jnp.dtype(dtype)
        key = jax.random.PRNGKey(0)
        kx, kr, kw = jax.random.split(key, 3)
        x = jax.random.normal(kx, (int(rows), d),
                              jnp.float32).astype(dt)
        r = jax.random.normal(kr, (int(rows), d),
                              jnp.float32).astype(dt)
        w = jax.random.normal(kw, (d,), jnp.float32).astype(dt)
        blk = int(config["block_rows"])

        if train:
            def loss(x, r, w):
                y, rr = rms_norm_residual(x, r, w)
                return (y.astype(jnp.float32).sum()
                        + rr.astype(jnp.float32).sum())
            step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        else:
            step = jax.jit(lambda x, r, w: rms_norm_residual(x, r, w))

        def fn():
            with force_residual_rows_block(blk):
                return _trial(step, x, r, w)
        return fn

    return builder


def swiglu_builder(rows=4096, dtype="bfloat16", train=True):
    """Builder for the ``swiglu`` surface (shape supplies the
    intermediate dim h)."""
    import jax
    import jax.numpy as jnp

    def builder(config, shape):
        from ..ops.pallas.swiglu import force_swiglu_blocks, swiglu_fused
        h = int(shape["h"])
        dt = jnp.dtype(dtype)
        key = jax.random.PRNGKey(0)
        kg, ku = jax.random.split(key)
        g = jax.random.normal(kg, (int(rows), h),
                              jnp.float32).astype(dt)
        u = jax.random.normal(ku, (int(rows), h),
                              jnp.float32).astype(dt)
        br = int(config["block_rows"])
        bc = int(config["block_cols"])

        if train:
            def loss(g, u):
                return swiglu_fused(g, u).astype(jnp.float32).sum()
            step = jax.jit(jax.grad(loss, argnums=(0, 1)))
        else:
            step = jax.jit(swiglu_fused)

        def fn():
            with force_swiglu_blocks(br, bc):
                return _trial(step, g, u)
        return fn

    return builder


def fused_ce_builder(rows=4096, dtype="bfloat16", train=True):
    """Builder for the ``fused_ce`` surface (shape supplies d/v): the
    chunked lm_head+CE tail at the train geometry. Candidates pin the
    chunk width through ``force_chunk_v`` (NOT set_flags — that would
    mark FLAGS_fused_ce_chunk_v user-explicit and defeat the
    override > cache > default precedence), fresh jit per candidate."""
    import jax
    import jax.numpy as jnp

    def builder(config, shape):
        from ..ops.fused_ce import (force_chunk_v,
                                    fused_linear_cross_entropy)
        d, v = int(shape["d"]), int(shape["v"])
        n = int(rows)
        dt = jnp.dtype(dtype)
        key = jax.random.PRNGKey(0)
        kh, kw, kl = jax.random.split(key, 3)
        h = jax.random.normal(kh, (n, d), jnp.float32).astype(dt)
        w = (jax.random.normal(kw, (d, v), jnp.float32) * 0.02).astype(dt)
        labels = jax.random.randint(kl, (n,), 0, v, jnp.int32)
        cv = int(config["chunk_v"])

        if train:
            step = jax.jit(jax.grad(
                lambda hh, ww: fused_linear_cross_entropy(hh, ww,
                                                          labels),
                argnums=(0, 1)))
        else:
            step = jax.jit(lambda hh, ww: fused_linear_cross_entropy(
                hh, ww, labels))

        def fn():
            # the force context must cover the first (tracing) call;
            # later calls hit this candidate's own jit cache
            with force_chunk_v(cv):
                return _trial(step, h, w)
        return fn

    return builder


def ragged_attention_builder(slots=8, heads=8, kv_heads=2,
                             dtype="bfloat16"):
    """Builder for the ``ragged_paged_attention`` surface (shape
    supplies c/pages/page/d): a mixed prefill+decode batch — half the
    slots stream a full chunk, half ride one decode token over a deep
    history — through the unified serving kernel. Candidates pin
    through ``force_ragged_blocks`` (NOT set_flags, which would defeat
    the override>cache>default precedence), fresh jit per candidate."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    def builder(config, shape):
        from ..ops.pallas.ragged_paged_attention import (
            force_ragged_blocks, ragged_paged_attention)
        c = int(shape["c"])
        pages = int(shape["pages"])
        page = int(shape["page"])
        d = int(shape["d"])
        # a "kvq" shape component selects the QUANTIZED kernel variant
        # (int8 data pools + f32 page-parallel scales) — the same
        # component _resolve_blocks keys the cache on, so quantized
        # winners land under a distinct sig from bf16 winners
        quant = bool(shape.get("kvq"))
        dt = jnp.dtype(dtype)
        total = slots * pages + 1      # + the trash page 0
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(
            kq, (slots, c, heads, d), jnp.float32).astype(dt)
        kp = jax.random.normal(
            kk, (kv_heads, total, page, d), jnp.float32).astype(dt)
        vp = jax.random.normal(
            kv, (kv_heads, total, page, d), jnp.float32).astype(dt)
        ks = vs = None
        if quant:
            from ..ops.paged_attention import quantize_kv
            (kp, ks), (vp, vs) = (quantize_kv(kp, jnp.int8),
                                  quantize_kv(vp, jnp.int8))
        rng = np.random.RandomState(0)
        tables = jnp.asarray(
            (rng.permutation(total - 1)[:slots * pages] + 1)
            .reshape(slots, pages).astype(np.int32))
        # mixed workload: even slots prefill the whole chunk from a
        # shallow ctx, odd slots decode one token over a deep history
        ctx = jnp.asarray([(3 if s % 2 == 0 else pages * page - c - 1)
                           for s in range(slots)], jnp.int32)
        lens = jnp.asarray([(c if s % 2 == 0 else 1)
                            for s in range(slots)], jnp.int32)
        qb = int(config["q_block"])
        g = int(config["kv_pages_per_block"])
        if quant:
            def step_fn(qq, kpp, vpp, tb, cx, ln, kss, vss):
                return ragged_paged_attention(
                    qq, kpp, vpp, tb, cx, ln,
                    k_scales=kss, v_scales=vss)
            step = jax.jit(step_fn)
            operands = (q, kp, vp, tables, ctx, lens, ks, vs)
        else:
            step = jax.jit(ragged_paged_attention)
            operands = (q, kp, vp, tables, ctx, lens)

        def fn():
            # the force context must cover the first (tracing) call —
            # it short-circuits _resolve_blocks, so the candidate is
            # pinned through the SAME resolution path production uses
            with force_ragged_blocks(qb, g):
                return _trial(step, *operands)
        return fn

    return builder


#: surface -> builder factory taking (dtype) — the tune-on-first-call
#: path and the CLI's default trial hyper-parameters
_AUTO_BUILDERS = {
    "grouped_matmul": lambda dtype: grouped_matmul_builder(dtype=dtype),
    "flash_attention": lambda dtype: flash_attention_builder(dtype=dtype),
    "rms_norm": lambda dtype: rms_norm_builder(dtype=dtype),
    "rms_norm_residual":
        lambda dtype: rms_norm_residual_builder(dtype=dtype),
    "swiglu": lambda dtype: swiglu_builder(dtype=dtype),
    "fused_ce": lambda dtype: fused_ce_builder(dtype=dtype),
    "ragged_paged_attention":
        lambda dtype: ragged_attention_builder(dtype=dtype),
}


def auto_builder(surface_name, dtype="bfloat16"):
    """Standalone builder for ``surface_name``, or None when the
    surface needs a model-level vehicle (scan_remat, serving_chunks)."""
    factory = _AUTO_BUILDERS.get(surface_name)
    return factory(dtype) if factory else None


#: named shape presets for the CLI: the sweep VERDICT r5 demands is
#: one command — `python -m paddle_tpu.tuner --preset moe_bench`.
#: grouped_matmul appears twice because the SwiGLU stack runs two bank
#: orientations: gate/up [E, d, h] and down [E, h, d].
BENCH_PRESETS = {
    "moe_bench": [
        ("grouped_matmul", {"d": 1024, "h": 1408, "E": 16}),
        ("grouped_matmul", {"d": 1408, "h": 1024, "E": 16}),
    ],
    "llama_train": [
        ("flash_attention", {"sq": 2048, "sk": 2048, "d": 128}),
        ("rms_norm", {"d": 2560}),
        # the training-kernel suite at the v5e 2.4B train bench
        # geometry (hidden 2560, intermediate 6912, vocab 32000)
        ("rms_norm_residual", {"d": 2560}),
        ("swiglu", {"h": 6912}),
        ("fused_ce", {"d": 2560, "v": 32000}),
    ],
    "serving": [
        # the v5e llama_1b cb-bench geometry: chunk 32, 12-page rows of
        # 32-token pages, head_dim 128
        ("ragged_paged_attention",
         {"c": 32, "pages": 12, "page": 32, "d": 128}),
        # quantized-KV variant (ISSUE 20): same geometry, int8 pools +
        # f32 scales — "kvq" keys a separate shape sig so bf16 winners
        # can't poison quantized configs (and vice versa)
        ("ragged_paged_attention",
         {"c": 32, "pages": 12, "page": 32, "d": 128, "kvq": 1}),
        # model-level: the CLI points at `bench.py --autotune`'s
        # cb-spec section, which sweeps K x draft source here
        ("spec_decode", {"slots": 1, "max_len": 384, "page": 32}),
    ],
    "cpu_smoke": [
        ("grouped_matmul", {"d": 64, "h": 128, "E": 4}),
        ("flash_attention", {"sq": 128, "sk": 128, "d": 64}),
        ("rms_norm", {"d": 128}),
        ("rms_norm_residual", {"d": 128}),
        ("swiglu", {"h": 256}),
        ("fused_ce", {"d": 64, "v": 1024}),
        ("ragged_paged_attention",
         {"c": 8, "pages": 4, "page": 8, "d": 16}),
        ("ragged_paged_attention",
         {"c": 8, "pages": 4, "page": 8, "d": 16, "kvq": 1}),
        ("spec_decode", {"slots": 1, "max_len": 64, "page": 8}),
    ],
}
