"""Trial engine — times compiled candidate variants and picks winners.

The measurement discipline comes from ``profiler/trace.py``'s
device-sync rules: a trial's clock only stops after
:func:`profiler.trace.block_on` confirms the device finished (dispatch
time alone is meaningless on an async backend). Each candidate runs
``warmup`` discarded iterations (compilation + cold caches), then
``repeats`` timed iterations reduced by MEDIAN — robust to one GC
pause or tunnel hiccup, unlike mean or min.

Before anything is timed, candidates are pruned with the roofline
model from ``profiler/cost.py``: a candidate whose lower-bound time
(``max(flops/peak_flops, bytes/hbm_bw)``) exceeds ``prune_ratio`` ×
the best candidate's lower bound cannot win even if it runs at 100%
of the roofline, so the engine proves it worse and skips its compile
+ trial entirely (the cost model is a bound, not an estimate — the
default ratio is deliberately generous).

Non-representative backends: when the trial backend is not a TPU
(``JAX_PLATFORMS=cpu`` smoke runs, interpret-mode Pallas), the engine
warns ONCE per process, still records results (they are real orderings
of the interpreted kernels, useful for plumbing tests) but flags every
cache entry ``representative: false`` — and the cache key's backend
namespace (cache.py) already guarantees such entries can never serve a
TPU process.
"""

from __future__ import annotations

import time
import warnings

from .cache import TuningCache, backend_signature, get_cache, make_key
from .surface import TunableSurface, get_surface, sig_from_dict

__all__ = ["TrialEngine", "TrialResult", "measure_callable",
           "roofline_lower_bound_s"]

_non_tpu_warned = False


def _warn_non_tpu_once(backend: str) -> bool:
    """One-time non-representative-backend warning. Returns True iff
    the backend IS representative (a TPU)."""
    global _non_tpu_warned
    if backend.startswith("tpu:"):
        return True
    if not _non_tpu_warned:
        _non_tpu_warned = True
        msg = (f"trial engine running on non-TPU backend {backend!r}: "
               "timings are recorded but flagged non-representative, "
               "and cached under this backend's namespace (they can "
               "never be served to a TPU process)")
        warnings.warn("paddle_tpu.tuner: " + msg, stacklevel=3)
        from ..profiler.trace import log_perf_event
        log_perf_event("tuner/non_tpu_backend", msg,
                       once_key="tuner/non_tpu_backend")
    return False


def measure_callable(fn, warmup=1, repeats=3) -> float:
    """Median seconds over ``repeats`` device-synced calls of ``fn``
    (a zero-arg callable returning jax arrays / pytrees), after
    ``warmup`` discarded calls that absorb compilation."""
    from ..profiler.trace import block_on
    for _ in range(max(int(warmup), 0)):
        block_on(fn())
    times = []
    for _ in range(max(int(repeats), 1)):
        t0 = time.perf_counter()
        block_on(fn())
        times.append(time.perf_counter() - t0)
    times.sort()
    n = len(times)
    mid = n // 2
    return times[mid] if n % 2 else 0.5 * (times[mid - 1] + times[mid])


def roofline_lower_bound_s(flops, bytes_, peaks) -> float:
    """The time no schedule can beat: compute-bound AND memory-bound
    floors, whichever binds."""
    lb = 0.0
    if flops and peaks.flops:
        lb = max(lb, float(flops) / peaks.flops)
    if bytes_ and peaks.hbm_bw:
        lb = max(lb, float(bytes_) / peaks.hbm_bw)
    return lb


class TrialResult:
    """Outcome of one surface search (winner + full trial table)."""

    def __init__(self, surface, shape_sig, dtype, backend, best_config,
                 best_ms, trials, pruned, representative, cached_hit=False,
                 truncated=0):
        self.surface = surface
        self.shape_sig = shape_sig
        self.dtype = str(dtype)
        self.backend = backend
        self.best_config = best_config
        self.best_ms = best_ms
        self.trials = trials            # [(config, median_ms)]
        self.pruned = pruned            # [(config, lower_bound_ms)]
        self.representative = representative
        self.cached_hit = cached_hit
        self.truncated = truncated      # candidates dropped by max_trials

    @property
    def key(self):
        return make_key(self.surface, self.shape_sig, self.dtype,
                        self.backend)

    def to_dict(self) -> dict:
        return {"surface": self.surface, "shape_sig": self.shape_sig,
                "dtype": self.dtype, "backend": self.backend,
                "config": self.best_config,
                "median_ms": self.best_ms,
                "representative": self.representative,
                "cached_hit": self.cached_hit,
                "truncated": self.truncated,
                "trials": [{"config": c, "median_ms": ms}
                           for c, ms in self.trials],
                "pruned": [{"config": c, "lower_bound_ms": ms}
                           for c, ms in self.pruned]}


class TrialEngine:
    """Search driver: prune → time → pick → persist (module docstring).

    measure_fn: ``fn(config, shape) -> seconds`` — injectable timing
      oracle. The default compiles and times ``builder(config, shape)``
      on the live backend; tests inject a synthetic cost table for a
      deterministic, TPU-free fast-tier check that the engine picks
      the known-best candidate.
    """

    def __init__(self, cache: TuningCache | None = None, *, warmup=2,
                 repeats=5, prune_ratio=4.0, device=None):
        self.cache = cache if cache is not None else get_cache()
        self.warmup = int(warmup)
        self.repeats = int(repeats)
        self.prune_ratio = float(prune_ratio)
        self._device = device
        self._backend = None

    @property
    def backend(self) -> str:
        if self._backend is None:
            self._backend = backend_signature(self._device)
        return self._backend

    # -- pruning -----------------------------------------------------------

    def _prune(self, surface: TunableSurface, shape, candidates):
        """Split candidates into (survivors, pruned): a candidate is
        pruned when the cost model PROVES it slower — its roofline
        lower bound exceeds ``prune_ratio`` × the grid's best lower
        bound (generous: the survivor would have to run below
        1/prune_ratio of roofline for the pruned one to have won)."""
        if surface.cost_fn is None or len(candidates) <= 1:
            return list(candidates), []
        try:
            from ..profiler.cost import device_peaks
            peaks = device_peaks(self._device)
        except Exception:
            return list(candidates), []
        bounds = []
        for c in candidates:
            try:
                flops, bytes_ = surface.cost_fn(c, shape)
                bounds.append(roofline_lower_bound_s(flops, bytes_, peaks))
            except Exception:
                bounds.append(None)     # unknown cost: THIS candidate
                #                         is never pruned, but it must
                #                         not poison the floor either
        known = [b for b in bounds if b]
        floor = min(known) if known else 0.0
        survivors, pruned = [], []
        for c, b in zip(candidates, bounds):
            if b and floor > 0.0 and b > self.prune_ratio * floor:
                pruned.append((c, b * 1e3))
            else:
                survivors.append(c)
        if not survivors:               # paranoia: never prune everything
            return list(candidates), []
        return survivors, pruned

    # -- search ------------------------------------------------------------

    def search(self, surface_name: str, shape: dict, builder=None, *,
               dtype="bfloat16", measure_fn=None, persist=True,
               force=False, max_trials=None) -> TrialResult:
        """Tune one surface at one shape.

        builder: ``fn(config, shape) -> zero-arg callable | None`` —
          produces the trial body for a candidate (None = candidate
          infeasible at runtime, dropped). Required unless
          ``measure_fn`` is given.
        force: re-tune even when the cache already holds this key
          (the CLI's --force; default is resume semantics — a crashed
          sweep restarts and skips every key that already committed).
        max_trials: cap on candidates actually timed (after pruning,
          default-first order). NOT a silent cap: the dropped count is
          reported in the result and the cache entry.
        """
        surface = get_surface(surface_name)
        shape = dict(shape)
        sig = sig_from_dict(shape)
        backend = self.backend
        representative = _warn_non_tpu_once(backend)
        key = make_key(surface_name, sig, dtype, backend)

        if not force:
            hit = self.cache.get(key)
            if hit is not None:
                return TrialResult(
                    surface_name, sig, dtype, backend,
                    dict(hit["config"]), hit.get("median_ms"),
                    trials=[], pruned=[],
                    representative=hit.get("representative", True),
                    cached_hit=True)

        candidates = surface.grid(shape)
        if not candidates:
            raise ValueError(
                f"surface {surface_name!r} produced no valid candidates "
                f"for shape {sig!r}")
        survivors, pruned = self._prune(surface, shape, candidates)
        truncated = 0
        if max_trials is not None and len(survivors) > max_trials:
            truncated = len(survivors) - int(max_trials)
            survivors = survivors[:int(max_trials)]

        if measure_fn is None and builder is None:
            raise ValueError("search() needs a builder when no "
                             "measure_fn is injected")
        trials, errored = [], []
        for config in survivors:
            # per-candidate isolation: one candidate that fails to
            # compile/run (VMEM overflow, Mosaic legalization, ...) is
            # dropped and reported — it must not abort the search and
            # discard every already-timed trial
            try:
                if measure_fn is not None:
                    seconds = measure_fn(dict(config), dict(shape))
                else:
                    fn = builder(dict(config), dict(shape))
                    if fn is None:
                        continue
                    seconds = measure_callable(fn, warmup=self.warmup,
                                               repeats=self.repeats)
            except Exception as e:  # noqa: BLE001 — candidate-scoped
                errored.append((dict(config), f"{type(e).__name__}: {e}"))
                continue
            if seconds is None:
                continue
            trials.append((dict(config), float(seconds) * 1e3))
        if errored:
            warnings.warn(
                f"paddle_tpu.tuner: {surface_name!r} @ {sig!r}: "
                f"{len(errored)} candidate(s) failed and were dropped "
                f"(first: {errored[0][0]} -> {errored[0][1]})",
                stacklevel=2)
        if not trials:
            raise RuntimeError(
                f"surface {surface_name!r}: no candidate produced a "
                f"timing at shape {sig!r}"
                + (f" ({len(errored)} errored; first: "
                   f"{errored[0][1]})" if errored else ""))
        best_config, best_ms = min(trials, key=lambda t: t[1])
        self.cache.put(key, best_config, median_ms=best_ms,
                       repeats=self.repeats, representative=representative,
                       source="search",
                       extra={"trials": len(trials),
                              "pruned": len(pruned),
                              "truncated": truncated,
                              "errored": len(errored)},
                       persist=False)
        if persist:
            self.cache.save_best_effort()
        return TrialResult(surface_name, sig, dtype, backend, best_config,
                           best_ms, trials, pruned, representative,
                           truncated=truncated)
