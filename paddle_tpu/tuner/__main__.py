"""Offline sweep CLI: ``python -m paddle_tpu.tuner``.

The one-command, resumable sweep artifact: each (surface, shape) pair
that finishes commits atomically to the tuning cache, so a sweep
killed mid-way restarts and SKIPS everything already recorded (pass
``--force`` to re-tune). Results print as JSON lines — one complete
record per search — so a driver's time limit can never erase finished
work.

Examples::

    python -m paddle_tpu.tuner --list
    python -m paddle_tpu.tuner --preset moe_bench
    python -m paddle_tpu.tuner --surface grouped_matmul \\
        --shape d=1024,h=1408,E=16 --dtype bfloat16 --repeats 5
    python -m paddle_tpu.tuner --surface flash_attention \\
        --shape sq=2048,sk=2048,d=128 --cache /tmp/cache.json
"""

from __future__ import annotations

import argparse
import json
import sys

from . import TrialEngine, get_surface, list_surfaces, set_cache_path
from .sweeps import BENCH_PRESETS, auto_builder, ensure_builtin_surfaces


def _parse_shape(text: str) -> dict:
    """``d=1024,h=1408,E=16`` -> {'d': 1024, 'h': 1408, 'E': 16}."""
    shape = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        if not _ or not key:
            raise SystemExit(f"bad --shape component {part!r} "
                             "(want key=int,key=int,...)")
        shape[key.strip()] = int(val)
    if not shape:
        raise SystemExit("--shape parsed to nothing")
    return shape


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tuner",
        description="Offline kernel/runtime autotuning sweeps "
                    "(docs/autotune.md)")
    ap.add_argument("--list", action="store_true",
                    help="list registered tunable surfaces and exit")
    ap.add_argument("--surface", action="append", default=[],
                    help="surface to sweep (repeatable)")
    ap.add_argument("--shape", action="append", default=[],
                    help="shape per --surface, e.g. d=1024,h=1408,E=16 "
                         "(repeatable, paired with --surface in order)")
    ap.add_argument("--preset", choices=sorted(BENCH_PRESETS),
                    help="named (surface, shape) list; "
                         "moe_bench = the MoE tile sweep")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--cache", default=None,
                    help="cache file (default: $PADDLE_TPU_TUNER_CACHE "
                         "or ~/.cache/paddle_tpu/tuning_cache.json)")
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--max-candidates", type=int, default=None,
                    help="cap candidates timed per search (dropped "
                         "count is reported, never silent)")
    ap.add_argument("--force", action="store_true",
                    help="re-tune keys already in the cache (default "
                         "resumes: cached keys are skipped)")
    args = ap.parse_args(argv)

    ensure_builtin_surfaces()

    if args.list:
        for name in list_surfaces():
            s = get_surface(name)
            runnable = auto_builder(name, args.dtype) is not None
            print(f"{name}: params={list(s.params)} "
                  f"default={s.default} "
                  f"{'[CLI-sweepable]' if runnable else '[model-level: sweep via bench.py]'}")
            if s.describe:
                print(f"    {s.describe}")
        return 0

    work: list = []
    if args.preset:
        work += [(s, dict(shape), True)
                 for s, shape in BENCH_PRESETS[args.preset]]
    if args.surface:
        if len(args.shape) != len(args.surface):
            raise SystemExit("need exactly one --shape per --surface")
        work += [(s, _parse_shape(sh), False)
                 for s, sh in zip(args.surface, args.shape)]
    if not work:
        ap.print_usage(sys.stderr)
        raise SystemExit("nothing to do: pass --list, --preset or "
                         "--surface/--shape")

    cache = set_cache_path(args.cache) if args.cache else None
    engine = TrialEngine(cache, warmup=args.warmup, repeats=args.repeats)
    print(f"# cache: {engine.cache.path} (backend {engine.backend})",
          file=sys.stderr)

    rc = 0
    for surface_name, shape, from_preset in work:
        builder = auto_builder(surface_name, args.dtype)
        if builder is None:
            print(f"# {surface_name}: no standalone trial builder "
                  "(model-level surface) — serving_chunks is swept by "
                  "`bench.py --autotune`'s cb section, spec_decode by "
                  "its cb-spec section; scan_remat has no automated "
                  "vehicle yet (pin a winner via "
                  "incubate.autotune.set_config or a manual A/B)",
                  file=sys.stderr)
            # presets advertise the full surface set for their
            # workload — a model-level member is a pointer, not a
            # failure; an EXPLICIT --surface ask stays an error
            if not from_preset:
                rc = max(rc, 2)
            continue
        try:
            res = engine.search(surface_name, shape, builder,
                                dtype=args.dtype, force=args.force,
                                max_trials=args.max_candidates)
        except Exception as e:  # one failed search must not kill a sweep
            print(f"# {surface_name} @ {shape}: search failed: {e!r}",
                  file=sys.stderr)
            rc = 1
            continue
        out = res.to_dict()
        if res.cached_hit:
            print(f"# {surface_name} @ {res.shape_sig}: cached, "
                  "skipping (--force to re-tune)", file=sys.stderr)
        print(json.dumps(out), flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
