"""``paddle_tpu.tuner`` — empirical autotuner subsystem.

Three layers (docs/autotune.md has the full story):

1. **Trial engine** (``engine.py``): times compiled candidate variants
   with device-sync points, warmup discard, median-of-k repeats, and
   roofline-based candidate pruning via ``profiler/cost.py``.
2. **Tunable surfaces** (``surface.py``): each searchable knob —
   Pallas grouped-matmul ``bn/bd/bh``, flash-attention
   ``block_q/block_kv``, rms_norm row blocks, the scan remat dose,
   the serving engine's chunk ladder — registers its candidate grid,
   shape-signature key and validity predicate next to the knob itself.
3. **Persistent cache** (``cache.py``): JSON keyed by kernel ×
   shape-signature × dtype × backend:chip, written with the atomic
   stage-then-rename protocol from ``distributed/checkpoint``;
   corrupt/torn caches are detected and discarded, never crashed on.

Kernel call sites read through :func:`lookup`, which resolves
**user override → cache → default**: explicit ``incubate.autotune.
set_config`` configs (and, for flash-attention, explicitly-set
``FLAGS_*`` values — framework/flags.py) always beat cached search
results, which beat static defaults. Sweeps run offline via
``python -m paddle_tpu.tuner`` or ``bench.py --autotune``.

This module is import-light (stdlib only at import time); jax loads
lazily inside the engine when trials actually run.
"""

from __future__ import annotations

import threading

from .cache import (CACHE_VERSION, TuningCache, backend_signature,
                    default_cache_path, get_cache, make_key,
                    set_cache_path)
from .engine import TrialEngine, TrialResult, measure_callable
from .surface import (TunableSurface, get_surface, list_surfaces,
                      register_surface, sig_from_dict)

__all__ = ["TuningCache", "get_cache", "set_cache_path", "make_key",
           "backend_signature", "default_cache_path", "CACHE_VERSION",
           "TrialEngine", "TrialResult", "measure_callable",
           "TunableSurface", "register_surface", "get_surface",
           "list_surfaces", "sig_from_dict",
           "lookup", "set_override", "clear_overrides", "get_override",
           "enabled", "enable", "disable",
           "set_tune_on_first_call", "tune_on_first_call"]

_state_lock = threading.Lock()
_enabled = True                     # cache consultation on by default
_tune_on_first_call = False         # incubate.autotune.set_config switch
_overrides: dict[str, dict] = {}    # surface -> pinned config
_first_call_tls = threading.local()  # reentrancy guard for lookup()


def enabled() -> bool:
    return _enabled


def enable():
    """Turn cache consultation on (the default). The real switch
    upstream users reach is ``incubate.autotune.set_config``."""
    global _enabled
    with _state_lock:
        _enabled = True


def disable():
    global _enabled
    with _state_lock:
        _enabled = False


def set_override(surface: str, config: dict | None):
    """Pin ``surface`` to ``config`` for every shape (None clears).
    Overrides rank above cache entries in :func:`lookup` — this is how
    ``incubate.autotune.set_config(kernel={'configs': ...})`` wins
    over searched values."""
    with _state_lock:
        if config is None:
            _overrides.pop(surface, None)
        else:
            _overrides[surface] = dict(config)


def get_override(surface: str) -> dict | None:
    with _state_lock:
        cfg = _overrides.get(surface)
        return dict(cfg) if cfg is not None else None


def clear_overrides():
    with _state_lock:
        _overrides.clear()


def set_tune_on_first_call(value: bool):
    """When on (via ``incubate.autotune.set_config(kernel={'enable':
    True, 'tune_on_first_call': True})``), a :func:`lookup` MISS for a
    surface with a standalone trial builder (sweeps.py) runs one
    synchronous search — the search cost lands on the first call, the
    winner persists to the cache for every later process."""
    global _tune_on_first_call
    with _state_lock:
        _tune_on_first_call = bool(value)


def tune_on_first_call() -> bool:
    return _tune_on_first_call


def lookup(surface: str, shape: dict | str, dtype="bfloat16") -> dict | None:
    """The hot-path read kernels call at trace time: the config to use
    for ``surface`` at this shape, or None (= use the static default).

    Resolution order: set_config override > persistent-cache entry for
    this backend namespace > (tune-on-first-call search, when enabled
    and the surface has a standalone builder) > None. A DISABLED tuner
    (``set_config(kernel={'enable': False})``) returns None
    unconditionally — every knob falls back to its static default;
    pinned overrides are kept but dormant until re-enabled. Host-side
    dict reads on the hot path — no jax work beyond one cached backend
    probe. NOTE: changing the cache between calls does not retrigger
    jit compilation for shapes jax already compiled; re-trace (fresh
    jit) to pick up new winners.
    """
    if not _enabled:
        return None
    ov = get_override(surface)
    if ov is not None:
        return ov
    sig = shape if isinstance(shape, str) else sig_from_dict(shape)
    try:
        hit = get_cache().lookup(surface, sig, dtype)
    except Exception:
        return None     # a broken cache must never break the kernel
    if hit is not None:
        return hit
    if (_tune_on_first_call and isinstance(shape, dict)
            and not getattr(_first_call_tls, "active", False)):
        # trials themselves call the kernels with explicit configs (no
        # lookup), but guard against any reentrant path anyway
        from .sweeps import auto_builder
        builder = auto_builder(surface, dtype)
        if builder is None:
            return None
        _first_call_tls.active = True
        try:
            res = TrialEngine(warmup=1, repeats=3).search(
                surface, shape, builder, dtype=dtype)
            return dict(res.best_config)
        except Exception:
            return None  # first-call tuning is best-effort by contract
        finally:
            _first_call_tls.active = False
    return None
