"""Persistent tuning cache — searched kernel/runtime configs that
survive the process AND a crash mid-sweep.

One JSON file maps ``surface × shape-signature × dtype × backend:chip``
keys to the winning config plus its trial evidence (median ms, repeat
count, whether the timing backend was representative). Two invariants,
both proven under ``paddle_tpu.testing.FaultInjector``
(tests/test_tuner.py):

- **Atomic commit.** Every write goes through :func:`_atomic_write` —
  the same stage-to-``.part`` + fsync + size-check + ``os.replace``
  protocol as ``distributed/checkpoint`` (and the same hygiene gate:
  ``tools/check_atomic_writes.py`` walks this package too). A crash or
  ENOSPC mid-write can never leave a torn cache; transient I/O errors
  retry with bounded backoff (``utils/retry``).
- **Corrupt caches are discarded, never crashed on.** Load validates
  JSON shape, schema version and a SHA-256 checksum over the entries
  payload; any mismatch (torn write from a pre-atomic writer, silent
  truncation, bit rot, hand-edits gone wrong) logs one warning and
  starts empty — the sweep re-tunes, it does not traceback.

Backend namespacing (the non-TPU-poisoning rule): the key's last
component is ``backend:chip`` (e.g. ``tpu:v5e``, ``cpu:unknown``), so
configs timed under ``JAX_PLATFORMS=cpu`` land in a ``cpu:*`` namespace
a TPU process never reads.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import warnings

__all__ = ["TuningCache", "get_cache", "set_cache_path", "make_key",
           "backend_signature", "default_cache_path", "CACHE_VERSION"]

CACHE_VERSION = 1

#: env var overriding the on-disk location (the offline CLI's --cache
#: flag and tests point here).
CACHE_PATH_ENV = "PADDLE_TPU_TUNER_CACHE"


def default_cache_path() -> str:
    env = os.environ.get(CACHE_PATH_ENV)
    if env:
        return env
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "paddle_tpu", "tuning_cache.json")


_backend_memo: str | None = None


def backend_signature(device=None) -> str:
    """``backend:chip`` namespace component (``tpu:v5e``,
    ``cpu:unknown``). jax is imported lazily and absence tolerated so
    the cache stays usable from stdlib-only tooling. The default-
    device answer is memoized — it is immutable for the process and
    this runs on every trace-time kernel lookup."""
    global _backend_memo
    if device is None and _backend_memo is not None:
        return _backend_memo
    memoize = device is None
    try:
        import jax
        if device is None:
            device = jax.devices()[0]
        platform = str(getattr(device, "platform", "unknown")).lower()
        kind = str(getattr(device, "device_kind", "") or "unknown")
        kind = kind.lower().replace(" ", "_")
        if platform == "tpu":
            # normalize marketing names to the generation tag the
            # profiler peak table keys on (profiler/cost.py)
            from ..profiler.cost import device_peaks
            kind = device_peaks(device).kind
        sig = f"{platform}:{kind}"
        if memoize:
            _backend_memo = sig
        return sig
    except Exception:
        return "cpu:unknown"  # NOT memoized: backend may init later


def make_key(surface: str, shape_sig: str, dtype, backend: str) -> str:
    """Cache key: ``surface|shape_sig|dtype|backend:chip``."""
    return "|".join((surface, shape_sig, str(dtype), backend))


def _entries_checksum(entries: dict) -> str:
    blob = json.dumps(entries, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


def _atomic_write(path, data):
    """THE write primitive for the tuning cache: stage the fully
    serialized bytes to ``<path>.part``, flush + fsync, verify the
    on-disk size, atomically rename into place (the
    ``distributed/checkpoint`` commit protocol; enforced by
    tools/check_atomic_writes.py). Transient OSErrors (ENOSPC a GC
    frees, EIO blips) retry with bounded backoff."""
    from ..utils.retry import retry_call

    part = path + ".part"

    def _write():
        with open(part, "wb") as f:  # atomic-ok: the helper itself
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        size = os.stat(part).st_size
        if size != len(data):
            import errno as _e
            raise OSError(_e.EIO,
                          f"short write: {size} != {len(data)}", part)
        os.replace(part, path)

    try:
        retry_call(_write)
    finally:
        if os.path.exists(part):
            try:
                os.remove(part)
            except OSError:
                pass


class TuningCache:
    """In-memory view of one on-disk tuning-cache file (see module
    docstring). Thread-safe; every mutation persists atomically unless
    ``persist=False``."""

    def __init__(self, path: str | None = None, autoload: bool = True):
        self.path = os.fspath(path) if path is not None \
            else default_cache_path()
        self._entries: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._save_lock = threading.Lock()
        self.discarded_corrupt = False
        if autoload:
            self.load()

    # -- load / validate ---------------------------------------------------

    def load(self) -> int:
        """(Re)load from disk. A missing file is an empty cache; a
        corrupt/torn/stale-schema file is DISCARDED with one warning
        (``discarded_corrupt`` flags it for callers that want to log
        harder). Returns the number of live entries."""
        with self._lock:
            self._entries = {}
            self.discarded_corrupt = False
            try:
                with open(self.path, "r", encoding="utf-8") as f:
                    raw = json.load(f)
                if not isinstance(raw, dict):
                    raise ValueError("cache root is not an object")
                if raw.get("version") != CACHE_VERSION:
                    raise ValueError(
                        f"schema version {raw.get('version')!r} != "
                        f"{CACHE_VERSION}")
                entries = raw.get("entries")
                if not isinstance(entries, dict):
                    raise ValueError("missing entries object")
                if raw.get("checksum") != _entries_checksum(entries):
                    raise ValueError("entries checksum mismatch "
                                     "(torn or corrupted write)")
                self._entries = entries
            except FileNotFoundError:
                pass
            except (ValueError, KeyError, OSError, UnicodeDecodeError) as e:
                # includes json.JSONDecodeError (a ValueError): discard,
                # warn once, re-tune — never traceback on a bad cache
                self.discarded_corrupt = True
                warnings.warn(
                    f"paddle_tpu.tuner: discarding corrupt tuning cache "
                    f"{self.path!r} ({e}); affected surfaces will "
                    f"re-tune", stacklevel=2)
            return len(self._entries)

    # -- read --------------------------------------------------------------

    def get(self, key: str) -> dict | None:
        with self._lock:
            ent = self._entries.get(key)
            return dict(ent) if ent is not None else None

    def lookup(self, surface, shape_sig, dtype, backend=None) -> dict | None:
        """The kernel-facing read: winning config dict for this
        surface × shape × dtype on THIS backend namespace, or None."""
        if backend is None:
            backend = backend_signature()
        ent = self.get(make_key(surface, shape_sig, dtype, backend))
        return dict(ent["config"]) if ent else None

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- write -------------------------------------------------------------

    def put(self, key: str, config: dict, *, median_ms=None, repeats=None,
            representative=True, source="search", extra=None,
            persist=True) -> dict:
        """Record a winning config. ``representative=False`` marks
        timings taken on a non-target backend (CPU interpret-mode
        trials); they still land, but in that backend's namespace and
        flagged, so readers can refuse them."""
        entry = {"config": dict(config),
                 "representative": bool(representative),
                 "source": source,
                 "timestamp": time.time()}
        if median_ms is not None:
            entry["median_ms"] = float(median_ms)
        if repeats is not None:
            entry["repeats"] = int(repeats)
        if extra:
            entry.update(extra)
        with self._lock:
            self._entries[key] = entry
        if persist:
            self.save()
        return entry

    def discard(self, key: str, persist=True) -> bool:
        with self._lock:
            existed = self._entries.pop(key, None) is not None
        if existed and persist:
            self.save()
        return existed

    def save(self):
        """Atomic commit of the full cache state (see module
        docstring). Raises OSError only after bounded retries — callers
        on best-effort paths catch it (``save_best_effort``).

        ``_save_lock`` serializes whole save operations: snapshotting
        outside it would let two concurrent searches race their full-
        state writes and land the STALER snapshot last, dropping the
        other thread's committed winner from disk."""
        with self._save_lock:
            with self._lock:
                entries = dict(self._entries)
            payload = {"version": CACHE_VERSION,
                       "entries": entries,
                       "checksum": _entries_checksum(entries)}
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            _atomic_write(self.path,
                          json.dumps(payload, sort_keys=True,
                                     indent=1).encode())

    def save_best_effort(self) -> bool:
        """Persist, swallowing (with one warning) persistent I/O
        failure: a full disk must not crash the tuned program — the
        in-memory configs still serve this process."""
        try:
            self.save()
            return True
        except OSError as e:
            warnings.warn(
                f"paddle_tpu.tuner: could not persist tuning cache "
                f"{self.path!r} ({e}); tuned configs remain in-memory "
                f"only for this process", stacklevel=2)
            return False


# -- process-global default cache -------------------------------------------

_global_cache: TuningCache | None = None
_global_lock = threading.Lock()


def get_cache() -> TuningCache:
    """The process-wide cache (lazily loaded from
    :func:`default_cache_path` / ``PADDLE_TPU_TUNER_CACHE``)."""
    global _global_cache
    with _global_lock:
        if _global_cache is None:
            _global_cache = TuningCache()
        return _global_cache


def set_cache_path(path) -> TuningCache:
    """Point the process-global cache at ``path`` (reloads). The
    ``incubate.autotune.set_config`` cache_path knob and tests."""
    global _global_cache
    with _global_lock:
        _global_cache = TuningCache(path)
        return _global_cache
