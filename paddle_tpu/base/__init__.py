"""``paddle.base`` — the reference's renamed ``fluid`` package (legacy
import path used by downstream code: ``paddle.base.core``,
``paddle.base.framework``, ``paddle.base.unique_name``; UNVERIFIED —
mount empty). Thin aliases onto this framework's real homes; the C++
``core`` module's surface maps to the Python framework core."""

import sys as _sys

from .. import framework as framework          # noqa: F401
from ..framework import core as core           # noqa: F401
from ..utils import unique_name as unique_name  # noqa: F401
from ..static import Program, Executor          # noqa: F401

# make `import paddle_tpu.base.core` / `from paddle_tpu.base import
# core` both resolve like the reference's real submodules
_sys.modules[__name__ + ".core"] = core
_sys.modules[__name__ + ".framework"] = framework
_sys.modules[__name__ + ".unique_name"] = unique_name

__all__ = ["core", "framework", "unique_name", "Program", "Executor"]
