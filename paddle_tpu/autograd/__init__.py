"""User-facing autograd API — ``python/paddle/autograd/`` parity
(UNVERIFIED): ``backward``, ``grad``, ``no_grad``, ``PyLayer``."""

from __future__ import annotations

from typing import Callable

import jax

from ..framework.core import (Tensor, apply, backward as _backward_impl,
                              no_grad, enable_grad, is_grad_enabled,
                              set_grad_enabled)

__all__ = ["backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer", "PyLayerContext", "hessian",
           "jacobian", "saved_tensors_hooks", "jvp", "vjp"]


def jvp(func, xs, v=None):
    """Forward-mode JVP (delegates to the jax-native incubate impl)."""
    from ..incubate.autograd import jvp as _jvp
    return _jvp(func, xs, v)


def vjp(func, xs, v=None):
    from ..incubate.autograd import vjp as _vjp
    return _vjp(func, xs, v)


def backward(tensors, grad_tensors=None, retain_graph=False):
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    _backward_impl(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None, name=None):
    """``paddle.grad`` — grads of outputs w.r.t. inputs without touching
    ``.grad`` of other leaves. Implemented by running the tape backward and
    collecting; parity caveat: ``create_graph=True`` (double grad) is
    supported through ``paddle_tpu.incubate.autograd.grad`` jax-native path.
    """
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    if grad_outputs is not None and isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    # save/restore existing .grad so paddle.grad is side-effect free;
    # accumulate_ids makes the engine deposit cotangents on the requested
    # inputs even when they are intermediates (non-leaves)
    # _grad_value/_grad_stale, not .grad: an internal save/restore must
    # neither fire nor consume the stale-grad warning
    saved = [(t, t._grad_value, t._grad_stale)
             for t in _all_leaves(outputs) + inputs]
    seen_saved = set()
    saved = [(t, g, st) for t, g, st in saved
             if not (id(t) in seen_saved or seen_saved.add(id(t)))]
    for t, _, _ in saved:
        t._grad_value = None
        t._grad_stale = False
    try:
        _backward_impl(outputs, grad_outputs, retain_graph=True,
                       accumulate_ids=frozenset(id(t) for t in inputs))
        res = []
        for i, t in enumerate(inputs):
            if t._grad_value is None:
                if not allow_unused:
                    raise ValueError(
                        f"paddle.grad: input {i} is unreachable from the "
                        "outputs (no gradient path); pass allow_unused=True "
                        "to get None for such inputs")
                res.append(None)
            else:
                res.append(Tensor(t._grad_value._data))
        return res
    finally:
        for t, g, st in saved:
            t._grad_value = g
            t._grad_stale = st


def _all_leaves(outputs):
    seen, leaves, stack = set(), [], []
    for o in outputs:
        if o._node is not None:
            stack.append(o._node)
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        for p in n.parents:
            if p._node is None:
                leaves.append(p)
            else:
                stack.append(p._node)
    return leaves


#: active (pack, unpack) hook pair installed by saved_tensors_hooks
_saved_hooks: list = []


class saved_tensors_hooks:
    """``paddle.autograd.saved_tensors_hooks(pack, unpack)`` — intercept
    tensors saved for backward (e.g. offload/compress activations).
    Applies to ``PyLayerContext.save_for_backward`` within the context:
    pack runs at save time, unpack at read time."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        _saved_hooks.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _saved_hooks.pop()


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._unpack = None
        self.__dict__["_attrs"] = {}

    def save_for_backward(self, *tensors):
        if _saved_hooks:
            pack, unpack = _saved_hooks[-1]
            self._saved = tuple(pack(t) for t in tensors)
            self._unpack = unpack
        else:
            self._saved = tensors

    def saved_tensor(self):
        if self._unpack is not None:
            return tuple(self._unpack(t) for t in self._saved)
        return self._saved


class PyLayer:
    """Custom autograd function — ``paddle.autograd.PyLayer`` parity.

    Subclass with static ``forward(ctx, *args)`` and ``backward(ctx, *grads)``
    returning input grads. Runs through the tape via jax.custom_vjp-style
    recording."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..framework.core import GradNode, is_grad_enabled
        ctx = PyLayerContext()
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = isinstance(outs, Tensor)
        out_list = [outs] if single else list(outs)
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        needs = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        if needs:
            def vjp_fn(cotangents):
                gs = cotangents if isinstance(cotangents, tuple) else (cotangents,)
                gts = [Tensor(g) for g in gs]
                with no_grad():
                    in_grads = cls.backward(ctx, *gts) if len(gts) > 1 \
                        else cls.backward(ctx, gts[0])
                if isinstance(in_grads, Tensor) or in_grads is None:
                    in_grads = (in_grads,)
                return tuple(
                    g._data if isinstance(g, Tensor) else g
                    for g in in_grads)
            parents = [t for t in tensor_inputs if not t.stop_gradient]
            # map backward outputs (per tensor input) onto parents
            def vjp_parents(cotangents):
                full = vjp_fn(cotangents)
                out = []
                k = 0
                for t in tensor_inputs:
                    g = full[k] if k < len(full) else None
                    k += 1
                    if not t.stop_gradient:
                        out.append(g)
                return tuple(out)
            node = GradNode(vjp_parents, parents, len(out_list),
                            name=cls.__name__,
                            out_avals=[(o._data.shape, o._data.dtype)
                                       for o in out_list])
            for i, o in enumerate(out_list):
                o._node = node
                o._out_idx = i
                o.stop_gradient = False
        return outs


def jacobian(ys, xs, batch_axis=None):
    """Dense jacobian via jax.jacrev on the underlying arrays."""
    single_y = isinstance(ys, Tensor)
    single_x = isinstance(xs, Tensor)
    ylist = [ys] if single_y else list(ys)
    xlist = [xs] if single_x else list(xs)
    raise NotImplementedError(
        "Use paddle_tpu.incubate.autograd.jacobian (jax-native) — the "
        "tape records concrete values; jacobians need a functional recompute.")


def hessian(ys, xs, batch_axis=None):
    raise NotImplementedError(
        "Use paddle_tpu.incubate.autograd.hessian (jax-native).")
