"""Autoregressive text generation (``model.generate``).

Reference role: PaddleNLP ``generation_utils.py`` ``GenerationMixin``
(greedy_search / sampling decode strategies over a ``cache_kv`` decoder
cache; reference mount empty, no cites — see SURVEY.md provenance note).

TPU-native design: decoding runs as ONE compiled XLA step per token —
model forward over a **static-shape KV cache** (`sdpa_with_cache`,
``lax.dynamic_update_slice`` writes), plus logits processing (repetition
penalty, temperature, top-k, top-p) and categorical sampling with an
explicit threaded PRNG key, all inside a single ``to_static`` program.
The host loop only carries the python step counter and the early-exit
check; shapes never change during decode, so the step compiles exactly
once (prefill compiles once per prompt length).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply

__all__ = ["GenerationConfig", "GenerationMixin"]


@dataclass
class GenerationConfig:
    max_new_tokens: int = 64
    decode_strategy: str = "sampling"  # "greedy_search" | "sampling"
    temperature: float = 1.0
    top_k: int = 0                     # 0 = disabled
    top_p: float = 1.0                 # 1.0 = disabled
    repetition_penalty: float = 1.0
    eos_token_id: int | None = None
    pad_token_id: int | None = None
    use_cache: bool = True
    seed: int | None = None


def _process_and_sample(logits, key, buf, write_pos, finished, *,
                        temperature, top_k, top_p, rep, greedy,
                        eos_id, pad_id):
    """Pure-jnp logits pipeline -> next token. Runs inside the compiled
    decode step. logits: [B, V] (last position), buf: [B, L] tokens so far,
    write_pos: int32 scalar (where the new token goes), finished: [B] bool.
    """
    b, vocab = logits.shape
    lg = logits.astype(jnp.float32)
    if rep != 1.0:
        # penalize every token id already present in buf[:, :write_pos]
        valid = jnp.arange(buf.shape[1])[None, :] < write_pos       # [B?, L]
        seen = jnp.zeros((b, vocab), jnp.float32).at[
            jnp.arange(b)[:, None], buf].add(valid.astype(jnp.float32))
        pen = jnp.where(lg > 0, lg / rep, lg * rep)
        lg = jnp.where(seen > 0, pen, lg)
    if temperature != 1.0 and not greedy:
        lg = lg / temperature
    if top_k and top_k > 0 and not greedy:
        kth = jax.lax.top_k(lg, min(top_k, vocab))[0][:, -1:]
        lg = jnp.where(lg < kth, -jnp.inf, lg)
    if top_p < 1.0 and not greedy:
        sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_lg, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set of tokens with cumulative prob >= top_p; the shifted
        # comparison keeps the first token crossing the threshold
        cutoff_mask = cum - probs > top_p
        cutoff = jnp.where(cutoff_mask, jnp.inf, sorted_lg).min(
            axis=-1, keepdims=True)
        lg = jnp.where(lg < cutoff, -jnp.inf, lg)
    if greedy:
        tok = jnp.argmax(lg, axis=-1).astype(buf.dtype)
        new_key = key
    else:
        key, sub = jax.random.split(key)
        tok = jax.random.categorical(sub, lg).astype(buf.dtype)
        new_key = key
    logprob = jax.nn.log_softmax(lg, axis=-1)[jnp.arange(b), tok]
    if eos_id >= 0:
        tok = jnp.where(finished, jnp.asarray(pad_id, buf.dtype), tok)
        logprob = jnp.where(finished, 0.0, logprob)
        new_finished = finished | (tok == eos_id)
    else:
        new_finished = finished
    buf = jax.lax.dynamic_update_slice(
        buf, tok[:, None], (jnp.zeros((), jnp.int32),
                            write_pos.astype(jnp.int32)))
    return tok, logprob, new_key, buf, new_finished


class GenerationMixin:
    """Adds ``generate`` to a causal-LM Layer.

    The model must implement
      - ``init_kv_cache(batch_size, max_length)`` -> list[Tensor] and
      - ``forward(input_ids, caches=..., pos=...)`` -> (logits, new_caches).
    """

    generation_config: GenerationConfig | None = None

    def init_kv_cache(self, batch_size, max_length, dtype=None):
        raise NotImplementedError

    # -- the compiled step ---------------------------------------------------

    def _gen_step_static(self):
        cached = self.__dict__.get("_generate_step_fn")
        if cached is None:
            from ..jit import to_static
            from ..framework.core import no_grad

            def step(tok, pos, key_t, buf, finished, caches, temperature,
                     top_k, top_p, rep, greedy, eos_id, pad_id):
                with no_grad():
                    logits, caches = self.forward(tok, caches=caches, pos=pos)
                last = logits[:, -1]

                def fn(lg, p, k, bf, fin):
                    s = tok.shape[1]
                    return _process_and_sample(
                        lg, k, bf, p.astype(jnp.int32) + s, fin,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        rep=rep, greedy=greedy, eos_id=eos_id, pad_id=pad_id)
                nxt, lp, nk, nbuf, nfin = apply(
                    fn, last, pos, key_t, buf, finished, n_outputs=5,
                    name="gen_select", differentiable=False)
                return nxt, lp, nk, nbuf, nfin, caches

            cached = to_static(step)
            self.__dict__["_generate_step_fn"] = cached
        return cached

    def _gen_fused_static(self):
        """Whole-generation compiled path: prefill + a ``lax.scan`` over
        every decode step in ONE program. Used when no eos early-exit is
        requested (the scan has a static trip count). This is the
        TPU-native serving shape — a device-side decode loop instead of
        one host dispatch per token (each of which pays scheduling /
        tunnel latency)."""
        cached = self.__dict__.get("_generate_fused_fn")
        if cached is None:
            from ..jit import to_static
            from ..framework.core import no_grad

            def run(ids32, key_t, buf, caches, temperature, top_k, top_p,
                    rep, greedy, pad_id, n_new):
                # temperature/top_k/top_p/rep/greedy/pad_id/n_new are
                # python scalars: part of the to_static signature key
                prompt_len = ids32.shape[1]
                with no_grad():
                    logits, caches = self.forward(
                        ids32, caches=caches,
                        pos=Tensor(jnp.zeros((), jnp.int32)))
                last = logits[:, -1]
                fwd = self.forward

                def fn(lg, key, bufa, *cache_leaves):
                    b = lg.shape[0]
                    fin = jnp.zeros((b,), bool)
                    tok, lp, key, bufa, _ = _process_and_sample(
                        lg, key, bufa,
                        jnp.asarray(prompt_len, jnp.int32), fin,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        rep=rep, greedy=greedy, eos_id=-1, pad_id=pad_id)

                    def body(carry, i):
                        tok_c, key_c, buf_c, cl, acc = carry
                        with no_grad():
                            lg2, nc = fwd(
                                Tensor(tok_c.reshape(b, 1)),
                                caches=[Tensor(a) for a in cl],
                                pos=Tensor((prompt_len + i)
                                           .astype(jnp.int32)))
                        t2, lp2, key2, buf2, _ = _process_and_sample(
                            lg2[:, -1]._data, key_c, buf_c,
                            (jnp.asarray(prompt_len + 1, jnp.int32)
                             + i.astype(jnp.int32)), fin,
                            temperature=temperature, top_k=top_k,
                            top_p=top_p, rep=rep, greedy=greedy,
                            eos_id=-1, pad_id=pad_id)
                        new_cl = [t._data for t in nc]
                        return (t2, key2, buf2, new_cl,
                                acc + lp2.astype(jnp.float32)), None

                    carry0 = (tok, key, bufa, list(cache_leaves),
                              lp.astype(jnp.float32))
                    carry, _ = jax.lax.scan(body, carry0,
                                            jnp.arange(n_new - 1))
                    _, key_f, buf_f, _, lp_f = carry
                    return buf_f, lp_f, key_f

                outs = apply(fn, last, key_t, buf, *caches, n_outputs=3,
                             name="fused_decode", differentiable=False)
                return outs

            cached = to_static(run)
            self.__dict__["_generate_fused_fn"] = cached
        return cached

    # -- public API ----------------------------------------------------------

    def generate(self, input_ids, generation_config=None, max_new_tokens=None,
                 max_length=None, decode_strategy=None, temperature=None,
                 top_k=None, top_p=None, repetition_penalty=None,
                 eos_token_id=None, pad_token_id=None, use_cache=None,
                 seed=None, **kwargs):
        """Generate token ids. Returns ``(generated_ids, scores)`` where
        ``generated_ids`` is [B, new_len] (prompt excluded, PaddleNLP
        convention) and ``scores`` the mean logprob of each sequence."""
        cfg = generation_config or self.generation_config or \
            GenerationConfig()
        pick = lambda v, d: d if v is None else v  # noqa: E731
        strategy = pick(decode_strategy, cfg.decode_strategy)
        greedy = strategy in ("greedy_search", "greedy")
        temperature_ = float(pick(temperature, cfg.temperature))
        top_k_ = int(pick(top_k, cfg.top_k))
        top_p_ = float(pick(top_p, cfg.top_p))
        rep_ = float(pick(repetition_penalty, cfg.repetition_penalty))
        eos_ = pick(eos_token_id, cfg.eos_token_id)
        pad_ = pick(pad_token_id, cfg.pad_token_id)
        pad_ = (eos_ if pad_ is None else pad_) or 0
        seed_ = pick(seed, cfg.seed)
        ids = input_ids if isinstance(input_ids, Tensor) else \
            Tensor(jnp.asarray(np.asarray(input_ids)))
        b, prompt_len = ids.shape
        if max_new_tokens is None and max_length is not None:
            max_new_tokens = int(max_length) - prompt_len
        n_new = int(pick(max_new_tokens, cfg.max_new_tokens))
        if n_new <= 0:
            raise ValueError(
                f"max_new_tokens must be positive, got {n_new} "
                f"(max_length={max_length}, prompt length {prompt_len})")
        total = prompt_len + n_new

        if seed_ is not None:
            key = jax.random.PRNGKey(seed_)
        else:
            from ..framework import random as fr
            key = fr.default_generator.next_key()
        key_t = Tensor(key)

        ids32 = Tensor(ids._data.astype(jnp.int32))
        buf = Tensor(jnp.concatenate(
            [ids32._data, jnp.full((b, n_new), pad_, jnp.int32)], axis=1))
        finished = Tensor(jnp.zeros((b,), bool))
        caches = self.init_kv_cache(b, total)
        eos_i = -1 if eos_ is None else int(eos_)
        if eos_i < 0:
            # no eos early-exit -> static trip count -> the whole decode
            # runs as ONE compiled program (prefill + lax.scan over steps)
            buf_f, lp_f, _key_f = self._gen_fused_static()(
                ids32, key_t, buf, caches, temperature_, top_k_, top_p_,
                rep_, greedy, int(pad_), n_new)
            gen = Tensor(buf_f._data[:, prompt_len:prompt_len + n_new])
            scores = Tensor(lp_f._data / float(n_new))
            return gen, scores

        step = self._gen_step_static()

        pos = Tensor(jnp.zeros((), jnp.int32))
        tok, lp, key_t, buf, finished, caches = step(
            ids32, pos, key_t, buf, finished, caches, temperature_, top_k_,
            top_p_, rep_, greedy, eos_i, int(pad_))
        lp_sum = lp.jax().astype(jnp.float32)
        # per-row generated-token counts: a row stops accruing once finished
        counts = np.ones((b,), np.float32)
        steps_done = 1
        # this step loop only serves the eos path now (eos-less decode
        # returned above via the fused scan); poll finished per token to
        # early-exit once every row hit eos
        for i in range(1, n_new):
            fin_np = np.asarray(finished.jax())
            if bool(fin_np.all()):
                break
            counts += (~fin_np).astype(np.float32)
            pos = Tensor(jnp.asarray(prompt_len + i - 1, jnp.int32))
            tok2d = Tensor(tok._data.reshape(b, 1))
            tok, lp, key_t, buf, finished, caches = step(
                tok2d, pos, key_t, buf, finished, caches, temperature_,
                top_k_, top_p_, rep_, greedy, eos_i, int(pad_))
            lp_sum = lp_sum + lp.jax().astype(jnp.float32)
            steps_done += 1
        gen = Tensor(buf._data[:, prompt_len:prompt_len + steps_done])
        scores = Tensor(lp_sum / jnp.asarray(counts))
        return gen, scores
