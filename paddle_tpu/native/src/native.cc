// paddle_tpu native runtime core.
//
// Reference parity: the C++ runtime pieces that remain host-side work on
// TPU (SURVEY.md §2.1): TCPStore rendezvous (paddle/fluid/distributed/
// store/tcp_store.*, UNVERIFIED — reference mount empty) and the
// data-loader's native batch assembly (paddle/fluid/operators/reader +
// DataLoader C++ workers). The TPU compute path is XLA; these are the
// honest native components: sockets, threads, memcpy.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).
//
// Components:
//   1. TCPStore — key/value rendezvous with blocking wait: a master
//      process serves set/get/add/wait over TCP; workers connect by
//      host:port. Used by paddle_tpu.distributed.launch for multi-host
//      bootstrap, barriers and elastic membership.
//   2. pts_parallel_stack — multi-threaded sample->batch memcpy (the hot
//      loop of collate) .
//   3. pts_shuffle — Fisher-Yates index shuffle with splitmix64.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------- utils

bool send_all(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) return false;
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) return false;
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool send_u32(int fd, uint32_t v) {
  uint32_t be = htonl(v);
  return send_all(fd, &be, 4);
}

bool recv_u32(int fd, uint32_t* v) {
  uint32_t be;
  if (!recv_all(fd, &be, 4)) return false;
  *v = ntohl(be);
  return true;
}

bool send_i64(int fd, int64_t v) {
  uint64_t u = static_cast<uint64_t>(v);
  uint32_t hi = htonl(static_cast<uint32_t>(u >> 32));
  uint32_t lo = htonl(static_cast<uint32_t>(u & 0xffffffffu));
  return send_all(fd, &hi, 4) && send_all(fd, &lo, 4);
}

bool recv_i64(int fd, int64_t* v) {
  uint32_t hi, lo;
  if (!recv_u32(fd, &hi) || !recv_u32(fd, &lo)) return false;
  *v = static_cast<int64_t>((static_cast<uint64_t>(hi) << 32) |
                            static_cast<uint64_t>(lo));
  return true;
}

bool send_str(int fd, const std::string& s) {
  return send_u32(fd, static_cast<uint32_t>(s.size())) &&
         (s.empty() || send_all(fd, s.data(), s.size()));
}

bool recv_str(int fd, std::string* s) {
  uint32_t n;
  if (!recv_u32(fd, &n)) return false;
  s->resize(n);
  return n == 0 || recv_all(fd, &(*s)[0], n);
}

// ---------------------------------------------------------------- server

// wire ops
enum Op : uint8_t { OP_SET = 1, OP_GET = 2, OP_ADD = 3, OP_WAIT = 4,
                    OP_DEL = 5, OP_PING = 6 };

struct StoreServer {
  int listen_fd = -1;
  std::atomic<bool> running{false};
  std::thread accept_thread;
  std::vector<std::thread> handlers;
  std::mutex mu;
  std::condition_variable cv;
  std::unordered_map<std::string, std::string> kv;

  ~StoreServer() { stop(); }

  void handle(int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    for (;;) {
      uint8_t op;
      if (!recv_all(fd, &op, 1)) break;
      std::string key;
      if (op != OP_PING && !recv_str(fd, &key)) break;
      if (op == OP_SET) {
        std::string val;
        if (!recv_str(fd, &val)) break;
        {
          std::lock_guard<std::mutex> g(mu);
          kv[key] = std::move(val);
        }
        cv.notify_all();
        uint8_t ok = 1;
        if (!send_all(fd, &ok, 1)) break;
      } else if (op == OP_GET) {
        std::string val;
        bool found;
        {
          std::lock_guard<std::mutex> g(mu);
          auto it = kv.find(key);
          found = it != kv.end();
          if (found) val = it->second;
        }
        uint8_t ok = found ? 1 : 0;
        if (!send_all(fd, &ok, 1)) break;
        if (found && !send_str(fd, val)) break;
        if (!found) { /* key absent signalled by ok=0 */ }
      } else if (op == OP_ADD) {
        int64_t delta, result;
        if (!recv_i64(fd, &delta)) break;
        {
          std::lock_guard<std::mutex> g(mu);
          int64_t cur = 0;
          auto it = kv.find(key);
          if (it != kv.end() && it->second.size() == 8)
            memcpy(&cur, it->second.data(), 8);
          cur += delta;
          std::string v(8, '\0');
          memcpy(&v[0], &cur, 8);
          kv[key] = v;
          result = cur;
        }
        cv.notify_all();
        if (!send_i64(fd, result)) break;
      } else if (op == OP_WAIT) {
        int64_t timeout_ms;
        if (!recv_i64(fd, &timeout_ms)) break;
        bool ok;
        {
          std::unique_lock<std::mutex> g(mu);
          auto pred = [&] { return kv.count(key) > 0; };
          if (timeout_ms < 0) {
            cv.wait(g, pred);
            ok = true;
          } else {
            ok = cv.wait_for(g, std::chrono::milliseconds(timeout_ms),
                             pred);
          }
        }
        uint8_t r = ok ? 1 : 0;
        if (!send_all(fd, &r, 1)) break;
      } else if (op == OP_DEL) {
        {
          std::lock_guard<std::mutex> g(mu);
          kv.erase(key);
        }
        uint8_t ok = 1;
        if (!send_all(fd, &ok, 1)) break;
      } else if (op == OP_PING) {
        uint8_t ok = 1;
        if (!send_all(fd, &ok, 1)) break;
      } else {
        break;
      }
    }
    ::close(fd);
  }

  bool start(int port) {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0)
      return false;
    if (::listen(listen_fd, 128) != 0) return false;
    running = true;
    accept_thread = std::thread([this] {
      while (running) {
        int fd = ::accept(listen_fd, nullptr, nullptr);
        if (fd < 0) break;
        handlers.emplace_back([this, fd] { handle(fd); });
      }
    });
    return true;
  }

  void stop() {
    if (!running.exchange(false)) return;
    ::shutdown(listen_fd, SHUT_RDWR);
    ::close(listen_fd);
    if (accept_thread.joinable()) accept_thread.join();
    for (auto& t : handlers)
      if (t.joinable()) t.join();
    handlers.clear();
  }
};

// ---------------------------------------------------------------- client

struct StoreClient {
  int fd = -1;
  std::mutex mu;  // one request/response in flight per client

  ~StoreClient() {
    if (fd >= 0) ::close(fd);
  }

  bool connect_to(const char* host, int port, int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    for (;;) {
      fd = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd < 0) return false;
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(static_cast<uint16_t>(port));
      if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
        ::close(fd);
        fd = -1;
        return false;
      }
      if (::connect(fd, reinterpret_cast<sockaddr*>(&addr),
                    sizeof(addr)) == 0) {
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return true;
      }
      ::close(fd);
      fd = -1;
      if (std::chrono::steady_clock::now() >= deadline) return false;
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
  }
};

}  // namespace

extern "C" {

// ---- TCPStore C ABI ----

void* pts_store_server_start(int port) {
  auto* s = new StoreServer();
  if (!s->start(port)) {
    delete s;
    return nullptr;
  }
  return s;
}

void pts_store_server_stop(void* h) {
  delete static_cast<StoreServer*>(h);
}

void* pts_store_client_new(const char* host, int port, int timeout_ms) {
  auto* c = new StoreClient();
  if (!c->connect_to(host, port, timeout_ms)) {
    delete c;
    return nullptr;
  }
  return c;
}

void pts_store_client_free(void* h) {
  delete static_cast<StoreClient*>(h);
}

int pts_store_set(void* h, const char* key, const uint8_t* val, int len) {
  auto* c = static_cast<StoreClient*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t op = OP_SET;
  if (!send_all(c->fd, &op, 1) || !send_str(c->fd, key) ||
      !send_str(c->fd, std::string(reinterpret_cast<const char*>(val),
                                   static_cast<size_t>(len))))
    return -1;
  uint8_t ok;
  return recv_all(c->fd, &ok, 1) && ok == 1 ? 0 : -1;
}

// returns length (>=0) and fills buf (up to buflen); -1 missing; -2 error
int pts_store_get(void* h, const char* key, uint8_t* buf, int buflen) {
  auto* c = static_cast<StoreClient*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t op = OP_GET;
  if (!send_all(c->fd, &op, 1) || !send_str(c->fd, key)) return -2;
  uint8_t ok;
  if (!recv_all(c->fd, &ok, 1)) return -2;
  if (!ok) return -1;
  std::string val;
  if (!recv_str(c->fd, &val)) return -2;
  int n = static_cast<int>(val.size());
  if (n > buflen) n = buflen;
  memcpy(buf, val.data(), static_cast<size_t>(n));
  return static_cast<int>(val.size());
}

long long pts_store_add(void* h, const char* key, long long delta) {
  auto* c = static_cast<StoreClient*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t op = OP_ADD;
  if (!send_all(c->fd, &op, 1) || !send_str(c->fd, key) ||
      !send_i64(c->fd, delta))
    return -(1LL << 62);
  int64_t result;
  if (!recv_i64(c->fd, &result)) return -(1LL << 62);
  return result;
}

// 1 = key present, 0 = timeout, -1 = error
int pts_store_wait(void* h, const char* key, long long timeout_ms) {
  auto* c = static_cast<StoreClient*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t op = OP_WAIT;
  if (!send_all(c->fd, &op, 1) || !send_str(c->fd, key) ||
      !send_i64(c->fd, timeout_ms))
    return -1;
  uint8_t ok;
  if (!recv_all(c->fd, &ok, 1)) return -1;
  return ok ? 1 : 0;
}

int pts_store_delete(void* h, const char* key) {
  auto* c = static_cast<StoreClient*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t op = OP_DEL;
  if (!send_all(c->fd, &op, 1) || !send_str(c->fd, key)) return -1;
  uint8_t ok;
  return recv_all(c->fd, &ok, 1) && ok == 1 ? 0 : -1;
}

int pts_store_ping(void* h) {
  auto* c = static_cast<StoreClient*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t op = OP_PING;
  if (!send_all(c->fd, &op, 1)) return -1;
  uint8_t ok;
  return recv_all(c->fd, &ok, 1) && ok == 1 ? 0 : -1;
}

// ---- data loader core ----

// stack n equally-sized samples into dst (contiguous batch) with threads
void pts_parallel_stack(uint8_t* dst, const uint8_t** srcs, long long n,
                        long long bytes_per_sample, int nthreads) {
  if (nthreads <= 1 || n < 4) {
    for (long long i = 0; i < n; ++i)
      memcpy(dst + i * bytes_per_sample, srcs[i],
             static_cast<size_t>(bytes_per_sample));
    return;
  }
  if (nthreads > n) nthreads = static_cast<int>(n);
  std::vector<std::thread> ts;
  ts.reserve(static_cast<size_t>(nthreads));
  long long per = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    long long lo = t * per;
    long long hi = lo + per > n ? n : lo + per;
    if (lo >= hi) break;
    ts.emplace_back([=] {
      for (long long i = lo; i < hi; ++i)
        memcpy(dst + i * bytes_per_sample, srcs[i],
               static_cast<size_t>(bytes_per_sample));
    });
  }
  for (auto& t : ts) t.join();
}

static inline uint64_t splitmix64(uint64_t* s) {
  uint64_t z = (*s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

// in-place Fisher-Yates over idx[0..n)
void pts_shuffle(long long* idx, long long n, unsigned long long seed) {
  uint64_t s = seed ? seed : 0x853c49e6748fea9bULL;
  for (long long i = n - 1; i > 0; --i) {
    uint64_t j = splitmix64(&s) % static_cast<uint64_t>(i + 1);
    long long tmp = idx[i];
    idx[i] = idx[static_cast<long long>(j)];
    idx[static_cast<long long>(j)] = tmp;
  }
}

}  // extern "C"
