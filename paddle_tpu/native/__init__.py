"""Native (C++) runtime components, loaded via ctypes.

Reference parity (SURVEY.md §2.1): the reference's host-side C++ runtime —
TCPStore rendezvous (paddle/fluid/distributed/store, UNVERIFIED) and the
DataLoader's native workers. On TPU the *compute* runtime is XLA/PJRT; the
honest native surface is this host-side core: a TCP key/value store with
blocking wait (multi-host bootstrap, barriers, elastic membership) and a
threaded batch-assembly memcpy core for the data loader.

The shared library is built on demand with g++ (toolchain is baked into
the image; no pybind11 — plain C ABI + ctypes). Every entry point has a
pure-Python fallback so the package works even without a compiler
(``available()`` reports which path is active).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["available", "parallel_stack", "shuffle_indices", "TCPStore",
           "TCPStoreServer"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "native.cc")
_LIB = os.path.join(_HERE, "_paddle_tpu_native.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> str | None:
    if os.path.exists(_LIB) and \
            os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return _LIB
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-pthread", "-std=c++17",
           _SRC, "-o", _LIB + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_LIB + ".tmp", _LIB)
        return _LIB
    except Exception:
        return None


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("FLAGS_paddle_tpu_disable_native", "0") == "1":
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.pts_store_server_start.restype = ctypes.c_void_p
        lib.pts_store_server_start.argtypes = [ctypes.c_int]
        lib.pts_store_server_stop.argtypes = [ctypes.c_void_p]
        lib.pts_store_client_new.restype = ctypes.c_void_p
        lib.pts_store_client_new.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                             ctypes.c_int]
        lib.pts_store_client_free.argtypes = [ctypes.c_void_p]
        lib.pts_store_set.restype = ctypes.c_int
        lib.pts_store_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_int]
        lib.pts_store_get.restype = ctypes.c_int
        lib.pts_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_int]
        lib.pts_store_add.restype = ctypes.c_longlong
        lib.pts_store_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_longlong]
        lib.pts_store_wait.restype = ctypes.c_int
        lib.pts_store_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_longlong]
        lib.pts_store_delete.restype = ctypes.c_int
        lib.pts_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pts_store_ping.restype = ctypes.c_int
        lib.pts_store_ping.argtypes = [ctypes.c_void_p]
        lib.pts_parallel_stack.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_int]
        lib.pts_shuffle.argtypes = [
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong,
            ctypes.c_ulonglong]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# ---- data loader core -----------------------------------------------------

def parallel_stack(arrays, nthreads: int = 4) -> np.ndarray:
    """np.stack over equally-shaped arrays using the native threaded
    memcpy core when possible."""
    lib = _load()
    first = np.asarray(arrays[0])
    if (lib is None or len(arrays) < 4 or first.nbytes < 1024):
        return np.stack([np.asarray(a) for a in arrays])
    mats = [np.ascontiguousarray(a) for a in arrays]
    if any(m.shape != first.shape or m.dtype != first.dtype
           for m in mats):
        return np.stack(mats)
    n = len(mats)
    out = np.empty((n,) + first.shape, dtype=first.dtype)
    srcs = (ctypes.c_void_p * n)(*[m.ctypes.data for m in mats])
    lib.pts_parallel_stack(ctypes.c_void_p(out.ctypes.data), srcs,
                           n, first.nbytes, nthreads)
    return out


def shuffle_indices(n: int, seed: int) -> np.ndarray:
    """Fisher-Yates permutation of arange(n) (native when available)."""
    idx = np.arange(n, dtype=np.int64)
    lib = _load()
    if lib is None or n < 2:
        rng = np.random.RandomState(seed & 0x7FFFFFFF)
        rng.shuffle(idx)
        return idx
    lib.pts_shuffle(idx.ctypes.data_as(
        ctypes.POINTER(ctypes.c_longlong)), n, seed)
    return idx


# ---- TCPStore -------------------------------------------------------------

class TCPStoreServer:
    """Master-side store (runs the accept loop in native threads)."""

    def __init__(self, port: int):
        lib = _load()
        self._lib = lib
        self._handle = None
        self.port = port
        if lib is not None:
            h = lib.pts_store_server_start(port)
            if not h:
                raise OSError(f"TCPStoreServer: cannot bind port {port}")
            self._handle = h
        else:
            self._py = _PyStoreServer(port)

    def close(self):
        if self._handle is not None:
            self._lib.pts_store_server_stop(self._handle)
            self._handle = None
        elif getattr(self, "_py", None) is not None:
            self._py.close()
            self._py = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class TCPStore:
    """Client — ``paddle.distributed.TCPStore``-shaped API.

    When ``is_master`` is True a server is started in-process first (the
    reference's master-rank behavior), then a client connects to it.
    """

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 30.0):
        self.host = host
        self.port = port
        self.is_master = is_master
        self._server = TCPStoreServer(port) if is_master else None
        lib = _load()
        self._lib = lib
        if lib is not None:
            connect_host = "127.0.0.1" if is_master else host
            h = lib.pts_store_client_new(connect_host.encode(), port,
                                         int(timeout * 1000))
            if not h:
                raise TimeoutError(
                    f"TCPStore: cannot connect {host}:{port}")
            self._handle = h
        else:
            self._handle = None
            self._py = _PyStoreClient(
                "127.0.0.1" if is_master else host, port, timeout)

    def set(self, key: str, value) -> None:
        data = value if isinstance(value, bytes) else str(value).encode()
        if self._handle is not None:
            rc = self._lib.pts_store_set(self._handle, key.encode(), data,
                                         len(data))
            if rc != 0:
                raise OSError("TCPStore.set failed")
        else:
            self._py.request(b"S", key, data)

    def get(self, key: str) -> bytes | None:
        if self._handle is not None:
            buf = ctypes.create_string_buffer(1 << 16)
            n = self._lib.pts_store_get(self._handle, key.encode(), buf,
                                        len(buf))
            if n == -1:
                return None
            if n < 0:
                raise OSError("TCPStore.get failed")
            if n > len(buf):  # retry with exact size
                buf = ctypes.create_string_buffer(n)
                n = self._lib.pts_store_get(self._handle, key.encode(),
                                            buf, len(buf))
            return buf.raw[:n]
        return self._py.request(b"G", key)

    def add(self, key: str, delta: int = 1) -> int:
        if self._handle is not None:
            r = self._lib.pts_store_add(self._handle, key.encode(), delta)
            if r == -(1 << 62):
                raise OSError("TCPStore.add failed")
            return int(r)
        return self._py.request(b"A", key, str(delta).encode())

    def wait(self, key: str, timeout: float | None = None) -> bool:
        ms = -1 if timeout is None else int(timeout * 1000)
        if self._handle is not None:
            r = self._lib.pts_store_wait(self._handle, key.encode(), ms)
            if r < 0:
                raise OSError("TCPStore.wait failed")
            return r == 1
        return self._py.request(b"W", key, str(ms).encode())

    def delete_key(self, key: str) -> None:
        if self._handle is not None:
            self._lib.pts_store_delete(self._handle, key.encode())
        else:
            self._py.request(b"D", key)

    def close(self):
        if self._handle is not None:
            self._lib.pts_store_client_free(self._handle)
            self._handle = None
        if self._server is not None:
            self._server.close()
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---- pure-Python fallback store (no compiler available) -------------------

class _PyStoreServer:
    def __init__(self, port):
        import socketserver
        import pickle

        kv = {}
        cond = threading.Condition()

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    try:
                        header = self.rfile.readline()
                        if not header:
                            return
                        op, key, n = pickle.loads(bytes.fromhex(
                            header.strip().decode()))
                        payload = self.rfile.read(n) if n else b""
                        if op == "S":
                            with cond:
                                kv[key] = payload
                                cond.notify_all()
                            resp = b"1"
                        elif op == "G":
                            with cond:
                                resp = kv.get(key)
                            resp = b"\x00" if resp is None else \
                                b"\x01" + resp
                        elif op == "A":
                            with cond:
                                cur = int(kv.get(key, b"0")) + \
                                    int(payload)
                                kv[key] = str(cur).encode()
                                cond.notify_all()
                            resp = str(cur).encode()
                        elif op == "W":
                            ms = int(payload)
                            with cond:
                                ok = cond.wait_for(
                                    lambda: key in kv,
                                    None if ms < 0 else ms / 1000)
                            resp = b"1" if ok else b"0"
                        else:  # D
                            with cond:
                                kv.pop(key, None)
                            resp = b"1"
                        self.wfile.write(
                            f"{len(resp):08d}".encode() + resp)
                        self.wfile.flush()
                    except Exception:
                        return

        socketserver.ThreadingTCPServer.allow_reuse_address = True
        self._srv = socketserver.ThreadingTCPServer(("0.0.0.0", port),
                                                    Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


class _PyStoreClient:
    def __init__(self, host, port, timeout):
        import socket
        import time
        deadline = time.time() + timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=timeout)
                break
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError(f"cannot connect {host}:{port}")
                time.sleep(0.05)
        self._lock = threading.Lock()

    def request(self, op, key, payload=b""):
        import pickle
        with self._lock:
            header = pickle.dumps(
                (op.decode(), key, len(payload))).hex().encode()
            self._sock.sendall(header + b"\n" + payload)
            n = int(self._recv_exact(8))
            resp = self._recv_exact(n)
        if op == b"G":
            return None if resp[:1] == b"\x00" else resp[1:]
        if op == b"A":
            return int(resp)
        if op == b"W":
            return resp == b"1"
        return None

    def _recv_exact(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise OSError("store connection closed")
            buf += chunk
        return buf
