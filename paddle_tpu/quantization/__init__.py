"""``paddle.quantization`` — QAT / PTQ (reference: quant passes +
fake_quantize kernels, ``paddle/fluid/contrib/slim`` hooks and
``phi/kernels/*/fake_quantize*``; SURVEY.md §2.1 "Quant/compression";
reference mount empty, no file:line cites).

TPU-native design:

- ``quant_abs_max`` / ``fake_quant_dequant`` are jnp ops with a
  straight-through-estimator custom VJP — the role the fake_quantize
  CUDA kernels play, but fused by XLA into the surrounding graph.
- QAT wraps layers with ``FakeQuanterWithAbsMax`` (weights: per-channel
  abs-max; activations: EMA abs-max collected while training).
- PTQ inserts observers, calibrates on sample batches, then ``convert``
  produces ``QuantedLinear``: weights stored **int8**, matmul runs
  int8xint8 -> int32 with ``preferred_element_type`` so XLA can use the
  MXU's int8 path, then rescales — the TPU analogue of the reference's
  int8 inference kernels.
"""

from __future__ import annotations

import copy
import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply
from .. import nn

__all__ = ["quant_abs_max_scale", "fake_quant_dequant",
           "FakeQuanterWithAbsMax", "MovingAverageAbsmaxObserver",
           "QuantConfig", "QAT", "PTQ", "QuantedLinear"]


# --------------------------------------------------------------------------
# fake-quant ops (STE)
# --------------------------------------------------------------------------

def quant_abs_max_scale(x, axis=None, eps=1e-8):
    """Per-tensor (axis=None) or per-channel abs-max scale for int8."""
    a = x.jax() if isinstance(x, Tensor) else jnp.asarray(x)
    if axis is None:
        m = jnp.max(jnp.abs(a))
    else:
        red = tuple(i for i in range(a.ndim) if i != axis)
        m = jnp.max(jnp.abs(a), axis=red, keepdims=False)
    return jnp.maximum(m, eps) / 127.0


@jax.custom_vjp
def _fqdq(a, scale):
    q = jnp.clip(jnp.round(a / scale), -127, 127)
    return q * scale


def _fqdq_fwd(a, scale):
    return _fqdq(a, scale), None


def _fqdq_bwd(_, g):
    return g, None  # straight-through estimator


_fqdq.defvjp(_fqdq_fwd, _fqdq_bwd)


def fake_quant_dequant(x, scale=None, axis=None):
    """Quantize to int8 grid and back (training-time simulation) with a
    straight-through gradient."""
    def fn(a):
        s = scale
        if s is None:
            if axis is None:
                m = jnp.max(jnp.abs(a))
            else:
                red = tuple(i for i in range(a.ndim) if i != axis)
                m = jnp.max(jnp.abs(a), axis=red, keepdims=True)
            s = jnp.maximum(m, 1e-8) / 127.0
        else:
            s = jnp.asarray(s)
            if axis is not None and s.ndim == 1:
                shape = [1] * a.ndim
                shape[axis] = s.shape[0]
                s = s.reshape(shape)
        return _fqdq(a, s.astype(a.dtype))
    if isinstance(x, Tensor):
        return apply(fn, x, name="fake_quant_dequant")
    return fn(jnp.asarray(x))


# --------------------------------------------------------------------------
# observers / quanters
# --------------------------------------------------------------------------

class MovingAverageAbsmaxObserver:
    """PTQ/QAT activation observer: EMA of per-tensor abs-max. The EMA
    stays a device scalar (no host sync on the training hot path); it
    is only pulled to a python float at convert() time."""

    def __init__(self, momentum=0.9):
        self.momentum = float(momentum)
        self.absmax = None  # jnp scalar once observed

    def observe(self, x):
        a = x.jax() if isinstance(x, Tensor) else jnp.asarray(x)
        m = jnp.max(jnp.abs(a)).astype(jnp.float32)
        if self.absmax is None:
            self.absmax = m
        else:
            self.absmax = (self.momentum * self.absmax
                           + (1 - self.momentum) * m)
        return x

    @property
    def scale(self):
        """Device scalar scale (use scale_float at convert time)."""
        return jnp.maximum(self.absmax, 1e-8) / 127.0

    @property
    def scale_float(self):
        return max(float(self.absmax), 1e-8) / 127.0


class FakeQuanterWithAbsMax(nn.Layer):
    """QAT quanter: fake-quant with live abs-max (weights) or EMA
    (activations)."""

    def __init__(self, ema=False, momentum=0.9, channel_axis=None):
        super().__init__()
        self._ema = ema
        self._observer = (MovingAverageAbsmaxObserver(momentum)
                          if ema else None)
        self._axis = channel_axis

    def forward(self, x):
        if self._ema:
            if self.training:
                self._observer.observe(x)
            if self._observer.absmax is not None:
                return fake_quant_dequant(x, scale=self._observer.scale)
            return fake_quant_dequant(x)
        return fake_quant_dequant(x, axis=self._axis)


# --------------------------------------------------------------------------
# config + QAT/PTQ drivers
# --------------------------------------------------------------------------

class QuantConfig:
    """Which layer types get quantized, and how."""

    def __init__(self, activation=True, weight=True,
                 weight_channel_axis=1, momentum=0.9):
        self.activation = activation
        self.weight = weight
        self.weight_channel_axis = weight_channel_axis
        self.momentum = momentum
        self._types = {nn.Linear}
        self._overrides = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        self._types.add(layer_type)
        ov = self._overrides.setdefault(layer_type, {})
        if activation is not None:
            ov["activation"] = bool(activation)
        if weight is not None:
            ov["weight"] = bool(weight)
        return self

    def matches(self, layer):
        return type(layer) in self._types

    def activation_for(self, layer):
        return self._overrides.get(type(layer), {}).get(
            "activation", self.activation)

    def weight_for(self, layer):
        return self._overrides.get(type(layer), {}).get(
            "weight", self.weight)


class _QATLinear(nn.Layer):
    """Linear with fake-quant on weight (per-out-channel) and input."""

    def __init__(self, inner, cfg: QuantConfig):
        super().__init__()
        self.inner = inner
        self.cfg = cfg
        self._quant_weight = cfg.weight_for(inner)
        self.act_quanter = (FakeQuanterWithAbsMax(
            ema=True, momentum=cfg.momentum)
            if cfg.activation_for(inner) else None)

    def forward(self, x):
        from ..ops.linalg import matmul
        if self.act_quanter is not None:
            x = self.act_quanter(x)
        w = self.inner.weight
        if self._quant_weight:
            w = fake_quant_dequant(w, axis=self.cfg.weight_channel_axis)
        y = matmul(x, w)
        if self.inner.bias is not None:
            y = y + self.inner.bias
        return y


def _swap_layers(model, predicate, factory):
    """Replace matching sublayers in place; returns count."""
    n = 0
    for name, child in list(model.named_children()):
        if predicate(child):
            setattr(model, name, factory(child))
            n += 1
        else:
            n += _swap_layers(child, predicate, factory)
    return n


class QAT:
    """Quantization-aware training driver."""

    def __init__(self, config: QuantConfig | None = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=True):
        if not inplace:
            model = copy.deepcopy(model)
        n = _swap_layers(model, self.config.matches,
                         lambda l: _QATLinear(l, self.config))
        if n == 0:
            raise ValueError("QAT.quantize: no quantizable layers found")
        return model

    def convert(self, model, inplace=True):
        """Fold fake-quant into real int8 QuantedLinear layers. Layers
        whose config had weight=False keep float weights (they were
        never trained against weight quantization)."""
        if not inplace:
            model = copy.deepcopy(model)

        def factory(q):
            if not q._quant_weight:
                return q.inner
            obs = (q.act_quanter._observer
                   if q.act_quanter is not None else None)
            scale = (obs.scale_float
                     if obs is not None and obs.absmax is not None
                     else None)
            return QuantedLinear.from_linear(
                q.inner, act_scale=scale,
                channel_axis=self.config.weight_channel_axis)
        _swap_layers(model, lambda l: isinstance(l, _QATLinear), factory)
        return model


class _PTQObserved(nn.Layer):
    def __init__(self, inner, cfg):
        super().__init__()
        self.inner = inner
        self.observer = MovingAverageAbsmaxObserver(cfg.momentum)

    def forward(self, x):
        self.observer.observe(x)
        return self.inner(x)


class PTQ:
    """Post-training quantization: observe -> calibrate -> convert."""

    def __init__(self, config: QuantConfig | None = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=True):
        if not inplace:
            model = copy.deepcopy(model)
        n = _swap_layers(model, self.config.matches,
                         lambda l: _PTQObserved(l, self.config))
        if n == 0:
            raise ValueError("PTQ.quantize: no quantizable layers found")
        return model

    def convert(self, model, inplace=True):
        if not inplace:
            model = copy.deepcopy(model)

        def factory(o):
            if not self.config.weight_for(o.inner):
                return o.inner
            use_act = self.config.activation_for(o.inner)
            scale = (o.observer.scale_float
                     if use_act and o.observer.absmax is not None
                     else None)
            return QuantedLinear.from_linear(
                o.inner, act_scale=scale,
                channel_axis=self.config.weight_channel_axis)
        _swap_layers(model, lambda l: isinstance(l, _PTQObserved),
                     factory)
        return model


# --------------------------------------------------------------------------
# converted inference layer
# --------------------------------------------------------------------------

class QuantedLinear(nn.Layer):
    """Int8-weight linear: w stored as int8 + per-out-channel scales;
    the matmul runs int8 x int8 -> int32 on the MXU when the activation
    scale is known, else int8-dequant x float."""

    def __init__(self, w_int8, w_scale, bias=None, act_scale=None,
                 channel_axis=1):
        super().__init__()
        self._w_int8 = jnp.asarray(w_int8, jnp.int8)
        self._w_scale = jnp.asarray(w_scale, jnp.float32)
        self._axis = int(channel_axis)
        self._bias = None if bias is None else jnp.asarray(bias)
        self._act_scale = (None if act_scale is None
                           else float(act_scale))

    @classmethod
    def from_linear(cls, linear, act_scale=None, channel_axis=1):
        w = linear.weight.jax()  # [in, out] (paddle layout)
        scale = quant_abs_max_scale(w, axis=channel_axis)
        bshape = [1, 1]
        bshape[channel_axis] = scale.shape[0]
        q = jnp.clip(jnp.round(w / scale.reshape(bshape)), -127,
                     127).astype(jnp.int8)
        b = None if linear.bias is None else linear.bias.jax()
        return cls(q, scale, b, act_scale, channel_axis)

    @property
    def weight_int8(self):
        return self._w_int8

    def forward(self, x):
        def fn(a):
            # per-OUT-channel scales (axis 1 of [in, out]) factor out of
            # the contraction, enabling the int8 MXU path; per-in-channel
            # scales must be applied before summation -> dequant path
            if self._act_scale is not None and self._axis == 1:
                qa = jnp.clip(jnp.round(a / self._act_scale), -127,
                              127).astype(jnp.int8)
                acc = jax.lax.dot_general(
                    qa, self._w_int8,
                    (((a.ndim - 1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                y = (acc.astype(jnp.float32)
                     * (self._act_scale * self._w_scale)).astype(a.dtype)
            else:
                bshape = [1, 1]
                bshape[self._axis] = self._w_scale.shape[0]
                w = (self._w_int8.astype(jnp.float32)
                     * self._w_scale.reshape(bshape)).astype(a.dtype)
                y = a @ w
            if self._bias is not None:
                y = y + self._bias.astype(y.dtype)
            return y
        if isinstance(x, Tensor):
            return apply(fn, x, name="quanted_linear")
        return fn(jnp.asarray(x))


# --------------------------------------------------------------------------
# quanter registration (reference ``paddle.quantization.quanter``:
# @quanter("MyFakeQuanter") registers a quanter class for explicit
# name-based lookup via get_quanter() — QuantConfig itself is
# layer-type keyed here and does not consult the registry)
# --------------------------------------------------------------------------

_QUANTER_REGISTRY: dict = {}


def quanter(name):
    """Class decorator registering a custom quanter under ``name``
    (resolvable via :func:`get_quanter`)."""
    def deco(cls):
        _QUANTER_REGISTRY[name] = cls
        return cls
    return deco


def get_quanter(name):
    try:
        return _QUANTER_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"no quanter registered under {name!r}; register with "
            f"@quantization.quanter({name!r})") from None


__all__ += ["quanter", "get_quanter"]
