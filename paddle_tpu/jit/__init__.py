from .to_static_api import to_static, StaticFunction, not_to_static, ignore_module
from .save_load import save, load, TranslatedLayer
from .input_spec import InputSpec

__all__ = ["to_static", "StaticFunction", "not_to_static", "save", "load",
           "InputSpec", "TranslatedLayer", "ignore_module"]


def enable_to_static(enable=True):
    """paddle.jit.enable_to_static — global kill-switch: with False every
    StaticFunction call runs its original eager function."""
    StaticFunction._globally_enabled = bool(enable)


def set_verbosity(level=0, also_to_stdout=False):
    """Transform-logging verbosity (dy2static parity): >0 enables DEBUG
    logs from the to_static module logger."""
    import logging
    logging.getLogger("paddle_tpu.jit.to_static_api").setLevel(
        logging.DEBUG if level and int(level) > 0 else logging.WARNING)


def set_code_level(level=100, also_to_stdout=False):
    """dy2static transformed-code logging (upstream prints the rewritten
    source at each transform stage). Captured programs here have no
    rewritten source; this maps to the same transform logger."""
    set_verbosity(1 if level else 0, also_to_stdout)


__all__ += ["enable_to_static", "set_verbosity", "set_code_level"]
