from .to_static_api import to_static, StaticFunction, not_to_static, ignore_module
from .save_load import save, load, TranslatedLayer
from .input_spec import InputSpec

__all__ = ["to_static", "StaticFunction", "not_to_static", "save", "load",
           "InputSpec", "TranslatedLayer", "ignore_module"]
