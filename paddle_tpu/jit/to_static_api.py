"""``paddle.jit.to_static`` — the trace-and-compile path.

Reference role (SURVEY.md §3.5, UNVERIFIED paths): SOT bytecode capture →
PIR program → CINN fusion → InterpreterCore executor. TPU-native design: the
user's imperative function (forward, or a whole train step with
``loss.backward()`` and ``optimizer.step()``) is *functionalized* and handed
to ``jax.jit`` — XLA plays the roles of PIR, CINN, and the executor at once.

How functionalization works (this replaces SOT's bytecode interception):
1. **Discovery pass** — the first call for a given input signature runs
   eagerly under a ``StateTracking`` scope. Every read/write of a
   *persistable* tensor (parameters, buffers, optimizer accumulators, RNG
   key) funnels through ``core.apply`` / ``Tensor.set_data``, so we learn
   exactly which state the function touches.
2. **Pure wrapper** — ``(state_arrays, arg_arrays) -> (new_state, outputs)``
   temporarily rebinds the tracked tensors to tracer arrays, replays the
   user function (the autograd tape runs on tracers, so ``.backward()``
   lowers into the same XLA program), and reads back mutated state.
3. ``jax.jit`` compiles it; python scalars in the signature are baked in as
   constants (they're part of the cache key, like SOT guards).

Graph breaks and guarded specialization (the SOT role): data-dependent
Python control flow on SCALARS (``if loss_improved:``, ``int(idx)``) does
NOT break the graph. Discovery records every scalar concretization; the
trace replays each recorded value as a baked constant and emits the traced
tensor as a *guard output*; every compiled step re-checks the guards on
device results before committing state. A guard mismatch discards that
run, re-runs eagerly (correctness), and re-specializes — distinct branch
patterns each get their own cached executable (SOT/dynamo branch
specialization). Only unguardable concretizations — ``float()``/``item()``
on floats (stale value would change numerics) and bulk host reads
(``.numpy()``) — fall back to eager for the signature, with a warning.

Caveat (documented divergence): ``.grad`` values left un-cleared across a
compiled call are not synchronized back — the standard step pattern
(backward → optimizer.step → clear_grad inside the function) is fully
supported; reading ``.grad`` after a compiled step warns.
"""

from __future__ import annotations

import functools
import logging
import time as _time
import warnings
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import (GraphBreak, ObservedFloat, Tensor,
                              StateTracking, guardable_concretization,
                              record_concretizations, replay_concretizations,
                              track_state)

__all__ = ["to_static", "StaticFunction", "not_to_static", "ignore_module"]

logger = logging.getLogger(__name__)


def not_to_static(fn):
    """Mark a function to never be compiled (paddle.jit.not_to_static)."""
    fn._paddle_tpu_not_to_static = True
    return fn


def ignore_module(modules):
    """Accepted for API parity (SOT concept); no-op."""
    return None


# ---- pytree helpers over plain python containers --------------------------

def _tree_flatten(obj, leaves):
    if isinstance(obj, Tensor):
        leaves.append(obj)
        return ("T", len(leaves) - 1)
    if isinstance(obj, (list, tuple)):
        return ("tuple" if isinstance(obj, tuple) else "list",
                [_tree_flatten(o, leaves) for o in obj])
    if isinstance(obj, dict):
        return ("dict", {k: _tree_flatten(v, leaves)
                         for k, v in sorted(obj.items())})
    leaves.append(obj)
    return ("L", len(leaves) - 1)


def _tree_unflatten(spec, leaves):
    kind = spec[0]
    if kind in ("T", "L"):
        return leaves[spec[1]]
    if kind == "dict":
        return {k: _tree_unflatten(v, leaves) for k, v in spec[1].items()}
    seq = [_tree_unflatten(s, leaves) for s in spec[1]]
    return tuple(seq) if kind == "tuple" else seq


def _signature_key(leaves):
    parts = []
    for leaf in leaves:
        if isinstance(leaf, Tensor):
            parts.append(f"T{tuple(leaf._data.shape)}:{leaf._data.dtype}"
                         f":{leaf.stop_gradient}")
        else:
            try:
                parts.append(f"V{type(leaf).__name__}:{leaf!r}")
            except Exception:
                parts.append(f"V{type(leaf).__name__}:?")
    return "|".join(parts)


class _CompiledGraph:
    __slots__ = ("state_list", "jitted", "pure_fn", "guard_log")

    def __init__(self, state_list, jitted, pure_fn, guard_log):
        self.state_list = state_list
        self.jitted = jitted
        self.pure_fn = pure_fn
        self.guard_log = guard_log   # [(kind, value)] from discovery


class _SigEntry:
    """Specializations for one input signature, keyed by the recorded
    concretization log (the branch-decision vector)."""

    __slots__ = ("by_key", "latest_key", "mispredicts")

    def __init__(self):
        self.by_key: dict = {}
        self.latest_key = None
        self.mispredicts = 0


class _GuardMismatch(Exception):
    pass


#: CONSECUTIVE mispredict budget per signature before giving up on
#: compilation (pathologically alternating branches); any successful
#: guard-hit compiled run resets the counter, so occasional flips over a
#: long training run never deoptimize
_MAX_MISPREDICTS = 16

_TRACE_ERRORS = (jax.errors.TracerBoolConversionError,
                 jax.errors.ConcretizationTypeError,
                 jax.errors.TracerArrayConversionError,
                 jax.errors.TracerIntegerConversionError,
                 GraphBreak)


class StaticFunction:
    def __init__(self, function: Callable, input_spec=None,
                 build_strategy=None, backend=None, full_graph=False,
                 donate_state: bool = False):
        functools.update_wrapper(self, function)
        self._fn = function
        self._input_spec = input_spec
        self._graphs: dict[str, _SigEntry] = {}
        self._fallback_sigs: set[str] = set()
        self._instance = None
        self._donate = donate_state
        self._enabled = not getattr(function,
                                    "_paddle_tpu_not_to_static", False)
        # run-mode telemetry (hapi fit attribution + tests): how many
        # calls executed as the compiled program vs python (discovery
        # runs and eager fallbacks both count as eager host work)
        self.n_compiled_runs = 0
        self.n_eager_runs = 0
        # cumulative wall seconds inside _discover (eager discovery run
        # + trace/graph construction) — the host-visible recompile cost
        # the goodput ledger books against the "recompile" category
        self.compile_seconds = 0.0

    # descriptor protocol so @to_static works on Layer methods; the bound
    # copy is cached per instance (each instance has its own parameters ⇒
    # its own discovered state and compile cache)
    def __get__(self, instance, owner):
        if instance is None:
            return self
        cache_name = f"__static_fn_{id(self)}"
        bound = instance.__dict__.get(cache_name)
        if bound is None:
            bound = StaticFunction(self._fn, self._input_spec,
                                   donate_state=self._donate)
            bound._instance = instance
            instance.__dict__[cache_name] = bound
        return bound

    @property
    def function(self):
        return self._fn

    def rollback(self):
        return self._fn

    def _call_fn(self, *args, **kwargs):
        if self._instance is not None:
            return self._fn(self._instance, *args, **kwargs)
        return self._fn(*args, **kwargs)

    #: flipped by paddle.jit.enable_to_static(False): every StaticFunction
    #: runs its original eager function
    _globally_enabled = True

    def __call__(self, *args, **kwargs):
        if not self._enabled or not StaticFunction._globally_enabled:
            self.n_eager_runs += 1
            return self._call_fn(*args, **kwargs)
        leaves: list = []
        spec = _tree_flatten((args, kwargs), leaves)
        sig = _signature_key(leaves)
        if sig in self._fallback_sigs:
            self.n_eager_runs += 1
            return self._call_segmented(sig, args, kwargs)
        entry = self._graphs.get(sig)
        if entry is None or entry.latest_key is None:
            self.n_eager_runs += 1
            return self._discover(sig, spec, leaves, args, kwargs)
        graph = entry.by_key[entry.latest_key]
        try:
            result = self._run_compiled(graph, leaves)
            self.n_compiled_runs += 1
            entry.mispredicts = 0   # guard-hit run: healthy specialization
            return result
        except _GuardMismatch:
            entry.mispredicts += 1
            if entry.mispredicts > _MAX_MISPREDICTS:
                warnings.warn(
                    f"to_static: {getattr(self._fn, '__name__', '?')} "
                    f"re-specialized more than {_MAX_MISPREDICTS} times "
                    "(unstable data-dependent branches); falling back to "
                    "eager for this signature")
                self._fallback_sigs.add(sig)
                self._graphs.pop(sig, None)
                self.n_eager_runs += 1
                return self._call_fn(*args, **kwargs)
            # the discarded run committed nothing; re-run eagerly (correct
            # for the new branch pattern) and re-specialize
            self.n_eager_runs += 1
            return self._discover(sig, spec, leaves, args, kwargs)
        except _TRACE_ERRORS as e:
            warnings.warn(
                f"to_static: graph break in "
                f"{getattr(self._fn, '__name__', '?')} "
                f"(data-dependent control flow: {e}); falling back to eager "
                "for this signature")
            self._fallback_sigs.add(sig)
            self._graphs.pop(sig, None)
            self.n_eager_runs += 1
            return self._call_fn(*args, **kwargs)

    # ---- broken signatures: compile AROUND the break ---------------------

    def _call_segmented(self, sig, args, kwargs):
        """SOT-style subgraph compilation for a signature with a genuine
        graph break (SURVEY.md §3.5): the function runs ONCE, but op
        dispatches are recorded lazily and flushed as jit-compiled
        SEGMENTS at each point Python actually needs a value (the
        ``float(loss)`` branch, a ``.numpy()`` read). Compiled prefix,
        eager break, compiled suffix — instead of dropping the whole
        signature to per-op eager dispatch. ``_segment_stats`` holds
        (segments_executed, ops_recorded) from the last call (the
        compile-around-break probe used by tests)."""
        from ..framework import segment as _segment
        if sig in getattr(self, "_eager_sigs", set()):
            return self._call_fn(*args, **kwargs)
        rec = _segment.SegmentRecorder()
        try:
            with _segment.segment_mode(rec):
                out = self._call_fn(*args, **kwargs)
        except ValueError as e:
            if "__jax_array__" not in str(e):
                raise
            # the function uses an op that consumes raw arrays outside
            # the apply() funnel — placeholders cannot flow through it
            # (jax 0.9 rejects coercion). segment_mode already rolled
            # back every state mutation, so a plain-eager retry is safe;
            # remember the signature so later calls skip segments
            if not hasattr(self, "_eager_sigs"):
                self._eager_sigs = set()
            self._eager_sigs.add(sig)
            warnings.warn(
                f"to_static: {getattr(self._fn, '__name__', '?')} uses "
                "an op that cannot carry lazy segments; running this "
                "broken signature fully eagerly instead of "
                "compile-around-break")
            return self._call_fn(*args, **kwargs)
        # normalize ESCAPED placeholders: the exit flush made every
        # SegValue concrete, but tensors handed back to the caller must
        # carry real arrays — jax 0.9 rejects __jax_array__ coercion, so
        # a leftover SegValue would crash the first comparison op done
        # on a returned tensor outside segment mode
        leaves: list = []
        _tree_flatten(out, leaves)
        for t in leaves:
            if isinstance(t, Tensor) and \
                    isinstance(t._data, _segment.SegValue):
                t._data = t._data.force()
        self._segment_stats = (rec.flushes, rec.ops_recorded)
        return out

    # ---- pass 1: eager run with state tracking --------------------------

    def _discover(self, sig, spec, leaves, args, kwargs):
        _t0 = _time.perf_counter()
        try:
            return self._discover_inner(sig, spec, leaves, args, kwargs)
        finally:
            self.compile_seconds += _time.perf_counter() - _t0

    def _discover_inner(self, sig, spec, leaves, args, kwargs):
        tracking = StateTracking()
        log: list = []
        with track_state(tracking), record_concretizations(log):
            outputs = self._call_fn(*args, **kwargs)
        # 3-tuple log entries are OBSERVED float reads (SOT partial
        # capture): when only observed (logged/formatted/returned) they
        # ride the compiled program as extra outputs instead of breaking
        # the graph; a misused one (branched on / fed back into tensors)
        # is a genuine break
        unguardable = [(e[0], e[1]) for e in log
                       if not guardable_concretization(e[0], e[1])
                       and not (len(e) == 3 and not e[2].misused)]
        if unguardable:
            kinds = sorted({k for k, _ in unguardable})
            warnings.warn(
                f"to_static: graph break in "
                f"{getattr(self._fn, '__name__', '?')}: {kinds} "
                "concretization(s) pull device values into python in a "
                "way that can change the computation (unguardable — a "
                "replayed stale value would change numerics); running "
                "eagerly for this signature. Observation-only .item() "
                "reads (logging, returning) stay compiled; prefer "
                ".item() over float() inside compiled functions.")
            self._fallback_sigs.add(sig)
            self._graphs.pop(sig, None)
            return outputs
        state, seen = [], set()
        for d in (tracking.read, tracking.written):
            for tid, t in d.items():
                if tid not in seen:
                    seen.add(tid)
                    state.append(t)
        entry = self._graphs.get(sig)
        if entry is None:
            entry = self._graphs[sig] = _SigEntry()
        # specialization key = the branch-decision vector. Observed float
        # VALUES move every step and decide nothing — key them by site
        # only, or every call would re-specialize
        key = tuple((e[0], e[1]) if len(e) == 2 else (e[0], "<obs>")
                    for e in log)
        if key not in entry.by_key:
            pure_fn = self._make_pure_fn(spec, leaves, state, log)
            # guards require the ability to DISCARD a run on mismatch, so
            # donation (which invalidates the input buffers) is only safe
            # on guard-free graphs
            donate = (0,) if self._donate and not log else ()
            jitted = jax.jit(pure_fn, donate_argnums=donate)
            entry.by_key[key] = _CompiledGraph(state, jitted, pure_fn, log)
        entry.latest_key = key
        return outputs

    # ---- the pure function ----------------------------------------------

    def _make_pure_fn(self, spec, proto_leaves, state_list, guard_log):
        donate = self._donate and not guard_log
        fn = self._call_fn
        # leaf prototypes: for tensors remember stop_gradient; for python
        # values bake in the discovery-call value (sig key guards equality)
        protos = [(True, leaf.stop_gradient) if isinstance(leaf, Tensor)
                  else (False, leaf) for leaf in proto_leaves]
        holder = {}

        def pure_fn(state_arrays, arg_arrays):
            # _grad_value (not .grad): internal save/restore must neither
            # trigger nor clear the stale-grad warning
            originals = [(t, t._data, t._node, t._grad_value)
                         for t in state_list]
            guards: list = []
            try:
                for t, a in zip(state_list, state_arrays):
                    t._data = a
                    t._node = None
                leaves2, ai = [], 0
                for is_tensor, v in protos:
                    if is_tensor:
                        leaves2.append(Tensor(arg_arrays[ai],
                                              stop_gradient=v))
                        ai += 1
                    else:
                        leaves2.append(v)
                built_args, built_kwargs = _tree_unflatten(spec, leaves2)
                with replay_concretizations(guard_log, guards):
                    outputs = fn(*built_args, **built_kwargs)
                out_leaves: list = []
                out_spec = _tree_flatten(outputs, out_leaves)
                # observed floats in the return value: emit the TRACED
                # scalar instead of baking the stale python value, and
                # remember to convert back to float per call (the "eager
                # read" of the partial-capture scheme)
                obs_ret = []
                out_list = []
                for i, o in enumerate(out_leaves):
                    if isinstance(o, Tensor):
                        out_list.append(o._data)
                    elif isinstance(o, ObservedFloat) and \
                            o._traced is not None:
                        out_list.append(o._traced)
                        obs_ret.append(i)
                    else:
                        out_list.append(o)
                out_arrays = tuple(out_list)
                holder["obs_ret"] = obs_ret
                holder["out_spec"] = out_spec
                holder["out_is_tensor"] = [isinstance(o, Tensor)
                                           for o in out_leaves]
                # only state actually REASSIGNED during the trace is an
                # output (identity check against the input tracer):
                # returning untouched params would force fresh device
                # buffers for the whole model every step
                if donate:
                    # donated input buffers are invalidated unless
                    # aliased to an output — must return full state
                    changed = list(range(len(state_list)))
                else:
                    changed = [i for i, (t, a) in
                               enumerate(zip(state_list, state_arrays))
                               if t._data is not a]
                holder["changed"] = changed
                new_state = tuple(state_list[i]._data for i in changed)
                # only tracer-backed concretizations become guards
                # (constants were verified equal at trace time). One
                # stacked int64 vector => ONE host sync per step at check
                # time, however many guards there are.
                # the staged dtype must match what the device actually
                # stores: with x64 disabled jnp silently downcasts int64
                # to int32, so guard_expect must wrap identically or an
                # out-of-int32-range guard value would mismatch forever
                # (permanent eager fallback for the signature)
                import jax as _jax
                gdt = jnp.int64 if _jax.config.jax_enable_x64 \
                    else jnp.int32
                if guards:
                    guard_vec = jnp.stack(
                        [jnp.asarray(g).astype(gdt).reshape(())
                         for g, _, _ in guards])
                else:
                    guard_vec = ()
                holder["guard_expect"] = np.asarray(
                    [int(v) for _, _, v in guards],
                    dtype=np.int64).astype(np.int64 if gdt == jnp.int64
                                           else np.int32)
                return new_state, out_arrays, guard_vec
            finally:
                for t, d, n, g in originals:
                    t._data = d
                    t._node = n
                    t._grad_value = g

        pure_fn._holder = holder
        return pure_fn

    def _run_compiled(self, graph: _CompiledGraph, leaves):
        arg_arrays = tuple(leaf._data for leaf in leaves
                           if isinstance(leaf, Tensor))
        state_arrays = tuple(t._data for t in graph.state_list)
        new_state, out_arrays, guard_vec = graph.jitted(state_arrays,
                                                        arg_arrays)
        holder = graph.pure_fn._holder
        # verify the guarded branch decisions BEFORE committing state —
        # a mismatched run must leave no trace (its outputs followed the
        # wrong branch). Single stacked vector: one host sync.
        expect = holder.get("guard_expect")
        if expect is not None and expect.size:
            if not np.array_equal(np.asarray(guard_vec), expect):
                raise _GuardMismatch()
        for i, a in zip(holder["changed"], new_state):
            graph.state_list[i].set_data(a)
            if not graph.state_list[i]._stop_gradient:
                graph.state_list[i]._grad_stale = True
        obs = set(holder.get("obs_ret", ()))
        out_leaves = [Tensor(a) if is_t else
                      (float(a) if i in obs else a)
                      for i, (a, is_t) in enumerate(
                          zip(out_arrays, holder["out_is_tensor"]))]
        return _tree_unflatten(holder["out_spec"], out_leaves)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=False, donate_state=False,
              **kwargs):
    """Decorator/wrapper converting an imperative function or a Layer into a
    compiled whole-program (paddle.jit.to_static parity).

    ``donate_state=True`` donates the captured persistable state buffers
    (params, optimizer slots) to the compiled program — XLA aliases the
    updated state into the input buffers instead of allocating a fresh
    copy per step. Only guard-free graphs donate (a guarded run must be
    discardable); the flag is a no-op otherwise."""

    def decorate(fn):
        from ..nn.layer.layers import Layer
        if isinstance(fn, Layer):
            static_fwd = StaticFunction(type(fn).forward, input_spec,
                                        donate_state=donate_state)
            static_fwd._instance = fn
            fn.forward = static_fwd
            return fn
        return StaticFunction(fn, input_spec, build_strategy, backend,
                              full_graph, donate_state=donate_state)
    if function is not None:
        return decorate(function)
    return decorate
