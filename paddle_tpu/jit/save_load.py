"""``paddle.jit.save/load`` — inference-model export
(python/paddle/jit/api.py parity, UNVERIFIED; pdmodel/pdiparams format in
spirit).

TPU-native format: instead of a ProgramDesc protobuf, we export the traced
function as **StableHLO text** (the portable XLA IR — the role pdmodel plays
for Paddle Inference) plus a pickled state dict. ``load`` returns a
``TranslatedLayer`` that executes the saved state dict through the original
python program when available, or pure StableHLO via jax when not."""

from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.io import save as _save_obj, load as _load_obj

__all__ = ["save", "load", "TranslatedLayer"]


def save(layer, path, input_spec=None, **configs):
    """Export layer (or function) + parameters for inference/serving."""
    from ..nn.layer.layers import Layer
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    meta = {"format": "paddle_tpu.stablehlo.v1"}
    if isinstance(layer, Layer):
        _save_obj(layer.state_dict(), path + ".pdiparams")
        meta["type"] = "layer"
        meta["class"] = type(layer).__name__
    else:
        # plain function: no parameters, but the export artifacts
        # below still make it a loadable inference model
        # (static.save_inference_model builds on this)
        _save_obj({}, path + ".pdiparams")
        meta["type"] = "function"
    # export stablehlo if an input_spec is given
    if input_spec is not None:
        arrays = []
        shape_strs = []
        has_dyn = False
        for i, spec in enumerate(input_spec):
            shape = tuple(1 if s in (-1, None) else s
                          for s in spec.shape)
            arrays.append(jnp.zeros(shape, spec.dtype))
            parts = []
            for j, sdim in enumerate(spec.shape):
                if sdim in (-1, None):
                    parts.append(f"d{i}_{j}")
                    has_dyn = True
                else:
                    parts.append("_")
            shape_strs.append(", ".join(parts) if parts else "")

        def fwd(*xs):
            outs = layer(*[Tensor(x) for x in xs])
            if isinstance(outs, (list, tuple)):
                return tuple(o._data for o in outs)
            return outs._data
        try:
            lowered = jax.jit(fwd).lower(*arrays)
            with open(path + ".pdmodel", "w") as f:
                f.write(lowered.as_text())
            meta["stablehlo"] = True
            meta["input_shapes"] = [tuple(a.shape) for a in arrays]
            meta["input_dtypes"] = [str(a.dtype) for a in arrays]
        except Exception as e:  # export is best-effort
            meta["stablehlo"] = False
            meta["export_error"] = str(e)
        # serialized jax.export artifact: the executable pdmodel
        # (runs without the python class — the inference engine's
        # real load format; .pdmodel text is for inspection).
        # InputSpec dims of -1/None export as symbolic dims so the
        # artifact serves any batch size.
        try:
            from jax import export as jexport
            spec_args = (jexport.symbolic_args_specs(arrays,
                                                     shape_strs)
                         if has_dyn else arrays)
            try:
                # multi-platform so the artifact serves on either
                # a CPU dev box or a TPU host
                exp = jexport.export(
                    jax.jit(fwd),
                    platforms=("cpu", "tpu"))(*spec_args)
            except Exception:
                exp = jexport.export(jax.jit(fwd))(*spec_args)
            with open(path + ".pdexported", "wb") as f:
                f.write(bytes(exp.serialize()))
            meta["exported"] = True
        except Exception as e:
            meta["exported"] = False
            meta["exported_error"] = str(e)
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer:
    """Loaded inference artifact. Holds the state dict; if the original
    layer class is supplied (``load(path, layer=...)`` or via program()),
    runs it; otherwise exposes the raw state dict."""

    def __init__(self, state_dict, meta, layer=None, exported=None):
        self._state_dict = state_dict
        self._meta = meta
        self._layer = layer
        self._exported = exported  # jax.export.Exported (class-free path)
        if layer is not None:
            layer.set_state_dict(state_dict)
            layer.eval()

    def state_dict(self):
        return self._state_dict

    def __call__(self, *args, **kwargs):
        if self._layer is not None:
            return self._layer(*args, **kwargs)
        if self._exported is not None:
            if kwargs:
                raise TypeError(
                    "TranslatedLayer loaded from a serialized export "
                    "takes positional inputs only (keyword arguments "
                    f"were baked in at save time): got {list(kwargs)}")
            xs = [a.jax() if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in args]
            out = self._exported.call(*xs)
            if isinstance(out, (list, tuple)):
                return tuple(Tensor(o) for o in out)
            return Tensor(out)
        raise RuntimeError(
            "TranslatedLayer loaded without a layer object or exported "
            "artifact; pass `layer=` to paddle_tpu.jit.load or use "
            ".state_dict()")

    def eval(self):
        if self._layer is not None:
            self._layer.eval()
        return self

    def train(self):
        if self._layer is not None:
            self._layer.train()
        return self


def load(path, layer=None, **configs):
    state = _load_obj(path + ".pdiparams")
    meta = {}
    if os.path.exists(path + ".pdmeta"):
        with open(path + ".pdmeta", "rb") as f:
            meta = pickle.load(f)
    exported = None
    if layer is None and os.path.exists(path + ".pdexported"):
        try:
            from jax import export as jexport
            with open(path + ".pdexported", "rb") as f:
                exported = jexport.deserialize(bytearray(f.read()))
        except Exception as e:
            import warnings
            warnings.warn(
                f"{path}.pdexported exists but could not be "
                f"deserialized ({type(e).__name__}: {e}); the loaded "
                f"model is state-dict-only", RuntimeWarning)
            exported = None
    return TranslatedLayer(state, meta, layer, exported)
