"""``paddle.jit.save/load`` — inference-model export
(python/paddle/jit/api.py parity, UNVERIFIED; pdmodel/pdiparams format in
spirit).

TPU-native format: instead of a ProgramDesc protobuf, we export the traced
function as **StableHLO text** (the portable XLA IR — the role pdmodel plays
for Paddle Inference) plus a pickled state dict. ``load`` returns a
``TranslatedLayer`` that executes the saved state dict through the original
python program when available, or pure StableHLO via jax when not."""

from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.io import save as _save_obj, load as _load_obj

__all__ = ["save", "load", "TranslatedLayer"]


def save(layer, path, input_spec=None, **configs):
    """Export layer (or function) + parameters for inference/serving."""
    from ..nn.layer.layers import Layer
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    meta = {"format": "paddle_tpu.stablehlo.v1"}
    if isinstance(layer, Layer):
        _save_obj(layer.state_dict(), path + ".pdiparams")
        meta["type"] = "layer"
        meta["class"] = type(layer).__name__
        # export stablehlo if an input_spec is given
        if input_spec is not None:
            arrays = []
            for spec in input_spec:
                shape = tuple(1 if s in (-1, None) else s
                              for s in spec.shape)
                arrays.append(jnp.zeros(shape, spec.dtype))

            def fwd(*xs):
                outs = layer(*[Tensor(x) for x in xs])
                if isinstance(outs, (list, tuple)):
                    return tuple(o._data for o in outs)
                return outs._data
            try:
                lowered = jax.jit(fwd).lower(*arrays)
                with open(path + ".pdmodel", "w") as f:
                    f.write(lowered.as_text())
                meta["stablehlo"] = True
                meta["input_shapes"] = [tuple(a.shape) for a in arrays]
                meta["input_dtypes"] = [str(a.dtype) for a in arrays]
            except Exception as e:  # export is best-effort
                meta["stablehlo"] = False
                meta["export_error"] = str(e)
    else:
        meta["type"] = "function"
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer:
    """Loaded inference artifact. Holds the state dict; if the original
    layer class is supplied (``load(path, layer=...)`` or via program()),
    runs it; otherwise exposes the raw state dict."""

    def __init__(self, state_dict, meta, layer=None):
        self._state_dict = state_dict
        self._meta = meta
        self._layer = layer
        if layer is not None:
            layer.set_state_dict(state_dict)
            layer.eval()

    def state_dict(self):
        return self._state_dict

    def __call__(self, *args, **kwargs):
        if self._layer is None:
            raise RuntimeError(
                "TranslatedLayer loaded without a layer object; pass "
                "`layer=` to paddle_tpu.jit.load or use .state_dict()")
        return self._layer(*args, **kwargs)

    def eval(self):
        if self._layer is not None:
            self._layer.eval()
        return self

    def train(self):
        if self._layer is not None:
            self._layer.train()
        return self


def load(path, layer=None, **configs):
    state = _load_obj(path + ".pdiparams")
    meta = {}
    if os.path.exists(path + ".pdmeta"):
        with open(path + ".pdmeta", "rb") as f:
            meta = pickle.load(f)
    return TranslatedLayer(state, meta, layer)
