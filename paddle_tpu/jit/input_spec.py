"""``paddle.static.InputSpec`` equivalent."""

from __future__ import annotations

from ..framework.core import to_jax_dtype

__all__ = ["InputSpec"]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = to_jax_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, " \
               f"dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)
