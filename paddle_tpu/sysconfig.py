"""paddle.sysconfig — install path introspection (upstream
``python/paddle/sysconfig.py``, UNVERIFIED)."""

from __future__ import annotations

import os


def get_include():
    """Directory of native headers (the C runtime core's sources double as
    the public headers — there is no generated libpaddle on TPU)."""
    return os.path.join(os.path.dirname(__file__), "native", "src")


def get_lib():
    return os.path.join(os.path.dirname(__file__), "native")


__all__ = ["get_include", "get_lib"]
