"""``paddle.distribution`` — probability distributions
(python/paddle/distribution/ parity, UNVERIFIED). Thin wrappers over jnp
with Tensor in/out."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework import random as framework_random
from ..ops.common import as_tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Dirichlet", "Exponential", "Gamma", "Gumbel", "Laplace",
           "LogNormal", "Multinomial", "Poisson", "kl_divergence"]


def _key():
    return framework_random.default_generator.next_key()


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._data))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = as_tensor(loc, "float32")
        self.scale = as_tensor(scale, "float32")

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return Tensor(jnp.square(self.scale._data))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape))
        z = jax.random.normal(_key(), shape)
        return Tensor(self.loc._data + self.scale._data * z)

    def rsample(self, shape=()):
        """Reparameterized sample: gradients flow to loc/scale (the tape
        records loc + scale * eps via ``apply``)."""
        from ..framework.core import apply
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape))
        z = jax.random.normal(_key(), shape)
        return apply(lambda l, s: l + s * z, self.loc, self.scale,
                     name="normal_rsample")

    def log_prob(self, value):
        v = as_tensor(value)._data
        var = jnp.square(self.scale._data)
        return Tensor(-jnp.square(v - self.loc._data) / (2 * var)
                      - jnp.log(self.scale._data)
                      - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale._data))

    def kl_divergence(self, other):
        var_ratio = jnp.square(self.scale._data / other.scale._data)
        t1 = jnp.square((self.loc._data - other.loc._data)
                        / other.scale._data)
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = as_tensor(low, "float32")
        self.high = as_tensor(high, "float32")

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.low._data.shape, self.high._data.shape))
        u = jax.random.uniform(_key(), shape)
        return Tensor(self.low._data + (self.high._data - self.low._data)
                      * u)

    def log_prob(self, value):
        v = as_tensor(value)._data
        inside = (v >= self.low._data) & (v < self.high._data)
        lp = -jnp.log(self.high._data - self.low._data)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high._data - self.low._data))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = as_tensor(logits, "float32")

    def sample(self, shape=()):
        out = jax.random.categorical(_key(), self.logits._data,
                                     shape=tuple(shape) +
                                     self.logits._data.shape[:-1])
        return Tensor(out.astype(jnp.int64))

    def probs(self, value=None):
        p = jax.nn.softmax(self.logits._data, -1)
        if value is None:
            return Tensor(p)
        v = as_tensor(value)._data.astype(jnp.int32)
        return Tensor(jnp.take_along_axis(p, v[..., None], -1)[..., 0])

    def log_prob(self, value):
        lp = jax.nn.log_softmax(self.logits._data, -1)
        v = as_tensor(value)._data.astype(jnp.int32)
        return Tensor(jnp.take_along_axis(lp, v[..., None], -1)[..., 0])

    def entropy(self):
        lp = jax.nn.log_softmax(self.logits._data, -1)
        return Tensor(-jnp.sum(jnp.exp(lp) * lp, -1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = as_tensor(probs, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.probs_._data.shape
        return Tensor(jax.random.bernoulli(
            _key(), self.probs_._data, shape).astype(jnp.float32))

    def log_prob(self, value):
        v = as_tensor(value)._data
        p = jnp.clip(self.probs_._data, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs_._data, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = as_tensor(alpha, "float32")
        self.beta = as_tensor(beta, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.alpha._data.shape, self.beta._data.shape)
        return Tensor(jax.random.beta(_key(), self.alpha._data,
                                      self.beta._data, shape))

    def log_prob(self, value):
        v = as_tensor(value)._data
        a, b = self.alpha._data, self.beta._data
        lbeta = (jax.scipy.special.gammaln(a)
                 + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                      - lbeta)


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = as_tensor(concentration, "float32")

    def sample(self, shape=()):
        return Tensor(jax.random.dirichlet(_key(),
                                           self.concentration._data,
                                           tuple(shape)))

    def log_prob(self, value):
        v = as_tensor(value)._data
        c = self.concentration._data
        lnB = jnp.sum(jax.scipy.special.gammaln(c), -1) - \
            jax.scipy.special.gammaln(jnp.sum(c, -1))
        return Tensor(jnp.sum((c - 1) * jnp.log(v), -1) - lnB)


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = as_tensor(rate, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.rate._data.shape
        return Tensor(jax.random.exponential(_key(), shape)
                      / self.rate._data)

    def log_prob(self, value):
        v = as_tensor(value)._data
        return Tensor(jnp.log(self.rate._data) - self.rate._data * v)


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = as_tensor(concentration, "float32")
        self.rate = as_tensor(rate, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.concentration._data.shape
        return Tensor(jax.random.gamma(_key(), self.concentration._data,
                                       shape) / self.rate._data)

    def log_prob(self, value):
        v = as_tensor(value)._data
        a, b = self.concentration._data, self.rate._data
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - jax.scipy.special.gammaln(a))


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = as_tensor(loc, "float32")
        self.scale = as_tensor(scale, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.loc._data.shape
        return Tensor(self.loc._data + self.scale._data *
                      jax.random.gumbel(_key(), shape))

    def log_prob(self, value):
        z = (as_tensor(value)._data - self.loc._data) / self.scale._data
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale._data))


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = as_tensor(loc, "float32")
        self.scale = as_tensor(scale, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.loc._data.shape
        return Tensor(self.loc._data + self.scale._data *
                      jax.random.laplace(_key(), shape))

    def log_prob(self, value):
        v = as_tensor(value)._data
        return Tensor(-jnp.abs(v - self.loc._data) / self.scale._data
                      - jnp.log(2 * self.scale._data))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.loc = as_tensor(loc, "float32")
        self.scale = as_tensor(scale, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.loc._data.shape
        z = jax.random.normal(_key(), shape)
        return Tensor(jnp.exp(self.loc._data + self.scale._data * z))

    def log_prob(self, value):
        v = as_tensor(value)._data
        lv = jnp.log(v)
        var = jnp.square(self.scale._data)
        return Tensor(-jnp.square(lv - self.loc._data) / (2 * var)
                      - lv - jnp.log(self.scale._data)
                      - 0.5 * math.log(2 * math.pi))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_ = as_tensor(probs, "float32")

    def sample(self, shape=()):
        logits = jnp.log(jnp.maximum(self.probs_._data, 1e-38))
        draws = jax.random.categorical(
            _key(), logits, shape=tuple(shape) + (self.total_count,)
            + logits.shape[:-1])
        k = logits.shape[-1]
        onehot = jax.nn.one_hot(draws, k)
        axis = len(tuple(shape))
        return Tensor(jnp.sum(onehot, axis=axis))

    def log_prob(self, value):
        v = as_tensor(value)._data
        p = jnp.maximum(self.probs_._data, 1e-38)
        logfact = jax.scipy.special.gammaln(v.sum(-1) + 1) - \
            jnp.sum(jax.scipy.special.gammaln(v + 1), -1)
        return Tensor(logfact + jnp.sum(v * jnp.log(p), -1))


class Poisson(Distribution):
    def __init__(self, rate):
        self.rate = as_tensor(rate, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.rate._data.shape
        return Tensor(jax.random.poisson(_key(), self.rate._data,
                                         shape).astype(jnp.float32))

    def log_prob(self, value):
        v = as_tensor(value)._data
        r = self.rate._data
        return Tensor(v * jnp.log(r) - r
                      - jax.scipy.special.gammaln(v + 1))


def kl_divergence(p, q):
    # explicit registrations (register_kl) first, walking the MROs the way
    # upstream's dispatch does; then the distribution's own method
    for tp in type(p).__mro__:
        for tq in type(q).__mro__:
            fn = _KL_REGISTRY.get((tp, tq))
            if fn is not None:
                return fn(p, q)
    if hasattr(p, "kl_divergence"):
        return p.kl_divergence(q)
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")


from . import transform  # noqa: E402
from .transform import (Transform, AffineTransform, ExpTransform,  # noqa
                        PowerTransform, SigmoidTransform, TanhTransform,
                        AbsTransform, SoftmaxTransform, ChainTransform,
                        IndependentTransform, ReshapeTransform,
                        StickBreakingTransform)

__all__ += ["Geometric", "Cauchy", "Chi2", "StudentT", "Binomial",
            "ContinuousBernoulli", "MultivariateNormal", "Independent",
            "TransformedDistribution", "register_kl", "transform",
            "Transform", "AffineTransform", "ExpTransform",
            "PowerTransform", "SigmoidTransform", "TanhTransform",
            "AbsTransform", "SoftmaxTransform", "ChainTransform",
            "IndependentTransform", "ReshapeTransform",
            "StickBreakingTransform"]


class Geometric(Distribution):
    """Number of failures before the first success, supported on 0, 1, ...
    (upstream paddle.distribution.Geometric convention)."""

    def __init__(self, probs):
        self.probs = as_tensor(probs, "float32")

    @property
    def mean(self):
        p = self.probs._data
        return Tensor((1.0 - p) / p)

    @property
    def variance(self):
        p = self.probs._data
        return Tensor((1.0 - p) / jnp.square(p))

    def sample(self, shape=()):
        p = self.probs._data
        shape = tuple(shape) + p.shape
        u = jax.random.uniform(_key(), shape, minval=1e-7, maxval=1.0)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-p)))

    def log_prob(self, value):
        v = as_tensor(value)._data
        p = self.probs._data
        return Tensor(v * jnp.log1p(-p) + jnp.log(p))

    def entropy(self):
        p = self.probs._data
        q = 1.0 - p
        return Tensor(-(q * jnp.log(q) + p * jnp.log(p)) / p)


class Cauchy(Distribution):
    def __init__(self, loc, scale):
        self.loc = as_tensor(loc, "float32")
        self.scale = as_tensor(scale, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape))
        z = jax.random.cauchy(_key(), shape)
        return Tensor(self.loc._data + self.scale._data * z)

    def log_prob(self, value):
        v = as_tensor(value)._data
        z = (v - self.loc._data) / self.scale._data
        return Tensor(-math.log(math.pi) - jnp.log(self.scale._data)
                      - jnp.log1p(jnp.square(z)))

    def entropy(self):
        return Tensor(math.log(4 * math.pi) + jnp.log(self.scale._data))

    def cdf(self, value):
        v = as_tensor(value)._data
        z = (v - self.loc._data) / self.scale._data
        return Tensor(jnp.arctan(z) / math.pi + 0.5)


class Chi2(Distribution):
    """Chi-squared with ``df`` degrees of freedom (Gamma(df/2, rate=1/2))."""

    def __init__(self, df):
        self.df = as_tensor(df, "float32")

    @property
    def mean(self):
        return self.df

    @property
    def variance(self):
        return Tensor(2.0 * self.df._data)

    def sample(self, shape=()):
        k = self.df._data / 2.0
        shape = tuple(shape) + k.shape
        g = jax.random.gamma(_key(), k, shape)
        return Tensor(2.0 * g)

    def log_prob(self, value):
        v = as_tensor(value)._data
        k = self.df._data / 2.0
        return Tensor((k - 1.0) * jnp.log(v) - v / 2.0
                      - k * math.log(2.0)
                      - jax.scipy.special.gammaln(k))


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0):
        self.df = as_tensor(df, "float32")
        self.loc = as_tensor(loc, "float32")
        self.scale = as_tensor(scale, "float32")

    def sample(self, shape=()):
        df = self.df._data
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            df.shape, self.loc._data.shape, self.scale._data.shape))
        z = jax.random.t(_key(), df, shape)
        return Tensor(self.loc._data + self.scale._data * z)

    def log_prob(self, value):
        v = as_tensor(value)._data
        df = self.df._data
        z = (v - self.loc._data) / self.scale._data
        ln = jax.scipy.special.gammaln
        return Tensor(ln((df + 1) / 2) - ln(df / 2)
                      - 0.5 * jnp.log(df * math.pi)
                      - jnp.log(self.scale._data)
                      - (df + 1) / 2 * jnp.log1p(jnp.square(z) / df))


class Binomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = as_tensor(total_count, "float32")
        self.probs = as_tensor(probs, "float32")

    @property
    def mean(self):
        return Tensor(self.total_count._data * self.probs._data)

    @property
    def variance(self):
        p = self.probs._data
        return Tensor(self.total_count._data * p * (1 - p))

    def sample(self, shape=()):
        n = self.total_count._data
        p = self.probs._data
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(n.shape, p.shape))
        # jax 0.4.x random.binomial mixes weak-f64 literals with the
        # f32 count under the framework's global x64 mode (lax.clamp
        # dtype mismatch inside _btrs) — sample with x64 promotion
        # off; operands carry explicit f32 dtypes so nothing changes
        # semantically
        from ..ops.pallas._utils import no_x64
        with no_x64():
            draw = jax.random.binomial(_key(), n, p, shape=shape)
        return Tensor(draw)

    def log_prob(self, value):
        v = as_tensor(value)._data
        n = self.total_count._data
        p = self.probs._data
        ln = jax.scipy.special.gammaln
        comb = ln(n + 1) - ln(v + 1) - ln(n - v + 1)
        return Tensor(comb + v * jnp.log(p) + (n - v) * jnp.log1p(-p))


class ContinuousBernoulli(Distribution):
    """Continuous relaxation of Bernoulli on [0, 1] (Loaiza-Ganem &
    Cunningham 2019)."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs = as_tensor(probs, "float32")
        self._lims = lims

    def _log_norm(self):
        p = self.probs._data
        # C(p) = 2*atanh(1-2p) / (1-2p), with the p ~ 0.5 limit -> 2
        near = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near, 0.25, p)
        x = 1.0 - 2.0 * safe
        log_c = jnp.log(2.0 * jnp.arctanh(x) / x)
        # taylor around p=0.5: log 2 + 4/3 eps^2, eps = p - 0.5
        eps = p - 0.5
        taylor = math.log(2.0) + (4.0 / 3.0) * jnp.square(eps)
        return jnp.where(near, taylor, log_c)

    def sample(self, shape=()):
        p = self.probs._data
        shape = tuple(shape) + p.shape
        u = jax.random.uniform(_key(), shape, minval=1e-6, maxval=1 - 1e-6)
        # inverse cdf: [log1p(-p + u(2p-1)) - log1p(-p)] /
        #              [log(p) - log1p(-p)]; near p=0.5 the cdf is ~ u
        near = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near, 0.25, p)
        icdf = (jnp.log1p(-safe + u * (2.0 * safe - 1.0))
                - jnp.log1p(-safe)) / (jnp.log(safe) - jnp.log1p(-safe))
        return Tensor(jnp.where(near, u, icdf))

    def log_prob(self, value):
        v = as_tensor(value)._data
        p = self.probs._data
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
                      + self._log_norm())


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, scale_tril=None):
        self.loc = as_tensor(loc, "float32")
        if scale_tril is not None:
            self._tril = as_tensor(scale_tril, "float32")._data
        elif covariance_matrix is not None:
            cov = as_tensor(covariance_matrix, "float32")._data
            self._tril = jnp.linalg.cholesky(cov)
        else:
            raise ValueError("need covariance_matrix or scale_tril")

    @property
    def mean(self):
        return self.loc

    @property
    def covariance_matrix(self):
        return Tensor(self._tril @ jnp.swapaxes(self._tril, -1, -2))

    def sample(self, shape=()):
        shape = tuple(shape) + self.loc._data.shape
        z = jax.random.normal(_key(), shape)
        return Tensor(self.loc._data
                      + jnp.einsum("...ij,...j->...i", self._tril, z))

    def log_prob(self, value):
        v = as_tensor(value)._data
        d = v.shape[-1]
        diff = v - self.loc._data
        sol = jax.scipy.linalg.solve_triangular(
            self._tril, diff[..., None], lower=True)[..., 0]
        maha = jnp.sum(jnp.square(sol), -1)
        logdet = jnp.sum(
            jnp.log(jnp.diagonal(self._tril, axis1=-2, axis2=-1)), -1)
        return Tensor(-0.5 * (maha + d * math.log(2 * math.pi)) - logdet)

    def entropy(self):
        d = self.loc._data.shape[-1]
        logdet = jnp.sum(
            jnp.log(jnp.diagonal(self._tril, axis1=-2, axis2=-1)), -1)
        return Tensor(0.5 * d * (1.0 + math.log(2 * math.pi)) + logdet)


class Independent(Distribution):
    """Reinterpret the last ``reinterpreted_batch_rank`` batch dims of a
    base distribution as event dims (log_prob sums over them)."""

    def __init__(self, base, reinterpreted_batch_rank=1):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)._data
        return Tensor(jnp.sum(lp, axis=tuple(range(-self.rank, 0))))

    def entropy(self):
        e = self.base.entropy()._data
        return Tensor(jnp.sum(e, axis=tuple(range(-self.rank, 0))))


class TransformedDistribution(Distribution):
    """base distribution pushed through a chain of transforms."""

    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transforms = list(transforms)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        y = as_tensor(value)
        xs = [y]
        for t in reversed(self.transforms):
            xs.append(t.inverse(xs[-1]))
        xs = list(reversed(xs))  # xs[0] = base value ... xs[-1] = y
        lp = self.base.log_prob(xs[0])._data
        for t, x in zip(self.transforms, xs[:-1]):
            ld = t.forward_log_det_jacobian(x)._data
            # reduce event-rank mismatch: sum trailing dims beyond base
            while ld.ndim > lp.ndim:
                ld = jnp.sum(ld, -1)
            lp = lp - ld
        return Tensor(lp)


_KL_REGISTRY: dict = {}


def register_kl(type_p, type_q):
    """Decorator registering an explicit KL(p||q) implementation
    (paddle.distribution.register_kl)."""
    def decorator(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn
    return decorator


from .transform import StackTransform  # noqa: E402,F401

__all__ += ["ExponentialFamily", "LKJCholesky", "StackTransform"]


class ExponentialFamily(Distribution):
    """Base class for exponential-family distributions
    (paddle.distribution.ExponentialFamily, UNVERIFIED — reference mount
    empty). p(x|θ) = h(x) exp(η(θ)·t(x) − A(η)).

    Subclasses provide ``_natural_parameters`` (tuple of Tensors) and
    ``_log_normalizer(*naturals) -> jax array``; ``entropy`` then follows
    from the Bregman identity H = A(η) − Σ ηᵢ ∂A/∂ηᵢ + E[−log h(x)]
    (the mean sufficient statistics are ∇A — computed here with jax
    autodiff instead of the reference's per-op derivative kernels)."""

    #: E[log h(x)] term of the entropy; subclasses override when nonzero
    _mean_carrier_measure = 0.0

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    def entropy(self):
        nats = [n._data if isinstance(n, Tensor) else jnp.asarray(n)
                for n in self._natural_parameters]
        # A is elementwise over the batch, so ∇ of its SUM is the
        # per-element mean sufficient statistic ∂A/∂ηᵢ
        grads = jax.grad(
            lambda ns: jnp.sum(self._log_normalizer(*ns)))(tuple(nats))
        ent = self._log_normalizer(*nats) - self._mean_carrier_measure
        for n, g in zip(nats, grads):
            ent = ent - n * g
        return Tensor(ent)


class LKJCholesky(Distribution):
    """LKJ distribution over Cholesky factors of correlation matrices
    (paddle.distribution.LKJCholesky; sampling via the onion method,
    log_prob in closed form — the classic Lewandowski-Kurowicka-Joe
    construction)."""

    def __init__(self, dim, concentration=1.0, sample_method="onion"):
        if dim < 2:
            raise ValueError("LKJCholesky requires dim >= 2")
        if sample_method not in ("onion", "cvine"):
            raise ValueError(f"unknown sample_method {sample_method!r}")
        self.dim = int(dim)
        self.concentration = as_tensor(concentration, "float32")
        self.sample_method = sample_method
        c = self.concentration._data
        # per-row Beta marginals of the onion construction: row k's
        # squared radius ~ Beta(offset_k + 1/2, marginal_conc - offset_k/2)
        # with marginal_conc = c + (dim-2)/2 (the LKJ onion recursion)
        offset = jnp.concatenate(
            [jnp.zeros((1,), c.dtype),
             jnp.arange(self.dim - 1, dtype=c.dtype)])
        marginal_conc = c[..., None] + 0.5 * (self.dim - 2)
        self._beta_a = offset + 0.5
        self._beta_b = marginal_conc - 0.5 * offset

    def sample(self, shape=()):
        shape = tuple(shape)
        batch = self.concentration._data.shape
        k1, k2 = jax.random.split(_key())
        y = jax.random.beta(k1, self._beta_a, self._beta_b,
                            shape + batch + (self.dim,))[..., None]
        u = jax.random.normal(k2, shape + batch + (self.dim, self.dim))
        u = jnp.tril(u, -1)
        norm = jnp.linalg.norm(u, axis=-1, keepdims=True)
        u_sphere = jnp.where(norm > 0, u / jnp.where(norm > 0, norm, 1.0),
                             jnp.zeros_like(u))
        w = jnp.sqrt(y) * u_sphere   # strictly-lower rows on the sphere
        diag = jnp.sqrt(jnp.clip(1.0 - jnp.sum(w * w, -1), 1e-38, None))
        eye = jnp.eye(self.dim, dtype=w.dtype)
        return Tensor(w + diag[..., None] * eye)

    def log_prob(self, value):
        L = as_tensor(value)._data
        c = self.concentration._data
        diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
        order = jnp.arange(2, self.dim + 1, dtype=L.dtype)
        order = 2.0 * (c[..., None] - 1.0) + self.dim - order
        unnorm = jnp.sum(order * jnp.log(diag), axis=-1)
        dm1 = self.dim - 1
        alpha = c + 0.5 * dm1
        denom = jax.scipy.special.gammaln(alpha) * dm1
        numer = jax.scipy.special.multigammaln(alpha - 0.5, dm1)
        norm_term = 0.5 * dm1 * math.log(math.pi) + numer - denom
        return Tensor(unnorm - norm_term)
