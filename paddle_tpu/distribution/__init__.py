"""``paddle.distribution`` — probability distributions
(python/paddle/distribution/ parity, UNVERIFIED). Thin wrappers over jnp
with Tensor in/out."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework import random as framework_random
from ..ops.common import as_tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Dirichlet", "Exponential", "Gamma", "Gumbel", "Laplace",
           "LogNormal", "Multinomial", "Poisson", "kl_divergence"]


def _key():
    return framework_random.default_generator.next_key()


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._data))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = as_tensor(loc, "float32")
        self.scale = as_tensor(scale, "float32")

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return Tensor(jnp.square(self.scale._data))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape))
        z = jax.random.normal(_key(), shape)
        return Tensor(self.loc._data + self.scale._data * z)

    def log_prob(self, value):
        v = as_tensor(value)._data
        var = jnp.square(self.scale._data)
        return Tensor(-jnp.square(v - self.loc._data) / (2 * var)
                      - jnp.log(self.scale._data)
                      - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale._data))

    def kl_divergence(self, other):
        var_ratio = jnp.square(self.scale._data / other.scale._data)
        t1 = jnp.square((self.loc._data - other.loc._data)
                        / other.scale._data)
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = as_tensor(low, "float32")
        self.high = as_tensor(high, "float32")

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.low._data.shape, self.high._data.shape))
        u = jax.random.uniform(_key(), shape)
        return Tensor(self.low._data + (self.high._data - self.low._data)
                      * u)

    def log_prob(self, value):
        v = as_tensor(value)._data
        inside = (v >= self.low._data) & (v < self.high._data)
        lp = -jnp.log(self.high._data - self.low._data)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high._data - self.low._data))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = as_tensor(logits, "float32")

    def sample(self, shape=()):
        out = jax.random.categorical(_key(), self.logits._data,
                                     shape=tuple(shape) +
                                     self.logits._data.shape[:-1])
        return Tensor(out.astype(jnp.int64))

    def probs(self, value=None):
        p = jax.nn.softmax(self.logits._data, -1)
        if value is None:
            return Tensor(p)
        v = as_tensor(value)._data.astype(jnp.int32)
        return Tensor(jnp.take_along_axis(p, v[..., None], -1)[..., 0])

    def log_prob(self, value):
        lp = jax.nn.log_softmax(self.logits._data, -1)
        v = as_tensor(value)._data.astype(jnp.int32)
        return Tensor(jnp.take_along_axis(lp, v[..., None], -1)[..., 0])

    def entropy(self):
        lp = jax.nn.log_softmax(self.logits._data, -1)
        return Tensor(-jnp.sum(jnp.exp(lp) * lp, -1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = as_tensor(probs, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.probs_._data.shape
        return Tensor(jax.random.bernoulli(
            _key(), self.probs_._data, shape).astype(jnp.float32))

    def log_prob(self, value):
        v = as_tensor(value)._data
        p = jnp.clip(self.probs_._data, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs_._data, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = as_tensor(alpha, "float32")
        self.beta = as_tensor(beta, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.alpha._data.shape, self.beta._data.shape)
        return Tensor(jax.random.beta(_key(), self.alpha._data,
                                      self.beta._data, shape))

    def log_prob(self, value):
        v = as_tensor(value)._data
        a, b = self.alpha._data, self.beta._data
        lbeta = (jax.scipy.special.gammaln(a)
                 + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                      - lbeta)


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = as_tensor(concentration, "float32")

    def sample(self, shape=()):
        return Tensor(jax.random.dirichlet(_key(),
                                           self.concentration._data,
                                           tuple(shape)))

    def log_prob(self, value):
        v = as_tensor(value)._data
        c = self.concentration._data
        lnB = jnp.sum(jax.scipy.special.gammaln(c), -1) - \
            jax.scipy.special.gammaln(jnp.sum(c, -1))
        return Tensor(jnp.sum((c - 1) * jnp.log(v), -1) - lnB)


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = as_tensor(rate, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.rate._data.shape
        return Tensor(jax.random.exponential(_key(), shape)
                      / self.rate._data)

    def log_prob(self, value):
        v = as_tensor(value)._data
        return Tensor(jnp.log(self.rate._data) - self.rate._data * v)


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = as_tensor(concentration, "float32")
        self.rate = as_tensor(rate, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.concentration._data.shape
        return Tensor(jax.random.gamma(_key(), self.concentration._data,
                                       shape) / self.rate._data)

    def log_prob(self, value):
        v = as_tensor(value)._data
        a, b = self.concentration._data, self.rate._data
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - jax.scipy.special.gammaln(a))


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = as_tensor(loc, "float32")
        self.scale = as_tensor(scale, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.loc._data.shape
        return Tensor(self.loc._data + self.scale._data *
                      jax.random.gumbel(_key(), shape))

    def log_prob(self, value):
        z = (as_tensor(value)._data - self.loc._data) / self.scale._data
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale._data))


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = as_tensor(loc, "float32")
        self.scale = as_tensor(scale, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.loc._data.shape
        return Tensor(self.loc._data + self.scale._data *
                      jax.random.laplace(_key(), shape))

    def log_prob(self, value):
        v = as_tensor(value)._data
        return Tensor(-jnp.abs(v - self.loc._data) / self.scale._data
                      - jnp.log(2 * self.scale._data))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.loc = as_tensor(loc, "float32")
        self.scale = as_tensor(scale, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.loc._data.shape
        z = jax.random.normal(_key(), shape)
        return Tensor(jnp.exp(self.loc._data + self.scale._data * z))

    def log_prob(self, value):
        v = as_tensor(value)._data
        lv = jnp.log(v)
        var = jnp.square(self.scale._data)
        return Tensor(-jnp.square(lv - self.loc._data) / (2 * var)
                      - lv - jnp.log(self.scale._data)
                      - 0.5 * math.log(2 * math.pi))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_ = as_tensor(probs, "float32")

    def sample(self, shape=()):
        logits = jnp.log(jnp.maximum(self.probs_._data, 1e-38))
        draws = jax.random.categorical(
            _key(), logits, shape=tuple(shape) + (self.total_count,)
            + logits.shape[:-1])
        k = logits.shape[-1]
        onehot = jax.nn.one_hot(draws, k)
        axis = len(tuple(shape))
        return Tensor(jnp.sum(onehot, axis=axis))

    def log_prob(self, value):
        v = as_tensor(value)._data
        p = jnp.maximum(self.probs_._data, 1e-38)
        logfact = jax.scipy.special.gammaln(v.sum(-1) + 1) - \
            jnp.sum(jax.scipy.special.gammaln(v + 1), -1)
        return Tensor(logfact + jnp.sum(v * jnp.log(p), -1))


class Poisson(Distribution):
    def __init__(self, rate):
        self.rate = as_tensor(rate, "float32")

    def sample(self, shape=()):
        shape = tuple(shape) + self.rate._data.shape
        return Tensor(jax.random.poisson(_key(), self.rate._data,
                                         shape).astype(jnp.float32))

    def log_prob(self, value):
        v = as_tensor(value)._data
        r = self.rate._data
        return Tensor(v * jnp.log(r) - r
                      - jax.scipy.special.gammaln(v + 1))


def kl_divergence(p, q):
    if hasattr(p, "kl_divergence"):
        return p.kl_divergence(q)
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")
