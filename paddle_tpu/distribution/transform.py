"""paddle.distribution.transform — invertible transforms for
TransformedDistribution (upstream
``python/paddle/distribution/transform.py``, UNVERIFIED; see SURVEY.md
provenance warning)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..ops.common import as_tensor

__all__ = ["Transform", "AffineTransform", "ExpTransform", "PowerTransform",
           "SigmoidTransform", "TanhTransform", "AbsTransform",
           "SoftmaxTransform", "ChainTransform", "IndependentTransform",
           "ReshapeTransform", "StickBreakingTransform", "StackTransform"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class Transform:
    """Bijection y = f(x) with log|det J| bookkeeping. The public methods
    run through ``apply`` so gradients flow (rsample reparameterization
    through TransformedDistribution stays differentiable)."""

    _event_rank = 0  # rank of the event space the jacobian acts on

    def forward(self, x):
        from ..framework.core import apply
        return apply(self._forward, as_tensor(x),
                     name=type(self).__name__ + ".forward")

    def inverse(self, y):
        from ..framework.core import apply
        return apply(self._inverse, as_tensor(y),
                     name=type(self).__name__ + ".inverse")

    def forward_log_det_jacobian(self, x):
        from ..framework.core import apply
        return apply(self._fldj, as_tensor(x),
                     name=type(self).__name__ + ".fldj")

    def inverse_log_det_jacobian(self, y):
        from ..framework.core import apply
        return apply(lambda a: -self._fldj(self._inverse(a)), as_tensor(y),
                     name=type(self).__name__ + ".ildj")

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # subclass hooks on raw arrays
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    """y = loc + scale * x."""

    def __init__(self, loc, scale):
        self.loc = as_tensor(loc, "float32")
        self.scale = as_tensor(scale, "float32")

    def _forward(self, x):
        return self.loc._data + self.scale._data * x

    def _inverse(self, y):
        return (y - self.loc._data) / self.scale._data

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale._data)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = as_tensor(power, "float32")

    def _forward(self, x):
        return jnp.power(x, self.power._data)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power._data)

    def _fldj(self, x):
        p = self.power._data
        return jnp.log(jnp.abs(p * jnp.power(x, p - 1.0)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        # log(1 - tanh^2 x) = 2*(log2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    """y = |x| (not bijective; inverse returns the positive branch)."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        return jnp.zeros_like(x)


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last axis (not volume preserving; ldj is
    not defined — upstream also raises)."""

    _event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        raise NotImplementedError(
            "SoftmaxTransform does not implement log_det_jacobian")


class StickBreakingTransform(Transform):
    """Maps R^{K-1} to the K-simplex via stick breaking."""

    _event_rank = 1

    def _forward(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zp = jnp.cumprod(1.0 - z, axis=-1)
        lead = jnp.concatenate(
            [jnp.ones_like(z[..., :1]), zp], axis=-1)
        probs = jnp.concatenate([z, jnp.ones_like(z[..., :1])], -1) * lead
        return probs

    def _inverse(self, y):
        ycum = jnp.cumsum(y[..., :-1], axis=-1)
        rest = 1.0 - jnp.concatenate(
            [jnp.zeros_like(ycum[..., :1]), ycum[..., :-1]], -1)
        z = y[..., :-1] / rest
        k = y.shape[-1] - 1
        offset = k - jnp.arange(k, dtype=y.dtype)
        return jnp.log(z / (1 - z)) + jnp.log(offset)

    def _fldj(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        u = x - jnp.log(offset)
        y = self._forward(x)
        # d simplex / d u: sum_k [ -u_k + log sigmoid(u_k) + log y_k ]
        return jnp.sum(-u + jax.nn.log_sigmoid(u)
                       + jnp.log(y[..., :-1]), axis=-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ChainTransform(Transform):
    """Composition: y = f_n(...f_1(x))."""

    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._event_rank = max((t._event_rank for t in self.transforms),
                               default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = None
        for t in self.transforms:
            ld = t._fldj(x)
            # reduce finer-grained ldj down to this chain's event rank
            # BEFORE accumulating: an elementwise transform's per-element
            # ldj must sum over the event dims a rank>0 transform (e.g.
            # StickBreakingTransform) treats as one event, or the shapes
            # broadcast-add and the result is wrong
            extra = self._event_rank - t._event_rank
            if extra > 0:
                ld = jnp.sum(ld, axis=tuple(range(-extra, 0)))
            total = ld if total is None else total + ld
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


class StackTransform(Transform):
    """Apply ``transforms[i]`` to slice ``i`` of the input along ``axis``
    (upstream ``paddle.distribution.StackTransform``)."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)
        self._event_rank = max((t._event_rank for t in self.transforms),
                               default=0)

    def _map(self, hook, x):
        n = x.shape[self.axis]
        if n != len(self.transforms):
            raise ValueError(
                f"StackTransform: input has {n} slices along axis "
                f"{self.axis} but {len(self.transforms)} transforms")
        parts = [hook(t)(jnp.squeeze(s, self.axis))
                 for t, s in zip(self.transforms,
                                 jnp.split(x, n, axis=self.axis))]
        return jnp.stack(parts, axis=self.axis)

    def _forward(self, x):
        return self._map(lambda t: t._forward, x)

    def _inverse(self, y):
        return self._map(lambda t: t._inverse, y)

    def _fldj(self, x):
        return self._map(lambda t: t._fldj, x)


class IndependentTransform(Transform):
    """Treat the last ``reinterpreted_batch_rank`` dims as event dims:
    the ldj sums over them."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._event_rank = base._event_rank + self.rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        ld = self.base._fldj(x)
        return jnp.sum(ld, axis=tuple(range(-self.rank, 0)))


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(int(s) for s in in_event_shape)
        self.out_event_shape = tuple(int(s) for s in out_event_shape)
        self._event_rank = len(self.in_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(tuple(batch) + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(tuple(batch) + self.in_event_shape)

    def _fldj(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)
