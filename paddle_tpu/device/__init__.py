"""``paddle.device`` namespace — device queries + memory stats.

The reference's allocator stats (StatAllocator, SURVEY.md §2.1) map to PJRT
memory stats here."""

from __future__ import annotations

import jax

from ..framework.device import (set_device, get_device, device_count,  # noqa
                                CPUPlace, TPUPlace, CUDAPlace, XPUPlace,
                                CustomPlace, is_compiled_with_cuda,
                                is_compiled_with_xpu, is_compiled_with_tpu)

__all__ = ["set_device", "get_device", "device_count", "CPUPlace",
           "TPUPlace", "CUDAPlace", "XPUPlace", "CustomPlace",
           "is_compiled_with_cuda", "is_compiled_with_xpu",
           "is_compiled_with_tpu", "memory_stats", "max_memory_allocated",
           "max_memory_reserved", "memory_allocated", "memory_reserved",
           "synchronize", "get_available_device", "cuda"]


def _mem_stats(device_id=0):
    devs = jax.devices()
    d = devs[min(device_id, len(devs) - 1)]
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}


def memory_stats(device=None):
    return _mem_stats(_dev_id(device))


def _dev_id(device):
    if device is None:
        return 0
    if isinstance(device, int):
        return device
    if isinstance(device, str) and ":" in device:
        return int(device.split(":")[1])
    return getattr(device, "device_id", 0)


def max_memory_allocated(device=None):
    return _mem_stats(_dev_id(device)).get("peak_bytes_in_use", 0)


def memory_allocated(device=None):
    return _mem_stats(_dev_id(device)).get("bytes_in_use", 0)


def max_memory_reserved(device=None):
    s = _mem_stats(_dev_id(device))
    return s.get("peak_bytes_in_use", s.get("bytes_limit", 0))


def memory_reserved(device=None):
    s = _mem_stats(_dev_id(device))
    return s.get("bytes_in_use", 0)


def synchronize(device=None):
    """Block until queued work is observable (paddle.device.synchronize).
    XLA serializes per-device execution, so readiness of a fresh transfer
    implies prior work completed."""
    jax.block_until_ready(jax.device_put(0))


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


class cuda:
    """paddle.device.cuda namespace shim (maps to the accelerator)."""

    @staticmethod
    def device_count():
        return device_count("tpu")

    @staticmethod
    def max_memory_allocated(device=None):
        return max_memory_allocated(device)

    @staticmethod
    def memory_allocated(device=None):
        return memory_allocated(device)

    @staticmethod
    def max_memory_reserved(device=None):
        return max_memory_reserved(device)

    @staticmethod
    def memory_reserved(device=None):
        return memory_reserved(device)

    @staticmethod
    def synchronize(device=None):
        return synchronize(device)

    @staticmethod
    def empty_cache():
        pass

    class Event:
        def __init__(self, *a, **k):
            pass

        def record(self, *a):
            pass

        def synchronize(self):
            synchronize()

    class Stream:
        def __init__(self, *a, **k):
            pass

        def synchronize(self):
            synchronize()


# ---- top-level Stream/Event/stream APIs (paddle.device parity) ------------
# XLA owns scheduling on TPU (SURVEY.md §2.1 new-executor row): a Stream is
# a compatibility handle; ordering is what the runtime already guarantees.

class Stream:
    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize(self.device)

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        ev = event or Event()
        ev.record(self)
        return ev

    def query(self):
        return True


class Event:
    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        self.device = device

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize(self.device)


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


def set_stream(stream):
    global _current_stream
    prev, _current_stream = _current_stream, stream
    return prev


class stream_guard:
    def __init__(self, stream):
        self._stream = stream

    def __enter__(self):
        self._prev = set_stream(self._stream)
        return self._stream

    def __exit__(self, *exc):
        set_stream(self._prev)


def is_compiled_with_rocm():
    return False


def get_available_custom_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()
            if d.platform not in ("cpu", "gpu", "tpu")]


class xpu:
    """paddle.device.xpu namespace shim (no XPU on this backend)."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def synchronize(device=None):
        return synchronize(device)


__all__ += ["Stream", "Event", "current_stream", "set_stream",
            "stream_guard", "is_compiled_with_rocm",
            "get_available_custom_device", "xpu"]


def get_cudnn_version():
    """No CUDA in a TPU build (upstream returns None when not compiled
    with CUDA)."""
    return None


def get_all_device_type():
    import jax
    kinds = {"cpu"}
    try:
        kinds.add(jax.default_backend())
    except Exception:
        pass
    return sorted(kinds)


def get_all_custom_device_type():
    return []


__all__ += ["get_cudnn_version", "get_all_device_type",
            "get_all_custom_device_type"]
