"""Activation recompute — ``paddle.distributed.fleet.utils.recompute``
parity (UNVERIFIED).

TPU-native: ``jax.checkpoint`` (remat) IS the mechanism — we functionalize
the layer call (parameters become explicit inputs), wrap it in
``jax.checkpoint``, and record it as ONE tape node, so backward recomputes
the block's activations instead of storing them (the HBM-for-FLOPs trade
SURVEY.md's design notes call out)."""

from __future__ import annotations

from ..framework.core import Tensor, apply
from ..ops.common import as_tensor

__all__ = ["recompute"]


def recompute(function, *args, params_from=None, n_outputs=1, **kwargs):
    """Run ``function(*args)`` under rematerialization. ``function`` may be
    a Layer (its parameters/buffers are captured as differentiable inputs)
    or a pure function of tensors. For a closure/bound method touching a
    Layer's parameters, pass that Layer as ``params_from`` so its
    parameters are captured as differentiable inputs (otherwise they'd be
    baked in as constants and receive no gradient)."""
    from ..nn.layer.layers import Layer
    params: list[Tensor] = []
    source = function if isinstance(function, Layer) else params_from
    if isinstance(source, Layer):
        source = [source]
    for lay in source or []:
        params.extend(lay.parameters())
        params.extend(lay.buffers())
    tensor_args = [as_tensor(a) if not isinstance(a, Tensor) else a
                   for a in args]
    n_args = len(tensor_args)

    def pure(*flat):
        arg_datas = flat[:n_args]
        p_datas = flat[n_args:]
        originals = [(p, p._data) for p in params]
        try:
            for p, d in zip(params, p_datas):
                p._data = d
            from ..framework.core import no_grad
            with no_grad():
                # inner ops must not re-record on the tape: the outer
                # checkpointed node owns the whole block's vjp
                out = function(*[Tensor(a) for a in arg_datas], **kwargs)
            return out._data if isinstance(out, Tensor) else \
                tuple(o._data for o in out)
        finally:
            for p, d in originals:
                p._data = d

    ckpt = checkpoint_with_policy(pure)
    return apply(ckpt, *tensor_args, *params, name="recompute",
                 n_outputs=n_outputs)


_POLICY_NAMES = ("dots_saveable", "nothing_saveable",
                 "dots_with_no_batch_dims_saveable", "everything_saveable",
                 "dots_and_flash_saveable")


def _resolve_policy(name):
    import jax

    if name == "dots_and_flash_saveable":
        # dots_saveable + the named Pallas flash-attention outputs.
        # Measured SLOWER than plain dots_saveable on v5e (112 vs 105 ms
        # on the 4-layer 2560-hidden slice): the custom-vjp's lse
        # residual is still recomputed, so saving the [B,S,H,D] context
        # only adds HBM traffic. Kept as an opt-in for configs where
        # memory, not bandwidth, is the binding constraint.
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_saveable,
            jax.checkpoint_policies.save_only_these_names("flash_out"))
    return getattr(jax.checkpoint_policies, name)


def checkpoint_with_policy(fn):
    """jax.checkpoint honoring FLAGS_recompute_policy — the single remat
    entry point for recompute(), scan_layers, and the pipeline engine.

    dots_saveable (the default) keeps matmul outputs and recomputes only
    elementwise ops: measured 60.2% vs 19.9% MFU for nothing_saveable on
    the B=4 Llama remat config (recomputing MXU work costs 3x;
    recomputing VPU work is nearly free). dots_and_flash_saveable
    additionally saves the flash-attention kernel outputs (opt-in; see
    _resolve_policy for the v5e measurement).
    """
    import jax

    from ..framework import flags
    name = flags.flag("FLAGS_recompute_policy")
    if name not in _POLICY_NAMES:
        raise ValueError(
            f"FLAGS_recompute_policy={name!r} is not a known policy; "
            f"choose one of {_POLICY_NAMES}")
    return jax.checkpoint(fn, policy=_resolve_policy(name))
