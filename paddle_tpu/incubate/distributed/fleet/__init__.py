"""``paddle.incubate.distributed.fleet`` — pipeline-parallel recompute
helpers (reference: ``incubate/distributed/fleet/recompute_hybrid.py``
etc., UNVERIFIED — mount empty). Both desugar to the framework
recompute (jax.checkpoint): the reference's hybrid variant additionally
manages cross-rank RNG and comm groups, which the compiled pipeline
engines own here."""

from ...recompute import recompute as _recompute

__all__ = ["recompute_sequential", "recompute_hybrid"]


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Run a Sequential (or list of layers) in ``ctx['segments']``
    rematerialized chunks (reference semantics: each segment's
    activations recompute in backward)."""
    segments = int((ctx or {}).get("segments", 1))
    layers = list(functions)
    if not layers:
        return args[0] if len(args) == 1 else args
    per = max(len(layers) // max(segments, 1), 1)
    out = args[0]

    def run_chunk(chunk, x):
        def f(t):
            for l in chunk:
                t = l(t)
            return t
        # params_from: closure-captured weights must be DIFFERENTIATED
        # THROUGH the checkpoint, not baked in as constants (without it
        # every chunk layer's grad is silently None)
        return _recompute(f, x, params_from=list(chunk))

    for i in range(0, len(layers), per):
        out = run_chunk(layers[i:i + per], out)
    return out


def recompute_hybrid(ctx, function, *args, params_from=None, **kwargs):
    """Hybrid-parallel recompute: the reference threads mp/pp RNG
    trackers and offload knobs through; here those live inside the
    compiled engines, so this is the framework recompute with the ctx
    accepted for parity. ``function`` closing over Layers must pass
    ``params_from=[those layers]`` so their weights get gradients
    through the checkpoint (same contract as incubate.recompute)."""
    return _recompute(function, *args, params_from=params_from, **kwargs)
