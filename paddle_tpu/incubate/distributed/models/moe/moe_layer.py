"""MoELayer — parity with ``paddle.incubate.distributed.models.moe``
(MoELayer + gates; UNVERIFIED, reference mount empty) re-designed TPU-first
over the pure-jax core in ``paddle_tpu.ops.moe``:

- Expert weights are a stacked bank ([E, d, h] Parameters) so expert
  compute is one grouped einsum on the MXU, not a per-expert loop.
- With fleet ep_degree > 1 the forward runs the all-to-all dispatch
  inside a partial-manual ``jax.shard_map`` over the 'expert' mesh axis
  (tokens and experts both sharded); otherwise the dense capacity path.
- ``layer.aux_loss`` / ``layer.z_loss`` hold the last forward's router
  losses (Tensor), matching the reference's gate-loss plumbing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .....utils.jax_compat import shard_map as _shard_map

from .....framework.core import Tensor, apply
from .....nn.layer.layers import Layer
from .....nn import initializer as I
from .....ops import moe as moe_ops

__all__ = ["MoELayer", "GShardGate", "SwitchGate"]


class _GateSpec:
    def __init__(self, top_k, capacity_factor, norm_topk_prob,
                 dropless=False):
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.norm_topk_prob = norm_topk_prob
        self.dropless = dropless


def GShardGate(top_k=2, capacity_factor=1.25):
    return _GateSpec(top_k, capacity_factor, True)


def SwitchGate(capacity_factor=1.25):
    return _GateSpec(1, capacity_factor, False)


def _ep_axis_and_mesh():
    from .....distributed.fleet.base import fleet as fleet_singleton
    hcg = fleet_singleton._hcg
    if hcg is None:
        return None, None, 1
    return (hcg.ep_axis_name, hcg.global_mesh,
            hcg.get_expert_parallel_world_size())


class MoELayer(Layer):
    """Sparse SwiGLU FFN block with top-k routing.

    d_model/d_hidden: token/expert hidden sizes. num_experts: global E.
    gate: a gate spec (GShardGate()/SwitchGate()) or dict(top_k=...,
    capacity_factor=...). Input [B, S, d] or [T, d]; same shape out.
    """

    def __init__(self, d_model, d_hidden, num_experts, gate=None,
                 weight_attr=None, name=None):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        if gate is None:
            gate = GShardGate()
        if isinstance(gate, dict):
            gate = _GateSpec(gate.get("top_k", 2),
                             gate.get("capacity_factor", 1.25),
                             gate.get("norm_topk_prob", True),
                             gate.get("dropless", False))
        self.gate = gate
        init = I.XavierNormal()
        self.router_weight = self.create_parameter(
            [d_model, num_experts], attr=weight_attr,
            default_initializer=init)
        self.w_gate = self.create_parameter(
            [num_experts, d_model, d_hidden], attr=weight_attr,
            default_initializer=init)
        self.w_up = self.create_parameter(
            [num_experts, d_model, d_hidden], attr=weight_attr,
            default_initializer=init)
        self.w_down = self.create_parameter(
            [num_experts, d_hidden, d_model], attr=weight_attr,
            default_initializer=init)
        self.aux_loss: Tensor | None = None
        self.z_loss: Tensor | None = None
        axis, mesh, ep = _ep_axis_and_mesh()
        self._ep_axis, self._mesh, self._ep = axis, mesh, ep
        if mesh is not None and ep > 1 and \
                getattr(self.gate, "dropless", False):
            # dropless is a single-device/GSPMD path; every ep>1 forward
            # (manual or GSPMD) takes the capacity all-to-all, which
            # DROPS tokens past capacity_factor — a silent numerics
            # downgrade without this warning (ADVICE.md round 5)
            import warnings
            warnings.warn(
                f"MoELayer: gate dropless=True requested but expert "
                f"parallelism is active (ep_degree={ep}); the EP "
                f"capacity dispatch path is taken instead and tokens "
                f"beyond capacity_factor={self.gate.capacity_factor} "
                f"are dropped (numerics differ from dropless). Use "
                f"ep_degree=1 for dropless, or raise capacity_factor.",
                UserWarning, stacklevel=2)
            from .....profiler.trace import log_perf_event
            log_perf_event(
                "moe/dropless_downgraded",
                f"dropless=True ignored under ep_degree={ep}: capacity "
                f"path (cf={self.gate.capacity_factor}) dispatches this "
                "layer", once_key=("moe/dropless_downgraded", ep))
        if mesh is not None and ep > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            for p in (self.w_gate, self.w_up, self.w_down):
                p.set_data(jax.device_put(
                    p._data, NamedSharding(mesh, P(axis, None, None))))
                # the pipeline engine reads this to keep the bank's
                # expert dim sharded through its manual region (so
                # per-device weight memory stays E/ep, not E)
                p._ep_shard_dim = 0

    def _ep_axis_is_manual(self) -> bool:
        from .....distributed.communication import axis_in_traced_region
        return self._ep_axis is not None and \
            axis_in_traced_region(self._ep_axis)

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        k = self.gate.top_k
        cf = self.gate.capacity_factor
        ntp = self.gate.norm_topk_prob
        axis, mesh, ep = self._ep_axis, self._mesh, self._ep

        if mesh is not None and ep > 1 and self._ep_axis_is_manual():
            # Inside a manual region that already binds the 'expert'
            # axis — the compiled pipeline engine running an ep x pp
            # hybrid. Activations arrive REPLICATED over 'expert': each
            # rank slices its token shard and its expert-bank shard by
            # axis index, runs the same all-to-all dispatch core, and
            # the full token set is reassembled with a masked psum
            # (which also restores expert-invariance for the carry
            # types). Weight cotangents psum over 'expert'
            # automatically at the region boundary (their specs don't
            # mention the axis).
            from jax import lax

            def fn(xx, rw, wg, wu, wd):
                flat = xx.reshape(-1, d)
                T = flat.shape[0]
                ep_n = self._ep
                if T % ep_n:
                    raise ValueError(
                        f"token count {T} not divisible by ep {ep_n}")
                E = self.num_experts
                idx = lax.axis_index(axis)
                tl, el = T // ep_n, E // ep_n
                xf = lax.dynamic_slice_in_dim(flat, idx * tl, tl, 0)
                if wg.shape[0] == el:
                    # the enclosing region kept the bank's expert dim
                    # sharded (pipeline param_specs): already local
                    wgl, wul, wdl = wg, wu, wd
                else:
                    wgl = lax.dynamic_slice_in_dim(wg, idx * el, el, 0)
                    wul = lax.dynamic_slice_in_dim(wu, idx * el, el, 0)
                    wdl = lax.dynamic_slice_in_dim(wd, idx * el, el, 0)
                y, aux, z = moe_ops.moe_forward_ep(
                    xf, rw,
                    lambda t: moe_ops.moe_ffn_grouped(t, wgl, wul, wdl),
                    axis, k=k, capacity_factor=cf, norm_topk_prob=ntp)
                buf = jnp.zeros_like(flat)
                buf = lax.dynamic_update_slice_in_dim(
                    buf, y.astype(buf.dtype), idx * tl, 0)
                full = lax.psum(buf, axis)
                return full.reshape(xx.shape), aux, z

            out, aux, z = apply(fn, x, self.router_weight, self.w_gate,
                                self.w_up, self.w_down, n_outputs=3,
                                name="moe_layer_ep_manual")
        elif mesh is not None and ep > 1:
            from jax.sharding import PartitionSpec as P
            from .....distributed.fleet.base import fleet as _fleet
            hcg = _fleet._hcg
            if hcg is not None and hcg.get_sep_parallel_world_size() > 1:
                # composing the GSPMD-EP shard_map with a live 'sep'
                # axis CHECK-crashes XLA's SPMD partitioner on this
                # version (spmd_partitioner_util.h scalar check,
                # jax 0.9; an explicit pre-reshard constraint does not
                # avoid it) — reject with a clear error instead of a
                # process abort. MoE long-context runs use sep via the
                # compiled pipeline region (ep x pp x sep) or ep-only.
                raise ValueError(
                    "ep_degree > 1 with sep_degree > 1 under GSPMD is "
                    "not supported on this XLA version (SPMD "
                    "partitioner CHECK failure); drop one axis or "
                    "compose ep with sep inside the pipeline engine")

            def fn(xx, rw, wg, wu, wd):
                flat = xx.reshape(-1, d)

                def core(xf, rwl, wgl, wul, wdl):
                    y, aux, z = moe_ops.moe_forward_ep(
                        xf, rwl,
                        lambda t: moe_ops.moe_ffn_grouped(t, wgl, wul, wdl),
                        axis, k=k, capacity_factor=cf, norm_topk_prob=ntp)
                    return y, aux, z

                f = _shard_map(
                    core, mesh=mesh,
                    in_specs=(P(axis, None), P(None, None),
                              P(axis, None, None), P(axis, None, None),
                              P(axis, None, None)),
                    out_specs=(P(axis, None), P(), P()),
                    axis_names={axis})
                y, aux, z = f(flat, rw, wg, wu, wd)
                return y.reshape(xx.shape), aux, z

            out, aux, z = apply(fn, x, self.router_weight, self.w_gate,
                                self.w_up, self.w_down, n_outputs=3,
                                name="moe_layer_ep")
        elif getattr(self.gate, "dropless", False):
            # MegaBlocks-style dropless dispatch over the Pallas grouped
            # matmul: no capacity, no drops, <= E*bm padding rows (vs
            # cf x T*k padded slots for the capacity path). Single-device
            # / GSPMD path; EP keeps the capacity all-to-all (per-device
            # quotas are what bound the a2a payload there).
            def fn(xx, rw, wg, wu, wd):
                flat = xx.reshape(-1, d)
                y, aux, z = moe_ops.moe_forward_dropless(
                    flat, rw, wg, wu, wd, k=k, norm_topk_prob=ntp)
                return y.reshape(xx.shape), aux, z

            out, aux, z = apply(fn, x, self.router_weight, self.w_gate,
                                self.w_up, self.w_down, n_outputs=3,
                                name="moe_layer_dropless")
        else:
            def fn(xx, rw, wg, wu, wd):
                flat = xx.reshape(-1, d)
                y, aux, z = moe_ops.moe_forward(
                    flat, rw,
                    lambda t: moe_ops.moe_ffn_grouped(t, wg, wu, wd),
                    k=k, capacity_factor=cf, norm_topk_prob=ntp)
                return y.reshape(xx.shape), aux, z

            out, aux, z = apply(fn, x, self.router_weight, self.w_gate,
                                self.w_up, self.w_down, n_outputs=3,
                                name="moe_layer")
        self.aux_loss = aux
        self.z_loss = z
        return out
