"""``paddle.incubate.distributed.models.moe`` — MoE layers
(paddle/incubate/distributed/models/moe parity, UNVERIFIED)."""

from .moe_layer import MoELayer, GShardGate, SwitchGate

__all__ = ["MoELayer", "GShardGate", "SwitchGate"]
