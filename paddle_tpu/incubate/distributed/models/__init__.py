"""``paddle.incubate.distributed.models`` (parity; UNVERIFIED)."""

from . import moe

__all__ = ["moe"]
