"""``paddle.incubate.distributed`` namespace (parity; UNVERIFIED)."""

from . import models

__all__ = ["models"]
