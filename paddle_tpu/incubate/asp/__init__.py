"""``paddle.incubate.asp`` — automatic structured (n:m) sparsity
(reference: ``python/paddle/incubate/asp/`` pruning masks + mask-aware
optimizer, UNVERIFIED; SURVEY.md §2.2 incubate row).

TPU note: XLA has no sparse-tensor-core path, so n:m sparsity is a
*model compression / regularization* feature here: masks are applied to
weights, and the decorated optimizer re-applies them after every step so
pruned weights stay zero through training. The masked matmuls still run
dense on the MXU (the reference's 2:4 speedup is an Ampere
sparse-tensor-core feature with no TPU analogue — documented, not
emulated).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...framework.core import Tensor
from ... import nn

__all__ = ["calculate_density", "decorate", "prune_model",
           "reset_excluded_layers", "set_excluded_layers",
           "check_sparsity", "create_mask", "clear_masks"]

_excluded = set()
_masks = {}  # id(param) -> (param, jnp mask)


def set_excluded_layers(layers, main_program=None):
    """Exclude layers (by full sublayer name) from pruning."""
    global _excluded
    _excluded |= set(layers)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def create_mask(weight, func_name="mask_1d", n=2, m=4):
    """n:m mask along the LAST axis: keep the n largest |w| in every
    group of m. Returns a {0,1} array shaped like weight."""
    w = np.asarray(weight.jax() if isinstance(weight, Tensor)
                   else weight)
    if w.shape[-1] % m:
        return np.ones_like(w)  # non-divisible: leave dense
    g = w.reshape(-1, m)
    order = np.argsort(-np.abs(g), axis=1)
    mask = np.zeros_like(g)
    np.put_along_axis(mask, order[:, :n], 1.0, axis=1)
    return mask.reshape(w.shape)


def check_sparsity(weight, n=2, m=4, func_name="mask_1d"):
    """True iff every group of m (last axis) has <= n nonzeros."""
    w = np.asarray(weight.jax() if isinstance(weight, Tensor)
                   else weight)
    if w.shape[-1] % m:
        return False
    g = (w.reshape(-1, m) != 0).sum(axis=1)
    return bool((g <= n).all())


def calculate_density(weight):
    w = np.asarray(weight.jax() if isinstance(weight, Tensor)
                   else weight)
    return float((w != 0).mean())


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every Linear weight (excluded layers skipped).
    Returns {param_name: mask}."""
    out = {}
    for name, layer in model.named_sublayers():
        if name in _excluded or not isinstance(layer, nn.Linear):
            continue
        p = layer.weight
        mask = jnp.asarray(create_mask(p, mask_algo, n, m), p.jax().dtype)
        p.set_value(Tensor(p.jax() * mask))
        if with_mask:
            _masks[id(p)] = (p, mask)
        out[name + ".weight"] = mask
    return out


class ASPOptimizer:
    """Optimizer wrapper: after each step, re-apply the pruning masks so
    pruned weights stay exactly zero (the reference's mask-aware
    optimizer semantics). Only masks belonging to THIS optimizer's
    parameters are applied — masks registered by other models are not
    touched."""

    def __init__(self, optimizer):
        self._inner = optimizer

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _owned_masks(self):
        try:
            owned = {id(p) for p in self._inner._parameter_list}
        except AttributeError:
            return list(_masks.values())
        return [(p, m) for pid, (p, m) in _masks.items() if pid in owned]

    def _reapply(self):
        for p, mask in self._owned_masks():
            p.set_value(Tensor(p.jax() * mask))

    def step(self):
        self._inner.step()
        self._reapply()

    def minimize(self, loss, *a, **k):
        r = self._inner.minimize(loss, *a, **k)
        self._reapply()
        return r


def decorate(optimizer):
    return ASPOptimizer(optimizer)


def clear_masks():
    """Drop all registered masks (call between unrelated models; masks
    hold strong references to their parameters)."""
    _masks.clear()
