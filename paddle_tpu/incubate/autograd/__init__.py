"""jax-native higher-order autograd (the role of paddle.incubate.autograd's
prim mechanism, UNVERIFIED): jacobian/hessian/vjp/jvp over functions of
Tensors, computed with jax transforms (exact, any order)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor

__all__ = ["jacobian", "hessian", "vjp", "jvp", "forward_grad", "grad"]


def _wrap_fn(func):
    """Lift a Tensor->Tensor function to arrays->arrays."""
    def fn(*arrays):
        ins = [Tensor(a, stop_gradient=False) for a in arrays]
        out = func(*ins)
        if isinstance(out, (list, tuple)):
            return tuple(o._data for o in out)
        return out._data
    return fn


def _datas(xs):
    if isinstance(xs, Tensor):
        return (xs._data,), True
    return tuple(x._data for x in xs), False


def jacobian(func, xs, is_batched=False):
    arrays, single = _datas(xs)
    jac = jax.jacrev(_wrap_fn(func), argnums=tuple(range(len(arrays))))(
        *arrays)
    if single:
        return Tensor(jac[0])
    return [Tensor(j) for j in jac]


def hessian(func, xs, is_batched=False):
    arrays, single = _datas(xs)
    hes = jax.hessian(_wrap_fn(func), argnums=tuple(range(len(arrays))))(
        *arrays)
    if single:
        return Tensor(hes[0][0])
    return [[Tensor(h) for h in row] for row in hes]


def vjp(func, xs, v=None):
    arrays, single = _datas(xs)
    out, vjp_fn = jax.vjp(_wrap_fn(func), *arrays)
    if v is None:
        cot = jnp.ones_like(out)
    else:
        cot = v._data if isinstance(v, Tensor) else v
    grads = vjp_fn(cot)
    gout = Tensor(grads[0]) if single else [Tensor(g) for g in grads]
    return Tensor(out), gout


def jvp(func, xs, v=None):
    arrays, single = _datas(xs)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    elif isinstance(v, Tensor):
        tangents = (v._data,)
    else:
        tangents = tuple(t._data for t in v)
    out, tang = jax.jvp(_wrap_fn(func), arrays, tangents)
    return Tensor(out), Tensor(tang)


def forward_grad(func, xs, v=None):
    return jvp(func, xs, v)[1]


def grad(func, xs, v=None):
    """Differentiable grad (create_graph=True semantics via jax.grad)."""
    arrays, single = _datas(xs)

    def scalar_fn(*ars):
        out = _wrap_fn(func)(*ars)
        return jnp.sum(out)
    g = jax.grad(scalar_fn, argnums=tuple(range(len(arrays))))(*arrays)
    if single:
        return Tensor(g[0])
    return [Tensor(x) for x in g]


class Jacobian:
    """Lazy Jacobian object (paddle.incubate.autograd.Jacobian parity):
    indexable like a matrix; the full matrix computes once on first use
    (jax.jacrev — XLA batches the rows; there is no per-row saving on
    TPU, so lazy-by-row would only add dispatches). With
    ``is_batched=True`` the leading axis is a batch dim: the result is
    the per-sample Jacobian stack [B, M, N] via vmap, not the
    (block-diagonal) cross-batch matrix."""

    def __init__(self, func, xs, is_batched=False):
        if isinstance(xs, (list, tuple)) and len(xs) > 1:
            raise NotImplementedError(
                "Jacobian/Hessian objects support a single input tensor; "
                "for multiple inputs use incubate.autograd.jacobian / "
                "hessian (returns one block per input)")
        self._func, self._xs = func, xs
        self._batched = bool(is_batched)
        self._mat = None

    def _compute(self):
        if not self._batched:
            out = jacobian(self._func, self._xs)
            return out if isinstance(out, Tensor) else out[0]
        x = self._xs[0] if isinstance(self._xs, (list, tuple)) \
            else self._xs
        jac = jax.vmap(jax.jacrev(
            lambda a: _wrap_fn(self._func)(a)))(x._data)
        return Tensor(jac)

    def _materialize(self):
        if self._mat is None:
            self._mat = self._compute()
        return self._mat

    @property
    def shape(self):
        return self._materialize().shape

    def __getitem__(self, item):
        return self._materialize()[item]


class Hessian(Jacobian):
    """Lazy Hessian object (paddle.incubate.autograd.Hessian parity);
    ``is_batched=True`` → per-sample Hessian stack [B, N, N]."""

    def _compute(self):
        if not self._batched:
            out = hessian(self._func, self._xs)
            return out if isinstance(out, Tensor) else out[0][0]
        x = self._xs[0] if isinstance(self._xs, (list, tuple)) \
            else self._xs
        hes = jax.vmap(jax.hessian(
            lambda a: _wrap_fn(self._func)(a)))(x._data)
        return Tensor(hes)


_prim_enabled = False


def enable_prim():
    """Upstream toggles composite-op decomposition into primitives for
    higher-order AD. jax IS primitive-based (every op already has a
    JVP/transpose rule), so this only records the flag for
    ``prim_enabled()`` readers."""
    global _prim_enabled
    _prim_enabled = True


def disable_prim():
    global _prim_enabled
    _prim_enabled = False


def prim_enabled():
    return _prim_enabled


__all__ += ["Jacobian", "Hessian", "enable_prim", "disable_prim",
            "prim_enabled"]
