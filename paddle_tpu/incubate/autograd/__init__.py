"""jax-native higher-order autograd (the role of paddle.incubate.autograd's
prim mechanism, UNVERIFIED): jacobian/hessian/vjp/jvp over functions of
Tensors, computed with jax transforms (exact, any order)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor

__all__ = ["jacobian", "hessian", "vjp", "jvp", "forward_grad", "grad"]


def _wrap_fn(func):
    """Lift a Tensor->Tensor function to arrays->arrays."""
    def fn(*arrays):
        ins = [Tensor(a, stop_gradient=False) for a in arrays]
        out = func(*ins)
        if isinstance(out, (list, tuple)):
            return tuple(o._data for o in out)
        return out._data
    return fn


def _datas(xs):
    if isinstance(xs, Tensor):
        return (xs._data,), True
    return tuple(x._data for x in xs), False


def jacobian(func, xs, is_batched=False):
    arrays, single = _datas(xs)
    jac = jax.jacrev(_wrap_fn(func), argnums=tuple(range(len(arrays))))(
        *arrays)
    if single:
        return Tensor(jac[0])
    return [Tensor(j) for j in jac]


def hessian(func, xs, is_batched=False):
    arrays, single = _datas(xs)
    hes = jax.hessian(_wrap_fn(func), argnums=tuple(range(len(arrays))))(
        *arrays)
    if single:
        return Tensor(hes[0][0])
    return [[Tensor(h) for h in row] for row in hes]


def vjp(func, xs, v=None):
    arrays, single = _datas(xs)
    out, vjp_fn = jax.vjp(_wrap_fn(func), *arrays)
    if v is None:
        cot = jnp.ones_like(out)
    else:
        cot = v._data if isinstance(v, Tensor) else v
    grads = vjp_fn(cot)
    gout = Tensor(grads[0]) if single else [Tensor(g) for g in grads]
    return Tensor(out), gout


def jvp(func, xs, v=None):
    arrays, single = _datas(xs)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    elif isinstance(v, Tensor):
        tangents = (v._data,)
    else:
        tangents = tuple(t._data for t in v)
    out, tang = jax.jvp(_wrap_fn(func), arrays, tangents)
    return Tensor(out), Tensor(tang)


def forward_grad(func, xs, v=None):
    return jvp(func, xs, v)[1]


def grad(func, xs, v=None):
    """Differentiable grad (create_graph=True semantics via jax.grad)."""
    arrays, single = _datas(xs)

    def scalar_fn(*ars):
        out = _wrap_fn(func)(*ars)
        return jnp.sum(out)
    g = jax.grad(scalar_fn, argnums=tuple(range(len(arrays))))(*arrays)
    if single:
        return Tensor(g[0])
    return [Tensor(x) for x in g]
