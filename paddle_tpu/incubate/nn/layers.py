"""Fused transformer layers (paddle.incubate.nn parity, UNVERIFIED)."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer.common import Linear, Dropout
from ...nn.layer.norm import LayerNorm
from ...nn.layer.transformer import MultiHeadAttention
from ...nn import functional as F

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]


class FusedMultiHeadAttention(Layer):
    """API-parity fused MHA; execution uses flash-attention + XLA fusion."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.attn = MultiHeadAttention(embed_dim, num_heads,
                                       attn_dropout_rate)
        self.dropout = Dropout(dropout_rate)
        self.norm = LayerNorm(embed_dim, epsilon)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        residual = query
        x = self.norm(query) if self.normalize_before else query
        out = self.attn(x, key, value, attn_mask, cache)
        if isinstance(out, tuple):
            out, cache = out
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-05, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward,
                              linear1_weight_attr, linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model,
                              linear2_weight_attr, linear2_bias_attr)
        self.dropout1 = Dropout(act_dropout_rate if act_dropout_rate
                                is not None else dropout_rate)
        self.dropout2 = Dropout(dropout_rate)
        self.norm = LayerNorm(d_model, epsilon)
        self.activation = activation

    def forward(self, src, cache=None):
        residual = src
        x = self.norm(src) if self.normalize_before else src
        h = getattr(F, self.activation)(self.linear1(x))
        h = self.linear2(self.dropout1(h))
        out = residual + self.dropout2(h)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate,
            attn_dropout_rate if attn_dropout_rate is not None
            else dropout_rate, normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        return self.ffn(out)


class FusedLinear(Layer):
    """incubate.nn.FusedLinear parity — one matmul+bias op (XLA fuses).
    With transpose_weight=True the weight is stored [out, in] and the
    matmul contracts its second dim (the reference's layout option)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose = bool(transpose_weight)
        if self._transpose:
            from ...nn import initializer as I
            self.weight = self.create_parameter(
                [out_features, in_features], attr=weight_attr,
                default_initializer=I.XavierNormal())
            self.bias = None if bias_attr is False else \
                self.create_parameter([out_features], attr=bias_attr,
                                      is_bias=True,
                                      default_initializer=I.Constant(0.0))
        else:
            self._inner = Linear(in_features, out_features,
                                 weight_attr=weight_attr,
                                 bias_attr=bias_attr)
            self.weight = self._inner.weight
            self.bias = self._inner.bias

    def forward(self, x):
        from .functional import fused_linear
        return fused_linear(x, self.weight, self.bias,
                            transpose_weight=self._transpose)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """incubate.nn parity: LN(residual + dropout(x + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ...nn import initializer as I
        from ...nn.param_attr import ParamAttr
        self.embed_dim = embed_dim
        self.linear_bias = self.create_parameter(
            [embed_dim], is_bias=True, default_initializer=I.Constant(0.0))
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            [embed_dim], is_bias=True, default_initializer=I.Constant(0.0))
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon

    def forward(self, x, residual):
        from .functional import fused_bias_dropout_residual_layer_norm
        return fused_bias_dropout_residual_layer_norm(
            x, residual, self.linear_bias, self.ln_scale, self.ln_bias,
            dropout_rate=self.dropout_rate, ln_epsilon=self.epsilon,
            training=self.training)


__all__ += ["FusedLinear", "FusedBiasDropoutResidualLayerNorm"]


class FusedDropoutAdd(Layer):
    """incubate.nn.FusedDropoutAdd parity: y = dropout(x) + residual in
    one fused op (XLA fuses the mask-scale-add chain)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        from .functional import fused_dropout_add
        return fused_dropout_add(x, y, p=self.p, training=self.training,
                                 mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedEcMoe(Layer):
    """incubate.nn.FusedEcMoe parity: fused expert-choice MoE FFN. Owns
    the per-expert up/down projections; the gate logits come in as an
    argument (the reference's signature: forward(x, gate))."""

    def __init__(self, hidden_size, inter_size, num_experts,
                 act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        if act_type not in ("gelu", "relu"):
            raise ValueError(f"unsupported act_type {act_type!r}")
        from ...nn import initializer as I
        self.act_type = act_type
        self.bmm0_weight = self.create_parameter(
            [num_experts, hidden_size, inter_size], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bmm0_bias = self.create_parameter(
            [num_experts, 1, inter_size], attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))
        self.bmm1_weight = self.create_parameter(
            [num_experts, inter_size, hidden_size], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bmm1_bias = self.create_parameter(
            [num_experts, 1, hidden_size], attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))

    def forward(self, x, gate):
        from .functional import fused_ec_moe
        return fused_ec_moe(x, gate, self.bmm0_weight,
                            self.bmm0_bias, self.bmm1_weight,
                            self.bmm1_bias, act_type=self.act_type)


__all__ += ["FusedDropoutAdd", "FusedEcMoe"]

class FusedMatmulBias(FusedLinear):
    """incubate.nn.FusedMatmulBias parity — same fused matmul+bias as
    FusedLinear (the reference distinguishes them by the cuBLASLt
    epilogue path; here XLA fuses both identically), so this is the
    FusedLinear body under the reference's other name."""
