"""Fused-op functional APIs (paddle.incubate.nn.functional parity,
UNVERIFIED: fused_multi_head_attention etc.).

On TPU "fused" means: written so XLA/Pallas emit one kernel. These
compositions hit the Pallas flash-attention / rms_norm kernels where
available and otherwise rely on XLA fusion — same API, TPU-native fusion
story (SURVEY.md §2.1 PHI fusion kernels row)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply
from ...ops.common import as_tensor
from ...nn import functional as F

__all__ = ["fused_multi_head_attention", "fused_feedforward",
           "fused_linear", "fused_linear_activation", "fused_rms_norm",
           "fused_layer_norm", "fused_dropout_add", "fused_bias_act",
           "fused_rotary_position_embedding",
           "fused_softmax_mask", "fused_softmax_mask_upper_triangle",
           "swiglu", "paged_attention"]


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    if transpose_weight:
        from ...ops.linalg import matmul
        out = matmul(x, weight, transpose_y=True)
        return out + bias if bias is not None else out
    return F.linear(x, weight, bias)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    from ...ops.linalg import matmul
    out = matmul(x, y, transpose_x=trans_x, transpose_y=trans_y)
    if bias is not None:
        out = out + bias
    return getattr(F, activation)(out)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    out = F.rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=1, **kw):
    x = as_tensor(x)
    shape = x.shape[begin_norm_axis:] if begin_norm_axis >= 0 else \
        x.shape[begin_norm_axis:]
    return F.layer_norm(x, shape, norm_weight, norm_bias, epsilon)


def fused_bias_act(x, bias=None, act_method="gelu",
                   compute_dtype="default", quant_scale=-1.0,
                   quant_round_type=0, quant_max_bound=0.0,
                   quant_min_bound=0.0, name=None):
    """act(x + bias) — the serving-path epilogue fusion (XLA fuses the
    add into the activation; the quant_* arguments configure the
    reference's int8 epilogue and are accepted for API parity, applied
    only when quant_scale > 0)."""
    x = as_tensor(x)
    args = [x]
    if bias is not None:
        args.append(as_tensor(bias))
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
           "silu": jax.nn.silu, "swish": jax.nn.silu,
           "sigmoid": jax.nn.sigmoid,
           "identity": lambda a: a}.get(act_method)
    if act is None:
        raise ValueError(f"unsupported act_method {act_method!r}")

    def fn(a, *b):
        h = a + b[0] if b else a
        if compute_dtype not in ("default", None):
            h = h.astype(compute_dtype)
        out = act(h)
        if quant_scale > 0:
            out = jnp.clip(jnp.round(out * quant_scale),
                           quant_min_bound, quant_max_bound)
        return out.astype(a.dtype) if quant_scale <= 0 else out

    return apply(fn, *args, name="fused_bias_act")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    return F.dropout(x, p, training=training, mode=mode) + y


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """RoPE applied to q/k (Pallas kernel on TPU when enabled)."""
    from ...ops.pallas import rope as rope_mod
    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        t = as_tensor(t)
        if sin is None or cos is None:
            s, c = rope_mod.build_sin_cos(t.shape[1], t.shape[-1],
                                          rotary_emb_base, t.dtype)
        else:
            s = sin._data if isinstance(sin, Tensor) else jnp.asarray(sin)
            c = cos._data if isinstance(cos, Tensor) else jnp.asarray(cos)
        pid = position_ids._data if isinstance(position_ids, Tensor) \
            else position_ids
        outs.append(apply(
            lambda a: rope_mod.apply_rope(a, s, c, pid,
                                          neox=use_neox_rotary_style),
            t, name="fused_rope"))
    return tuple(outs)


def paged_attention(q, key_pages, value_pages, block_tables, context_lens,
                    scale=None, name=None):
    """Serving decode-step attention over a paged KV cache (Pallas TPU
    kernel; see ops/paged_attention.py for layouts)."""
    from ...ops.paged_attention import paged_attention as _pa

    def fn(qq, kp, vp, bt, cl):
        return _pa(qq, kp, vp, bt, cl, scale)
    return apply(fn, as_tensor(q), as_tensor(key_pages),
                 as_tensor(value_pages), as_tensor(block_tables),
                 as_tensor(context_lens), name="paged_attention")


def fused_softmax_mask(x, mask, name=None):
    def fn(a, m):
        return jax.nn.softmax(a + m.astype(a.dtype), -1)
    return apply(fn, as_tensor(x), as_tensor(mask),
                 name="fused_softmax_mask")


def fused_softmax_mask_upper_triangle(x, name=None):
    def fn(a):
        s = a.shape[-1]
        mask = jnp.tril(jnp.ones((a.shape[-2], s), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e30), -1)
    return apply(fn, as_tensor(x), name="fused_softmax_mask_upper_triangle")


def swiglu(x, y=None, name=None):
    return F.swiglu(x, y)


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, name=None):
    """Fused MHA epilogue/prologue around the flash-attention core."""
    x = as_tensor(x)
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    qkvw = as_tensor(qkv_weight)  # [3, H, D, E] paddle layout
    nh, hd = qkvw.shape[1], qkvw.shape[2]

    def qkv_fn(a, w, *b):
        out = jnp.einsum("bse,thde->bsthd", a, w)
        if b:
            out = out + b[0][None, None]
        return out
    if qkv_bias is not None:
        qkv = apply(qkv_fn, x, qkvw, as_tensor(qkv_bias), name="fused_qkv")
    else:
        qkv = apply(qkv_fn, x, qkvw, name="fused_qkv")
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    ctx = F.scaled_dot_product_attention(q, k, v, attn_mask,
                                         attn_dropout_rate, False, training)
    b, s = ctx.shape[0], ctx.shape[1]
    from ...ops.manipulation import reshape
    ctx = reshape(ctx, [b, s, nh * hd])
    out = F.linear(ctx, linear_weight, linear_bias)
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = out + residual
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias,
                           ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode="upscale_in_train",
                      ring_id=-1, add_residual=True, name=None):
    x = as_tensor(x)
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], ln1_scale, ln1_bias, ln1_epsilon)
    h = F.linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    h = F.dropout(h, dropout1_rate, training=training, mode=mode)
    h = F.linear(h, linear2_weight, linear2_bias)
    h = F.dropout(h, dropout2_rate, training=training, mode=mode)
    if add_residual:
        h = h + residual
    if not pre_layer_norm:
        h = F.layer_norm(h, [h.shape[-1]], ln2_scale, ln2_bias, ln2_epsilon)
    return h


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode=None,
        name=None):
    """incubate parity: LN(residual + dropout(x + bias)). On TPU this is
    one XLA fusion; the API exists so reference model code runs
    unchanged."""
    h = x if bias is None else x + bias
    h = F.dropout(h, dropout_rate, training=training,
                  mode=mode or "upscale_in_train")
    h = residual + h
    d = h.shape[-1]
    return F.layer_norm(h, [d], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def masked_multihead_attention(x, cache_kv=None, bias=None,
                               src_mask=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None,
                               num_heads=None, head_dim=None,
                               compute_dtype="default", name=None,
                               **kwargs):
    """Decode-step MHA over a dense cache with optional additive mask —
    the reference's fused decoder-attention op (UNVERIFIED; mount empty).
    x: [B, 3*H*D] packed qkv for ONE step; cache_kv: [2, B, H, T, D]."""
    xt = as_tensor(x)
    b = xt.shape[0]
    if num_heads is None or head_dim is None:
        raise ValueError("masked_multihead_attention needs num_heads and "
                         "head_dim (packed-qkv layout is ambiguous)")
    if sequence_lengths is None:
        raise ValueError(
            "masked_multihead_attention needs sequence_lengths (tokens "
            "already cached per row): the write position of this step's "
            "k/v cannot be inferred from a fixed-capacity cache")
    if rotary_tensor is not None or beam_cache_offset is not None:
        raise NotImplementedError(
            "masked_multihead_attention: rotary_tensor/beam_cache_offset "
            "are not supported — apply "
            "fused_rotary_position_embedding to q/k before packing")
    H, D = int(num_heads), int(head_dim)
    ckv = as_tensor(cache_kv)
    sl = as_tensor(sequence_lengths)
    mask = as_tensor(src_mask) if src_mask is not None else None

    def fn(packed, cache, *rest):
        ri = 0
        lens = rest[ri]; ri += 1
        m = None
        if mask is not None:
            m = rest[ri]; ri += 1
        q, k, v = [packed.reshape(b, 3, H, D)[:, i] for i in range(3)]
        T = cache.shape[3]
        # append this step's k/v at position lens
        bidx = jnp.arange(b)
        kc = cache[0].at[bidx, :, lens, :].set(k)
        vc = cache[1].at[bidx, :, lens, :].set(v)
        logits = jnp.einsum("bhd,bhtd->bht", q, kc,
                            preferred_element_type=jnp.float32)
        logits = logits / jnp.sqrt(jnp.asarray(D, jnp.float32))
        valid = jnp.arange(T)[None, :] <= lens[:, None]
        logits = jnp.where(valid[:, None, :], logits, -1e30)
        if m is not None:
            logits = logits + m.reshape(b, 1, -1)[:, :, :T]
        p = jax.nn.softmax(logits, -1).astype(vc.dtype)
        out = jnp.einsum("bht,bhtd->bhd", p, vc).reshape(b, H * D)
        return out, jnp.stack([kc, vc])

    args = [xt, ckv, sl]
    if mask is not None:
        args.append(mask)
    return apply(fn, *args, n_outputs=2,
                 name="masked_multihead_attention")


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, pre_cache_length=0, name=None):
    """Ragged-batch attention: per-sequence valid lengths mask the
    attention matrix (the memory-efficient kernel's contract; XLA fuses
    the masked softmax). q/k/v: [B, H, S, D]; seq_lens/kv_seq_lens: [B]."""
    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    ql, kl = as_tensor(seq_lens), as_tensor(kv_seq_lens)

    def fn(qq, kk, vv, qlen, klen, *rest):
        import math as _math
        d = qq.shape[-1]
        s = scale if scale is not None else 1.0 / _math.sqrt(d)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qq, kk,
                            preferred_element_type=jnp.float32) * s
        sq, sk = logits.shape[-2], logits.shape[-1]
        okq = jnp.arange(sq)[None, :] < qlen.reshape(-1, 1)
        okk = jnp.arange(sk)[None, :] < klen.reshape(-1, 1)
        ok = okq[:, None, :, None] & okk[:, None, None, :]
        if causal:
            # align the diagonal to the LAST query: with a cached prefix
            # (sk > sq, e.g. decode/extend) query row i may see keys up
            # to (sk - sq) + i
            ok = ok & jnp.tril(jnp.ones((sq, sk), bool),
                               k=sk - sq)[None, None]
        if rest:
            logits = logits + rest[0].astype(logits.dtype)
        logits = jnp.where(ok, logits, -1e30)
        p = jax.nn.softmax(logits, -1).astype(vv.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, vv)
        # zero out padded query rows (softmax over all -1e30 is uniform)
        return out * okq[:, None, :, None].astype(out.dtype)

    args = [q, k, v, ql, kl]
    if mask is not None:
        args.append(as_tensor(mask))
    return apply(fn, *args,
                 name="variable_length_memory_efficient_attention")


def block_multihead_attention(q, key_pages, value_pages, block_tables,
                              context_lens, scale=None, name=None,
                              **kwargs):
    """Block/paged decode attention — alias surface of the reference's
    block_multihead_attention over the paged-KV pool (see
    ops/paged_attention.py for layouts)."""
    return paged_attention(q, key_pages, value_pages, block_tables,
                           context_lens, scale=scale)


def fused_moe(x, gate_weight, expert_weights_up, expert_weights_down,
              top_k=2, norm_topk_prob=True, name=None):
    """Dense-compute MoE forward (incubate fused_moe parity): softmax
    gate -> top-k routing -> SwiGLU-less expert FFNs, computed as
    grouped einsum over ALL experts then combined by routing weight —
    the TPU-friendly dense formulation (no scatter)."""
    xt = as_tensor(x)
    gw = as_tensor(gate_weight)
    wu = as_tensor(expert_weights_up)
    wd = as_tensor(expert_weights_down)

    def fn(a, g, up, down):
        b = a.reshape(-1, a.shape[-1])               # [N, d]
        logits = b @ g                                # [N, E]
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
        topv, topi = jax.lax.top_k(probs, top_k)      # [N, K]
        if norm_topk_prob:
            topv = topv / jnp.sum(topv, -1, keepdims=True)
        h = jnp.einsum("nd,edf->nef", b, up)          # [N, E, f]
        h = jax.nn.gelu(h, approximate=False)
        o = jnp.einsum("nef,efd->ned", h, down)       # [N, E, d]
        sel = jnp.take_along_axis(
            o, topi[:, :, None].astype(jnp.int32), 1)  # [N, K, d]
        out = jnp.sum(sel * topv[:, :, None].astype(sel.dtype), 1)
        return out.reshape(a.shape)

    return apply(fn, xt, gw, wu, wd, name="fused_moe")


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu", name=None):
    """incubate fused_ec_moe parity: expert-choice style fused MoE FFN
    with biases; dense-compute formulation (every expert computes, the
    gate combines). ``gate`` is the per-token expert logits
    [..., num_experts] (the reference signature); a 2-D [hidden, E]
    projection weight is also accepted (logits computed in-op)."""
    xt = as_tensor(x)

    def fn(a, g, w0, b0, w1, b1):
        b = a.reshape(-1, a.shape[-1])
        # per-token logits share x's leading dims (the documented
        # signature) — that takes priority over the weight reading when
        # a square x makes both interpretations shape-check
        if g.shape[:-1] == a.shape[:-1]:
            logits = g.reshape(-1, g.shape[-1])      # [N, E]
        elif g.ndim == 2 and g.shape[0] == b.shape[-1]:
            logits = b @ g                           # [hidden, E] weight
        else:
            logits = g.reshape(-1, g.shape[-1])
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
        b0 = b0.reshape(b0.shape[0], -1)             # [E,1,F] and [E,F]
        b1 = b1.reshape(b1.shape[0], -1)
        h = jnp.einsum("nd,edf->nef", b, w0) + b0[None]
        h = jax.nn.gelu(h, approximate=False) if act_type == "gelu" \
            else jnp.maximum(h, 0)
        o = jnp.einsum("nef,efd->ned", h, w1) + b1[None]
        out = jnp.einsum("ne,ned->nd", probs.astype(o.dtype), o)
        return out.reshape(a.shape)

    return apply(fn, xt, as_tensor(gate), as_tensor(bmm0_weight),
                 as_tensor(bmm0_bias), as_tensor(bmm1_weight),
                 as_tensor(bmm1_bias), name="fused_ec_moe")


__all__ += ["fused_bias_dropout_residual_layer_norm",
            "masked_multihead_attention",
            "variable_length_memory_efficient_attention",
            "block_multihead_attention", "fused_moe", "fused_ec_moe"]


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """incubate fused_matmul_bias parity — matmul with optional transposes
    and epilogue bias add, one XLA-fused op."""
    def fn(a, b, *rest):
        a = jnp.swapaxes(a, -1, -2) if transpose_x else a
        b = jnp.swapaxes(b, -1, -2) if transpose_y else b
        out = a @ b
        return out + rest[0] if rest else out

    args = [as_tensor(x), as_tensor(y)]
    if bias is not None:
        args.append(as_tensor(bias))
    return apply(fn, *args, name="fused_matmul_bias")


__all__ += ["fused_matmul_bias"]
