"""Fused-op functional APIs (paddle.incubate.nn.functional parity,
UNVERIFIED: fused_multi_head_attention etc.).

On TPU "fused" means: written so XLA/Pallas emit one kernel. These
compositions hit the Pallas flash-attention / rms_norm kernels where
available and otherwise rely on XLA fusion — same API, TPU-native fusion
story (SURVEY.md §2.1 PHI fusion kernels row)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply
from ...ops.common import as_tensor
from ...nn import functional as F

__all__ = ["fused_multi_head_attention", "fused_feedforward",
           "fused_linear", "fused_linear_activation", "fused_rms_norm",
           "fused_layer_norm", "fused_dropout_add", "fused_rotary_position_embedding",
           "fused_softmax_mask", "fused_softmax_mask_upper_triangle",
           "swiglu", "paged_attention"]


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    if transpose_weight:
        from ...ops.linalg import matmul
        out = matmul(x, weight, transpose_y=True)
        return out + bias if bias is not None else out
    return F.linear(x, weight, bias)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    from ...ops.linalg import matmul
    out = matmul(x, y, transpose_x=trans_x, transpose_y=trans_y)
    if bias is not None:
        out = out + bias
    return getattr(F, activation)(out)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    out = F.rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=1, **kw):
    x = as_tensor(x)
    shape = x.shape[begin_norm_axis:] if begin_norm_axis >= 0 else \
        x.shape[begin_norm_axis:]
    return F.layer_norm(x, shape, norm_weight, norm_bias, epsilon)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    return F.dropout(x, p, training=training, mode=mode) + y


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """RoPE applied to q/k (Pallas kernel on TPU when enabled)."""
    from ...ops.pallas import rope as rope_mod
    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        t = as_tensor(t)
        if sin is None or cos is None:
            s, c = rope_mod.build_sin_cos(t.shape[1], t.shape[-1],
                                          rotary_emb_base, t.dtype)
        else:
            s = sin._data if isinstance(sin, Tensor) else jnp.asarray(sin)
            c = cos._data if isinstance(cos, Tensor) else jnp.asarray(cos)
        pid = position_ids._data if isinstance(position_ids, Tensor) \
            else position_ids
        outs.append(apply(
            lambda a: rope_mod.apply_rope(a, s, c, pid,
                                          neox=use_neox_rotary_style),
            t, name="fused_rope"))
    return tuple(outs)


def paged_attention(q, key_pages, value_pages, block_tables, context_lens,
                    scale=None, name=None):
    """Serving decode-step attention over a paged KV cache (Pallas TPU
    kernel; see ops/paged_attention.py for layouts)."""
    from ...ops.paged_attention import paged_attention as _pa

    def fn(qq, kp, vp, bt, cl):
        return _pa(qq, kp, vp, bt, cl, scale)
    return apply(fn, as_tensor(q), as_tensor(key_pages),
                 as_tensor(value_pages), as_tensor(block_tables),
                 as_tensor(context_lens), name="paged_attention")


def fused_softmax_mask(x, mask, name=None):
    def fn(a, m):
        return jax.nn.softmax(a + m.astype(a.dtype), -1)
    return apply(fn, as_tensor(x), as_tensor(mask),
                 name="fused_softmax_mask")


def fused_softmax_mask_upper_triangle(x, name=None):
    def fn(a):
        s = a.shape[-1]
        mask = jnp.tril(jnp.ones((a.shape[-2], s), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e30), -1)
    return apply(fn, as_tensor(x), name="fused_softmax_mask_upper_triangle")


def swiglu(x, y=None, name=None):
    return F.swiglu(x, y)


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, name=None):
    """Fused MHA epilogue/prologue around the flash-attention core."""
    x = as_tensor(x)
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    qkvw = as_tensor(qkv_weight)  # [3, H, D, E] paddle layout
    nh, hd = qkvw.shape[1], qkvw.shape[2]

    def qkv_fn(a, w, *b):
        out = jnp.einsum("bse,thde->bsthd", a, w)
        if b:
            out = out + b[0][None, None]
        return out
    if qkv_bias is not None:
        qkv = apply(qkv_fn, x, qkvw, as_tensor(qkv_bias), name="fused_qkv")
    else:
        qkv = apply(qkv_fn, x, qkvw, name="fused_qkv")
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    ctx = F.scaled_dot_product_attention(q, k, v, attn_mask,
                                         attn_dropout_rate, False, training)
    b, s = ctx.shape[0], ctx.shape[1]
    from ...ops.manipulation import reshape
    ctx = reshape(ctx, [b, s, nh * hd])
    out = F.linear(ctx, linear_weight, linear_bias)
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = out + residual
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias,
                           ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode="upscale_in_train",
                      ring_id=-1, add_residual=True, name=None):
    x = as_tensor(x)
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], ln1_scale, ln1_bias, ln1_epsilon)
    h = F.linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    h = F.dropout(h, dropout1_rate, training=training, mode=mode)
    h = F.linear(h, linear2_weight, linear2_bias)
    h = F.dropout(h, dropout2_rate, training=training, mode=mode)
    if add_residual:
        h = h + residual
    if not pre_layer_norm:
        h = F.layer_norm(h, [h.shape[-1]], ln2_scale, ln2_bias, ln2_epsilon)
    return h
