from . import functional
from .layers import (FusedMultiHeadAttention, FusedFeedForward,
                     FusedTransformerEncoderLayer, FusedLinear,
                     FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd,
                     FusedEcMoe)

__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedLinear",
           "FusedBiasDropoutResidualLayerNorm", "FusedDropoutAdd",
           "FusedEcMoe"]
