from . import functional
from .layers import (FusedMultiHeadAttention, FusedFeedForward,
                     FusedTransformerEncoderLayer, FusedLinear,
                     FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd,
                     FusedEcMoe, FusedMatmulBias)

__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedLinear",
           "FusedBiasDropoutResidualLayerNorm", "FusedDropoutAdd",
           "FusedEcMoe", "FusedMatmulBias"]
