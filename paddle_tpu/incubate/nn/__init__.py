from . import functional
from .layers import (FusedMultiHeadAttention, FusedFeedForward,
                     FusedTransformerEncoderLayer, FusedLinear,
                     FusedBiasDropoutResidualLayerNorm)

__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedLinear",
           "FusedBiasDropoutResidualLayerNorm"]
