from . import functional
from .layers import (FusedMultiHeadAttention, FusedFeedForward,
                     FusedTransformerEncoderLayer)

__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]
