"""``paddle.incubate.autotune`` — kernel/layout/dataloader auto-tuning
(upstream python/paddle/incubate/autotune.py, UNVERIFIED).

This is the user entry point of the real autotuner subsystem
(``paddle_tpu.tuner``, docs/autotune.md). The ``kernel`` section now
drives an empirical search over registered tunable surfaces (Pallas
grouped-matmul tiles, flash-attention blocks, rms_norm row blocks, the
serving chunk ladder, the scan remat dose) backed by a persistent,
crash-safe tuning cache:

- ``set_config()`` / ``set_config({"kernel": {"enable": True}})`` —
  load-from-cache mode: kernels consult the cache (reloaded from the
  configured path) and fall back to static defaults on a miss.
- ``{"kernel": {"enable": True, "tune_on_first_call": True}}`` — a
  cache miss for a surface with a standalone trial builder triggers
  one synchronous search; the winner commits atomically and serves
  every later call and process.
- ``{"kernel": {"configs": {"flash_attention": {"block_q": 512,
  "block_kv": 512}}}}`` — manual pins: user override beats cache beats
  default (and for flash-attention, explicitly-set
  ``FLAGS_flash_attn_block_q/kv`` rank above even these —
  framework/flags.py documents the full precedence).
- ``{"kernel": {"enable": False}}`` — cache consultation off; every
  knob returns to its static default.
- ``{"kernel": {"cache_path": ...}}`` — repoint the persistent cache.

``layout`` stays XLA-delegated on TPU: operand layout assignment
happens inside XLA compilation, where the role of the reference's
layout-autotune pass already lives. ``dataloader`` is recorded for
``get_config()`` readers (the dataloader sizes itself from its own
config).
"""

from __future__ import annotations

import json
import warnings

__all__ = ["set_config"]

_config: dict = {}


def _apply_kernel_section(section: dict):
    from .. import tuner
    from ..tuner.sweeps import ensure_builtin_surfaces

    enable = bool(section.get("enable", True))
    repointed = False
    if "cache_path" in section and section["cache_path"]:
        tuner.set_cache_path(section["cache_path"])   # loads on build
        repointed = True
    if enable:
        ensure_builtin_surfaces()
        tuner.enable()
        if not repointed:
            # load-from-cache mode: pick up entries written by offline
            # sweeps since this process last looked (a just-repointed
            # cache already loaded in its constructor)
            tuner.get_cache().load()
    else:
        tuner.disable()
    tuner.set_tune_on_first_call(
        enable and bool(section.get("tune_on_first_call", False)))
    configs = section.get("configs") or {}
    if not isinstance(configs, dict):
        raise TypeError("autotune: kernel.configs must map surface "
                        "name -> config dict")
    for surface, cfg in configs.items():
        if cfg is not None and not isinstance(cfg, dict):
            raise TypeError(f"autotune: kernel.configs[{surface!r}] "
                            "must be a dict (or None to clear)")
        tuner.set_override(surface, cfg)


def set_config(config=None, **sections):
    """Accepts the upstream dict (or a JSON file path) with optional
    'kernel' / 'layout' / 'dataloader' sections; sections may also be
    passed as keywords (``set_config(kernel={...})``). See module
    docstring for the kernel-section schema."""
    global _config
    if config is None and not sections:
        _config = {"kernel": {"enable": True},
                   "layout": {"enable": True},
                   "dataloader": {"enable": True}}
        _apply_kernel_section(_config["kernel"])
        return
    if isinstance(config, str):
        with open(config) as fh:
            config = json.load(fh)
    if config is not None and not isinstance(config, dict):
        raise TypeError("autotune config must be a dict or JSON path")
    config = dict(config) if config else {}
    config.update(sections)
    _config = dict(config)
    for key in config:
        if key not in ("kernel", "layout", "dataloader"):
            warnings.warn(f"autotune: unknown section {key!r} ignored")
    kernel = config.get("kernel")
    if isinstance(kernel, dict):
        _apply_kernel_section(kernel)
    # layout tuning is XLA's job on TPU (delegated at compile time);
    # the dataloader section is recorded for get_config() readers


def get_config() -> dict:
    return dict(_config)
