"""``paddle.incubate.autotune`` — kernel/layout/dataloader auto-tuning
config (upstream python/paddle/incubate/autotune.py, UNVERIFIED).

TPU-native: XLA autotunes kernel selection and layout during compilation
(the role of the reference's kernel/layout autotune passes), so
``set_config`` records the request, applies the pieces that have a jax
knob, and reports the rest as XLA-delegated."""

from __future__ import annotations

import json
import warnings

__all__ = ["set_config"]

_config: dict = {}


def set_config(config=None):
    """Accepts the upstream dict (or a JSON file path) with optional
    'kernel' / 'layout' / 'dataloader' sections."""
    global _config
    if config is None:
        _config = {"kernel": {"enable": True},
                   "layout": {"enable": True},
                   "dataloader": {"enable": True}}
        return
    if isinstance(config, str):
        with open(config) as fh:
            config = json.load(fh)
    if not isinstance(config, dict):
        raise TypeError("autotune config must be a dict or JSON path")
    _config = dict(config)
    for key in config:
        if key not in ("kernel", "layout", "dataloader"):
            warnings.warn(f"autotune: unknown section {key!r} ignored")
    # kernel/layout tuning is XLA's job on TPU (delegated at compile
    # time); the dataloader section is recorded for get_config() readers


def get_config() -> dict:
    return dict(_config)
