"""``paddle.incubate.optimizer.functional`` — functional minimizers
(upstream python/paddle/incubate/optimizer/functional/{bfgs,lbfgs}.py,
UNVERIFIED; reference mount empty).

TPU-native: both lower to ``jax.scipy.optimize.minimize`` — the whole
minimization loop (line search included) is one compiled XLA program
with ``lax.while_loop`` control flow, instead of the reference's python
loop of per-op kernel launches."""

from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import Tensor
from ...ops.common import as_tensor

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _run(method, objective_func, initial_position, max_iters, tol,
         dtype):
    x0 = as_tensor(initial_position)._data
    if dtype is not None:
        from ...framework.core import to_jax_dtype
        x0 = x0.astype(to_jax_dtype(dtype))

    def fn(x):
        out = objective_func(Tensor(x))
        return out._data if isinstance(out, Tensor) else jnp.asarray(out)

    import jax
    from jax.scipy.optimize import minimize as jax_minimize

    res = jax_minimize(fn, x0, method=method, tol=tol,
                       options={"maxiter": int(max_iters)})
    grad = jax.grad(fn)(res.x)
    # upstream return contract:
    # (is_converge, num_func_calls, position, objective_value,
    #  objective_gradient)
    return (Tensor(res.success), Tensor(res.nfev),
            Tensor(res.x), Tensor(res.fun), Tensor(grad))


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn="strong_wolfe", max_line_search_iters=50,
                  initial_step_length=1.0, dtype="float32", name=None):
    return _run("BFGS", objective_func, initial_position, max_iters,
                tolerance_grad, dtype)


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-8,
                   tolerance_change=1e-8, line_search_fn="strong_wolfe",
                   max_line_search_iters=50, initial_step_length=1.0,
                   dtype="float32", name=None):
    return _run("l-bfgs-experimental-do-not-rely-on-this",
                objective_func, initial_position, max_iters,
                tolerance_grad, dtype)
