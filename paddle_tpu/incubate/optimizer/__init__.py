"""``paddle.incubate.optimizer`` — LookAhead / ModelAverage
(python/paddle/incubate/optimizer/ parity, UNVERIFIED: lookahead.py,
modelaverage.py).

Both are wrapper optimizers over an inner optimizer: LookAhead blends
slow/fast weights every k steps; ModelAverage keeps a running average of
parameters applied at eval time."""

from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import Tensor, no_grad
from ...optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """k fast steps with the inner optimizer, then pull the slow weights
    toward the fast ones: slow += alpha * (fast - slow); fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self._inner = inner_optimizer
        self.alpha = float(alpha)
        self.k = max(int(k), 1)
        self._step_count = 0
        self._slow: dict[int, jnp.ndarray] = {}

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        self._inner.step()
        self._step_count += 1
        if self._step_count % self.k:
            return
        with no_grad():
            for p in self._inner._parameter_list:
                slow = self._slow.get(id(p))
                if slow is None:
                    slow = p._data.astype(jnp.float32)
                slow = slow + self.alpha * (
                    p._data.astype(jnp.float32) - slow)
                self._slow[id(p)] = slow
                p.set_data(slow.astype(p.dtype))

    def minimize(self, loss, *a, **k):
        out = self._inner.minimize(loss, *a, **k)
        return out

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        sd = self._inner.state_dict()
        sd["@lookahead_step"] = self._step_count
        return sd

    def set_state_dict(self, state):
        self._step_count = int(state.pop("@lookahead_step", 0))
        self._inner.set_state_dict(state)


class ModelAverage(Optimizer):
    """Maintains sum of parameter values over steps; ``apply()`` swaps in
    the average (eval), ``restore()`` swaps back (paddle's
    min/max_average_window control when the accumulator restarts)."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(0.0, parameters, None, None, name)
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._sum: dict[int, jnp.ndarray] = {}
        self._num = 0
        self._backup: dict[int, jnp.ndarray] | None = None

    def step(self):
        with no_grad():
            for p in self._parameter_list:
                acc = self._sum.get(id(p))
                v = p._data.astype(jnp.float32)
                self._sum[id(p)] = v if acc is None else acc + v
        self._num += 1
        # restart the window once it outgrows max_average_window
        if self._num > self.max_w and self._num > self.min_w:
            for p in self._parameter_list:
                self._sum[id(p)] = p._data.astype(jnp.float32)
            self._num = 1

    def apply(self, executor=None, need_restore=True):
        """Swap averaged weights in (context-manager friendly)."""
        self._backup = {}
        with no_grad():
            for p in self._parameter_list:
                self._backup[id(p)] = p._data
                acc = self._sum.get(id(p))
                if acc is not None and self._num:
                    p.set_data((acc / self._num).astype(p.dtype))
        import contextlib

        @contextlib.contextmanager
        def ctx():
            try:
                yield
            finally:
                if need_restore:
                    self.restore()
        return ctx()

    def restore(self, executor=None):
        if self._backup:
            for p in self._parameter_list:
                b = self._backup.get(id(p))
                if b is not None:
                    p.set_data(b)
        self._backup = None


from ...optimizer.optimizer import LBFGS  # noqa: E402 — re-export (upstream
# incubate.optimizer.LBFGS graduated to paddle.optimizer; both paths work)
from ...optimizer import Lamb as _Lamb  # noqa: E402


class DistributedFusedLamb(_Lamb):
    """paddle.incubate.DistributedFusedLamb parity. The reference fuses
    multi-tensor LAMB kernels and shards optimizer states across the data
    group by hand; here XLA fuses the update and state sharding comes
    from wrapping with ``fleet.distributed_optimizer`` / GSPMD — so this
    IS Lamb, keeping the extra constructor knobs for signature parity."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, use_master_param_norm=True,
                 gradient_accumulation_steps=1, use_master_acc_grad=True,
                 nproc_per_node=None, use_hierarchical_allreduce=False,
                 name=None):
        super().__init__(learning_rate=learning_rate,
                         lamb_weight_decay=lamb_weight_decay,
                         beta1=beta1, beta2=beta2, epsilon=epsilon,
                         parameters=parameters, grad_clip=grad_clip,
                         exclude_from_weight_decay_fn=(
                             exclude_from_weight_decay_fn))


__all__ += ["LBFGS", "DistributedFusedLamb"]


from . import functional  # noqa: E402,F401

__all__ += ["functional"]
