"""``paddle.incubate`` — experimental APIs (python/paddle/incubate/ parity,
UNVERIFIED): fused-op functional APIs, jax-native higher-order autograd,
MoE layers."""

from . import nn
from . import autograd
from . import asp
from . import optimizer
from .nn import functional
from .optimizer import LookAhead, ModelAverage

__all__ = ["nn", "autograd", "functional", "optimizer", "LookAhead",
           "ModelAverage", "softmax_mask_fuse",
           "graph_send_recv", "segment_sum", "segment_mean", "segment_max",
           "segment_min"]


def softmax_mask_fuse(x, mask, name=None):
    from .nn.functional import fused_softmax_mask
    return fused_softmax_mask(x, mask)


def _segment(op):
    import jax
    import jax.numpy as jnp
    from ..framework.core import Tensor, apply
    from ..ops.common import as_tensor

    def seg(data, segment_ids, name=None):
        data, segment_ids = as_tensor(data), as_tensor(segment_ids)
        num = int(jnp.max(segment_ids._data)) + 1 if \
            segment_ids._data.size else 0

        def fn(d, ids):
            if op == "sum":
                return jax.ops.segment_sum(d, ids, num) if hasattr(
                    jax.ops, "segment_sum") else \
                    jax.ops.segment_sum(d, ids, num)
            if op == "mean":
                s = jax.ops.segment_sum(d, ids, num)
                c = jax.ops.segment_sum(jnp.ones_like(ids,
                                                      dtype=d.dtype),
                                        ids, num)
                shape = (num,) + (1,) * (d.ndim - 1)
                return s / jnp.maximum(c.reshape(shape), 1)
            if op == "max":
                return jax.ops.segment_max(d, ids, num)
            return jax.ops.segment_min(d, ids, num)
        return apply(fn, data, segment_ids, name=f"segment_{op}")
    return seg


segment_sum = _segment("sum")
segment_mean = _segment("mean")
segment_max = _segment("max")
segment_min = _segment("min")


def graph_send_recv(x, src_index, dst_index, reduce_op="sum",
                    out_size=None, name=None):
    import jax
    import jax.numpy as jnp
    from ..framework.core import apply
    from ..ops.common import as_tensor
    x = as_tensor(x)
    src = as_tensor(src_index)
    dst = as_tensor(dst_index)
    n = out_size or x.shape[0]

    def fn(xx, s, d):
        gathered = jnp.take(xx, s, axis=0)
        if reduce_op in ("sum", "mean"):
            out = jax.ops.segment_sum(gathered, d, n)
            if reduce_op == "mean":
                cnt = jax.ops.segment_sum(
                    jnp.ones_like(d, dtype=xx.dtype), d, n)
                shape = (n,) + (1,) * (xx.ndim - 1)
                out = out / jnp.maximum(cnt.reshape(shape), 1)
            return out
        if reduce_op == "max":
            return jax.ops.segment_max(gathered, d, n)
        return jax.ops.segment_min(gathered, d, n)
    return apply(fn, x, src, dst, name="graph_send_recv")
