"""``paddle.incubate`` — experimental APIs (python/paddle/incubate/ parity,
UNVERIFIED): fused-op functional APIs, jax-native higher-order autograd,
MoE layers."""

from . import nn
from . import autograd
from . import asp
from . import optimizer
from . import autotune
from .nn import functional
from .optimizer import LookAhead, ModelAverage, DistributedFusedLamb
from . import multiprocessing  # noqa: F401

__all__ = ["nn", "autograd", "functional", "optimizer", "LookAhead",
           "ModelAverage", "softmax_mask_fuse", "autotune",
           "DistributedFusedLamb",
           "graph_send_recv", "segment_sum", "segment_mean", "segment_max",
           "segment_min"]


def softmax_mask_fuse(x, mask, name=None):
    from .nn.functional import fused_softmax_mask
    return fused_softmax_mask(x, mask)


def _segment(op):
    import jax
    import jax.numpy as jnp
    from ..framework.core import Tensor, apply
    from ..ops.common import as_tensor

    def seg(data, segment_ids, name=None):
        data, segment_ids = as_tensor(data), as_tensor(segment_ids)
        num = int(jnp.max(segment_ids._data)) + 1 if \
            segment_ids._data.size else 0

        def fn(d, ids):
            if op == "sum":
                return jax.ops.segment_sum(d, ids, num) if hasattr(
                    jax.ops, "segment_sum") else \
                    jax.ops.segment_sum(d, ids, num)
            if op == "mean":
                s = jax.ops.segment_sum(d, ids, num)
                c = jax.ops.segment_sum(jnp.ones_like(ids,
                                                      dtype=d.dtype),
                                        ids, num)
                shape = (num,) + (1,) * (d.ndim - 1)
                return s / jnp.maximum(c.reshape(shape), 1)
            if op == "max":
                return jax.ops.segment_max(d, ids, num)
            return jax.ops.segment_min(d, ids, num)
        return apply(fn, data, segment_ids, name=f"segment_{op}")
    return seg


segment_sum = _segment("sum")
segment_mean = _segment("mean")
segment_max = _segment("max")
segment_min = _segment("min")


def graph_send_recv(x, src_index, dst_index, reduce_op="sum",
                    out_size=None, name=None):
    import jax
    import jax.numpy as jnp
    from ..framework.core import apply
    from ..ops.common import as_tensor
    x = as_tensor(x)
    src = as_tensor(src_index)
    dst = as_tensor(dst_index)
    n = out_size or x.shape[0]

    def fn(xx, s, d):
        gathered = jnp.take(xx, s, axis=0)
        if reduce_op in ("sum", "mean"):
            out = jax.ops.segment_sum(gathered, d, n)
            if reduce_op == "mean":
                cnt = jax.ops.segment_sum(
                    jnp.ones_like(d, dtype=xx.dtype), d, n)
                shape = (n,) + (1,) * (xx.ndim - 1)
                out = out / jnp.maximum(cnt.reshape(shape), 1)
            return out
        if reduce_op == "max":
            return jax.ops.segment_max(gathered, d, n)
        return jax.ops.segment_min(gathered, d, n)
    return apply(fn, x, src, dst, name="graph_send_recv")


def softmax_mask_fuse_upper_triangle(x, name=None):
    from .nn.functional import fused_softmax_mask_upper_triangle
    return fused_softmax_mask_upper_triangle(x)


def identity_loss(x, reduction="none", name=None):
    """paddle.incubate.identity_loss — mark a value as the loss for the
    graph builder; numerically a (reduced) identity."""
    from ..ops import math as M
    if reduction in ("mean", 1):
        return M.mean(x)
    if reduction in ("sum", 2):
        return M.sum(x)
    return x


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """K-hop neighbor sampling: repeated 1-hop sampling + reindex
    (paddle.incubate.graph_khop_sampler parity; host-side like the
    geometric samplers)."""
    from ..geometric import reindex_graph, sample_neighbors
    import numpy as np
    from ..framework.core import Tensor
    import jax.numpy as jnp

    cur = input_nodes
    all_rows, all_cols = [], []
    for size in list(sample_sizes):
        neigh, count = sample_neighbors(row, colptr, cur,
                                        sample_size=int(size))
        cur_np = np.asarray(cur._data if isinstance(cur, Tensor) else cur)
        cnt_np = np.asarray(count._data)
        src = np.repeat(cur_np, cnt_np)
        dst = np.asarray(neigh._data)
        all_rows.append(dst)
        all_cols.append(src)
        cur = Tensor(jnp.asarray(np.unique(dst)))
    rows = np.concatenate(all_rows) if all_rows else np.zeros(0, np.int64)
    cols = np.concatenate(all_cols) if all_cols else np.zeros(0, np.int64)
    nodes = np.unique(np.concatenate(
        [np.asarray(input_nodes._data if isinstance(input_nodes, Tensor)
                    else input_nodes), rows, cols]))
    remap = {int(n): i for i, n in enumerate(nodes)}
    r2 = np.asarray([remap[int(v)] for v in rows], np.int64)
    c2 = np.asarray([remap[int(v)] for v in cols], np.int64)
    return (Tensor(jnp.asarray(r2)), Tensor(jnp.asarray(c2)),
            Tensor(jnp.asarray(nodes)),
            Tensor(jnp.asarray(np.zeros(0, np.int64))))


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    from ..geometric import sample_neighbors as _sn
    return _sn(row, colptr, input_nodes, sample_size=sample_size)


def graph_reindex(x, neighbors, count, value_buffer=None,
                  index_buffer=None, flag_buffer_hashtable=False,
                  name=None):
    from ..geometric import reindex_graph as _rg
    return _rg(x, neighbors, count)


__all__ += ["softmax_mask_fuse_upper_triangle", "identity_loss",
            "graph_khop_sampler", "graph_sample_neighbors",
            "graph_reindex"]
