"""``paddle.incubate.multiprocessing`` — tensor-aware multiprocessing
(reference: ``python/paddle/incubate/multiprocessing``, UNVERIFIED —
mount empty). The reference teaches the stdlib pickler to move GPU/CPU
tensors through shared memory (cuda IPC handles / mmap'd files).

TPU-native stance: device arrays are not shareable across host
processes (each process owns its PJRT client), so a Tensor crossing a
process boundary travels as its HOST value — pickled via
``reduction``'s registered reducer as (dtype, numpy bytes) and rebuilt
as a CPU-backed Tensor on the other side. That is exactly the behavior
the DataLoader worker pool relies on; this module makes it available
through the reference's module surface (``get_context``, ``Process``,
``Queue``, ``Pool``, ``reductions``-style registration).
"""

from __future__ import annotations

import multiprocessing as _std

import numpy as np

__all__ = ["get_context", "Process", "Queue", "SimpleQueue", "Pool",
           "Pipe", "init_reductions"]


def _reduce_tensor(t):
    from ..framework.core import Tensor
    arr = np.asarray(t._data)
    return (_rebuild_tensor, (arr, bool(t.stop_gradient),
                              getattr(t, "name", "") or ""))


def _rebuild_tensor(arr, stop_gradient, name):
    import jax.numpy as jnp
    from ..framework.core import Tensor
    t = Tensor(jnp.asarray(arr), stop_gradient=stop_gradient)
    if name:
        t.name = name
    return t


def init_reductions():
    """Register the Tensor reducer with the stdlib ForkingPickler
    (idempotent). Called automatically on module import, matching the
    reference's import-time hook."""
    from multiprocessing.reduction import ForkingPickler
    from ..framework.core import Tensor
    ForkingPickler.register(Tensor, _reduce_tensor)


init_reductions()


def get_context(method=None):
    """multiprocessing context with tensor pickling active. ``spawn``
    is the default (fork inherits the parent's PJRT/TPU client state,
    which is unsafe — same policy as io.DataLoader's worker pool)."""
    return _std.get_context(method or "spawn")


def Process(*args, **kwargs):
    return get_context().Process(*args, **kwargs)


def Queue(*args, **kwargs):
    return get_context().Queue(*args, **kwargs)


def SimpleQueue(*args, **kwargs):
    return get_context().SimpleQueue(*args, **kwargs)


def Pool(*args, **kwargs):
    return get_context().Pool(*args, **kwargs)


def Pipe(*args, **kwargs):
    return get_context().Pipe(*args, **kwargs)
