"""Linear algebra ops (paddle/tensor/linalg.py parity, UNVERIFIED).

Matmuls are the MXU path: ``matmul`` honors the global matmul precision flag
and the AMP auto-cast policy (bf16-first on TPU).
"""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply
from ..framework import flags
from .common import as_tensor

__all__ = [
    "matmul", "mm", "bmm", "dot", "t", "transpose_matmul", "dist", "norm",
    "cond", "cross", "cholesky", "cholesky_solve", "eig", "eigh", "eigvals",
    "eigvalsh", "det", "slogdet", "inv", "pinv", "matrix_power", "matrix_rank",
    "mv", "multi_dot", "qr", "lu", "svd", "solve", "triangular_solve",
    "lstsq", "corrcoef", "cov", "histogram", "bincount", "householder_product",
]


def _precision():
    p = flags.flag("FLAGS_tpu_matmul_precision")
    return {"default": None, "high": jax.lax.Precision.HIGH,
            "highest": jax.lax.Precision.HIGHEST}.get(p, None)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    x, y = as_tensor(x), as_tensor(y)
    from ..amp.auto_cast import maybe_cast_matmul
    x, y = maybe_cast_matmul(x, y)

    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b, precision=_precision())
    return apply(fn, x, y, name="matmul")


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return apply(lambda a, b: (a * b).sum(-1), as_tensor(x), as_tensor(y),
                 name="dot")


def mv(x, vec, name=None):
    return apply(lambda a, b: a @ b, as_tensor(x), as_tensor(vec), name="mv")


def t(input, name=None):
    input = as_tensor(input)
    if input.ndim < 2:
        return apply(lambda a: a, input, name="t")
    return apply(lambda a: a.T, input, name="t")


def transpose_matmul(x, y, name=None):
    return matmul(x, y, transpose_x=True)


def dist(x, y, p=2, name=None):
    def fn(a, b):
        d = jnp.abs(a - b).reshape(-1)
        if p == float("inf"):
            return jnp.max(d)
        if p == float("-inf"):
            return jnp.min(d)
        if p == 0:
            return jnp.sum(d != 0).astype(a.dtype)
        return jnp.sum(d ** p) ** (1.0 / p)
    return apply(fn, as_tensor(x), as_tensor(y), name="dist")


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = as_tensor(x)

    def fn(a):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.square(a)))
            return jnp.linalg.norm(a, ord=None, axis=_ax(axis),
                                   keepdims=keepdim)
        if p == float("inf") or p == float("-inf"):
            if axis is None:
                d = jnp.abs(a).reshape(-1)
                return jnp.max(d) if p > 0 else jnp.min(d)
            return jnp.linalg.norm(a, ord=p, axis=_ax(axis), keepdims=keepdim)
        if axis is None:
            d = jnp.abs(a).reshape(-1)
            return jnp.sum(d ** p) ** (1.0 / p)
        return jnp.linalg.norm(a, ord=p, axis=_ax(axis), keepdims=keepdim)
    return apply(fn, x, name="norm")


def _ax(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis) if axis is not None else None


def cond(x, p=None, name=None):
    return apply(lambda a: jnp.linalg.cond(a, p=p), as_tensor(x), name="cond")


def cross(x, y, axis=9, name=None):
    x, y = as_tensor(x), as_tensor(y)
    ax = axis
    if ax == 9:  # paddle default: first axis with dim 3
        ax = next(i for i, s in enumerate(x.shape) if s == 3)
    return apply(lambda a, b: jnp.cross(a, b, axis=int(ax)), x, y,
                 name="cross")


def cholesky(x, upper=False, name=None):
    return apply(lambda a: jnp.linalg.cholesky(
        jnp.swapaxes(a, -1, -2) if upper else a).swapaxes(-1, -2)
        if upper else jnp.linalg.cholesky(a), as_tensor(x), name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, l):
        ll = jnp.swapaxes(l, -1, -2) if upper else l
        z = jax.scipy.linalg.solve_triangular(ll, b, lower=True)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(ll, -1, -2), z, lower=False)
    return apply(fn, as_tensor(x), as_tensor(y), name="cholesky_solve")


def eig(x, name=None):
    x = as_tensor(x)
    import numpy as np
    w, v = np.linalg.eig(np.asarray(x._data))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    outs = apply(lambda a: jnp.linalg.eigh(a, UPLO=UPLO), as_tensor(x),
                 n_outputs=2, name="eigh")
    return outs[0], outs[1]


def eigvals(x, name=None):
    import numpy as np
    x = as_tensor(x)
    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(x._data))))


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), as_tensor(x),
                 name="eigvalsh")


def det(x, name=None):
    return apply(jnp.linalg.det, as_tensor(x), name="det")


def slogdet(x, name=None):
    outs = apply(lambda a: tuple(jnp.linalg.slogdet(a)), as_tensor(x),
                 n_outputs=2, name="slogdet")
    from .manipulation import stack
    return stack([outs[0], outs[1]], axis=0)


def inv(x, name=None):
    return apply(jnp.linalg.inv, as_tensor(x), name="inv")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.pinv(a, rtol=rcond,
                                           hermitian=hermitian),
                 as_tensor(x), name="pinv")


def matrix_power(x, n, name=None):
    return apply(lambda a: jnp.linalg.matrix_power(a, n), as_tensor(x),
                 name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return Tensor(jnp.linalg.matrix_rank(as_tensor(x)._data, rtol=tol))


def multi_dot(x, name=None):
    ts = [as_tensor(t_) for t_ in x]
    return apply(lambda *xs: jnp.linalg.multi_dot(xs), *ts, name="multi_dot")


def qr(x, mode="reduced", name=None):
    outs = apply(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), as_tensor(x),
                 n_outputs=2, name="qr")
    return outs[0], outs[1]


def lu(x, pivot=True, get_infos=False, name=None):
    x = as_tensor(x)
    lu_, piv = apply(lambda a: tuple(jax.scipy.linalg.lu_factor(a)), x,
                     n_outputs=2, name="lu")
    piv = Tensor((piv._data + 1).astype(jnp.int32))
    if get_infos:
        info = Tensor(jnp.zeros((), jnp.int32))
        return lu_, piv, info
    return lu_, piv


def svd(x, full_matrices=False, name=None):
    outs = apply(lambda a: tuple(jnp.linalg.svd(
        a, full_matrices=full_matrices)), as_tensor(x), n_outputs=3,
        name="svd")
    return outs[0], outs[1], outs[2]


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, as_tensor(x), as_tensor(y), name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply(fn, as_tensor(x), as_tensor(y), name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    outs = apply(lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)),
                 as_tensor(x), as_tensor(y), n_outputs=4, name="lstsq")
    return outs


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), as_tensor(x),
                 name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(lambda a: jnp.cov(a, rowvar=rowvar,
                                   ddof=1 if ddof else 0),
                 as_tensor(x), name="cov")


def histogram(input, bins=100, min=0, max=0, name=None):
    input = as_tensor(input)
    lo, hi = min, max
    if lo == 0 and hi == 0:
        lo = float(jnp.min(input._data))
        hi = float(jnp.max(input._data))
    hist, _ = jnp.histogram(input._data, bins=bins, range=(lo, hi))
    return Tensor(hist.astype(jnp.int64))


def bincount(x, weights=None, minlength=0, name=None):
    x = as_tensor(x)
    w = as_tensor(weights)._data if weights is not None else None
    import numpy as np
    out = np.bincount(np.asarray(x._data), weights=np.asarray(w) if w is not None else None,
                      minlength=minlength)
    return Tensor(jnp.asarray(out))


def householder_product(x, tau, name=None):
    def fn(a, t_):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() \
            if a.ndim > 2 else eye
        for i in range(n):
            v = jnp.concatenate([
                jnp.zeros(a.shape[:-2] + (i,), a.dtype),
                jnp.ones(a.shape[:-2] + (1,), a.dtype),
                a[..., i + 1:, i]], axis=-1)
            vv = v[..., :, None] * v[..., None, :]
            q = q @ (jnp.eye(m, dtype=a.dtype) - t_[..., i, None, None] * vv)
        return q[..., :, :n] if m >= n else q
    return apply(fn, as_tensor(x), as_tensor(tau), name="householder_product")



# ---- long-tail linalg (round-2 breadth) -----------------------------------
# (matrix_exp / cdist / vecdot / ormqr / lu_unpack / svd_lowrank /
#  pca_lowrank / vector_norm / matrix_norm / matrix_transpose live in
#  paddle_tpu/linalg.py — the namespace upstream exposes them under)

def cholesky_inverse(x, upper=False, name=None):
    """Inverse of A from its Cholesky factor (paddle.linalg.cholesky_inverse)."""
    def fn(a):
        ident = jnp.eye(a.shape[-1], dtype=a.dtype)
        inv_f = jax.scipy.linalg.solve_triangular(a, ident, lower=not upper)
        # A = L L^T -> A^-1 = L^-T L^-1  (or U^-1 U^-T for upper)
        if upper:
            return inv_f @ inv_f.T
        return inv_f.T @ inv_f
    return apply(fn, as_tensor(x), name="cholesky_inverse")


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances of one row batch (paddle.pdist)."""
    x = as_tensor(x)
    n = x.shape[0]
    iu = jnp.triu_indices(n, k=1)

    def fn(a):
        d = a[:, None, :] - a[None, :, :]
        if p == 2.0:
            full = jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-30)
        elif jnp.isinf(p):
            full = jnp.max(jnp.abs(d), axis=-1)
        else:
            full = jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
        return full[iu]
    return apply(fn, x, name="pdist")


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    x = as_tensor(input)
    lo, hi = float(min), float(max)

    def fn(a):
        if lo == 0 and hi == 0:
            mn, mx = jnp.min(a), jnp.max(a)
        else:
            mn = jnp.asarray(lo, a.dtype)
            mx = jnp.asarray(hi, a.dtype)
        mx = jnp.where(mx == mn, mn + 1, mx)
        return jnp.linspace(mn, mx, int(bins) + 1)
    return apply(fn, x, name="histogram_bin_edges", differentiable=False)


__all__ += ["cholesky_inverse", "pdist", "histogram_bin_edges"]


def inverse(x, name=None):
    """paddle.inverse — alias of linalg.inv at the top level."""
    return inv(x, name=name)


__all__ += ["inverse"]


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """paddle.linalg.lu_unpack — split ``lu()``'s packed output into
    (P, L, U) with A = P @ L @ U. Pivots are 1-based (LAPACK/``lu()``
    convention)."""
    x, y = as_tensor(x), as_tensor(y)
    m, n = x.shape[-2], x.shape[-1]
    k = builtins.min(m, n)

    def fn(a, piv):
        eye_k = jnp.eye(m, k, dtype=a.dtype)
        l_full = jnp.tril(a[..., :k], -1) + eye_k
        u_full = jnp.triu(a[..., :k, :])

        def perm_of(p1):
            # apply LAPACK row swaps to the identity permutation
            def body(i, perm):
                j = p1[i] - 1
                pi, pj = perm[i], perm[j]
                perm = perm.at[i].set(pj)
                return perm.at[j].set(pi)
            return jax.lax.fori_loop(0, k, body, jnp.arange(m))

        batch = a.shape[:-2]
        if batch:
            perms = jax.vmap(perm_of)(piv.reshape((-1, k))).reshape(
                batch + (m,))
        else:
            perms = perm_of(piv)
        # rows of A were swapped into LU order: P undoes that on the left
        p_mat = jax.nn.one_hot(perms, m, dtype=a.dtype)
        p_mat = jnp.swapaxes(p_mat, -1, -2)
        return p_mat, l_full, u_full

    p_t, l_t, u_t = apply(fn, x, y, n_outputs=3, name="lu_unpack")
    return (p_t if unpack_pivots else None,
            l_t if unpack_ludata else None,
            u_t if unpack_ludata else None)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """paddle.cdist — batched pairwise p-norm distances:
    x [*, P, M], y [*, R, M] -> [*, P, R]."""
    x, y = as_tensor(x), as_tensor(y)
    pv = float(p)

    def fn(a, b):
        if pv == 2.0 and str(compute_mode) != \
                "donot_use_mm_for_euclid_dist":
            # |a-b|^2 = |a|^2 + |b|^2 - 2 a.b — one big MXU matmul
            a2 = jnp.sum(a * a, -1)[..., :, None]
            b2 = jnp.sum(b * b, -1)[..., None, :]
            ab = jnp.einsum("...pm,...rm->...pr", a, b)
            return jnp.sqrt(jnp.maximum(a2 + b2 - 2 * ab, 0.0))
        d = a[..., :, None, :] - b[..., None, :, :]
        if pv == 0:
            return jnp.sum((d != 0).astype(a.dtype), -1)
        if pv == float("inf"):
            return jnp.max(jnp.abs(d), -1)
        return jnp.sum(jnp.abs(d) ** pv, -1) ** (1.0 / pv)
    return apply(fn, x, y, name="cdist")


def vecdot(x, y, axis=-1, name=None):
    """paddle.linalg.vecdot — broadcasted vector dot along ``axis``."""
    x, y = as_tensor(x), as_tensor(y)
    return apply(lambda a, b: jnp.sum(a * b, axis=axis), x, y,
                 name="vecdot")


__all__ += ["lu_unpack", "cdist", "vecdot"]
