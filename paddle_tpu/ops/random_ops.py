"""Random sampling ops (paddle/tensor/random.py parity, UNVERIFIED).

All draws go through the global ``Generator`` (framework.random), which
splits a jax PRNG key per call — so randomness is reproducible under
``paddle_tpu.seed`` and functionalizes cleanly under to_static.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply, to_jax_dtype
from ..framework import random as framework_random
from .common import as_tensor
from .creation import _shape

__all__ = [
    "rand", "randn", "randint", "randint_like", "uniform", "normal",
    "standard_normal", "gaussian", "multinomial", "randperm", "bernoulli",
    "poisson", "exponential_", "uniform_", "normal_", "binomial",
    "standard_gamma", "log_normal",
]


def _key():
    return framework_random.default_generator.next_key()


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else _key()
    return Tensor(jax.random.uniform(key, _shape(shape),
                                     to_jax_dtype(dtype or "float32"),
                                     minval=min, maxval=max))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype or "float32", 0.0, 1.0)


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(_key(), _shape(shape),
                                    to_jax_dtype(dtype or "float32")))


standard_normal = randn


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = as_tensor(mean) if not isinstance(mean, Tensor) else mean
        s = as_tensor(std) if not isinstance(std, Tensor) else std
        out_shape = tuple(m.shape) if isinstance(mean, Tensor) else tuple(s.shape)
        noise = jax.random.normal(_key(), out_shape, jnp.float32)
        return apply(lambda mm, ss: mm + ss * noise, m, s, name="normal")
    shape = _shape(shape if shape is not None else [1])
    return Tensor(mean + std * jax.random.normal(_key(), shape, jnp.float32))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype="float32", name=None):
    key = jax.random.PRNGKey(seed) if seed else _key()
    return Tensor(mean + std * jax.random.normal(
        key, _shape(shape), to_jax_dtype(dtype or "float32")))


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    shape = _shape(shape if shape is not None else [1])
    return Tensor(jnp.exp(mean + std * jax.random.normal(_key(), shape,
                                                         jnp.float32)))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_key(), _shape(shape), low, high,
                                     to_jax_dtype(dtype or "int64")))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = as_tensor(x)
    return randint(low, high, x.shape, dtype or x.dtype)


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = as_tensor(x)
    probs = x._data / jnp.sum(x._data, axis=-1, keepdims=True)
    logits = jnp.log(jnp.maximum(probs, 1e-38))
    if replacement:
        out = jax.random.categorical(_key(), logits,
                                     shape=(num_samples,) + logits.shape[:-1]
                                     if logits.ndim > 1 else (num_samples,))
        out = jnp.moveaxis(out, 0, -1) if logits.ndim > 1 else out
    else:
        g = jax.random.gumbel(_key(), logits.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(_key(), n).astype(
        to_jax_dtype(dtype or "int64")))


def bernoulli(x, name=None):
    x = as_tensor(x)
    u = jax.random.uniform(_key(), tuple(x.shape))
    return Tensor((u < x._data).astype(x.dtype))


def binomial(count, prob, name=None):
    count, prob = as_tensor(count), as_tensor(prob)
    out = jax.random.binomial(_key(), count._data.astype(jnp.float32),
                              prob._data)
    return Tensor(out.astype(jnp.int64))


def poisson(x, name=None):
    x = as_tensor(x)
    return Tensor(jax.random.poisson(_key(), x._data).astype(x.dtype))


def standard_gamma(x, name=None):
    x = as_tensor(x)
    return Tensor(jax.random.gamma(_key(), x._data).astype(x.dtype))


# ---- in-place samplers (tensor methods) -----------------------------------

def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else _key()
    x.set_data(jax.random.uniform(key, tuple(x.shape), x.dtype
                                  if jnp.issubdtype(x.dtype, jnp.floating)
                                  else jnp.float32, minval=min, maxval=max))
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x.set_data(mean + std * jax.random.normal(_key(), tuple(x.shape),
                                              x.dtype))
    return x


def exponential_(x, lam=1.0, name=None):
    u = jax.random.uniform(_key(), tuple(x.shape), x.dtype)
    x.set_data(-jnp.log(1.0 - u) / lam)
    return x


def bernoulli_(x, p=0.5, name=None):
    x.set_data(jax.random.bernoulli(
        _key(), p, tuple(x.shape)).astype(x.dtype))
    return x


def cauchy_(x, loc=0, scale=1, name=None):
    u = jax.random.uniform(_key(), tuple(x.shape), x.dtype,
                           minval=1e-7, maxval=1.0 - 1e-7)
    x.set_data(loc + scale * jnp.tan(jnp.pi * (u - 0.5)))
    return x


def geometric_(x, probs, name=None):
    u = jax.random.uniform(_key(), tuple(x.shape), x.dtype,
                           minval=1e-7, maxval=1.0 - 1e-7)
    x.set_data(jnp.floor(jnp.log1p(-u) / jnp.log1p(-probs)) + 1)
    return x


def log_normal_(x, mean=1.0, std=2.0, name=None):
    """Namespace form of ``Tensor.log_normal_`` — delegates to the ONE
    implementation in ops/tensor_methods.py (float32 draw cast to the
    tensor dtype, integer dtypes included)."""
    from .tensor_methods import _log_normal_
    return _log_normal_(x, mean=mean, std=std, name=name)


__all__ += ["bernoulli_", "cauchy_", "geometric_", "log_normal_"]
