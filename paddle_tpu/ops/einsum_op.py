"""einsum (paddle/tensor/einsum.py parity, UNVERIFIED)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import apply
from .common import as_tensor

__all__ = ["einsum"]


def einsum(equation, *operands, name=None):
    ts = [as_tensor(o) for o in operands]
    return apply(lambda *xs: jnp.einsum(equation, *xs), *ts, name="einsum")
