"""Tensor creation ops (paddle/tensor/creation.py parity, UNVERIFIED paths)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply, to_jax_dtype
from .common import as_tensor

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "diag", "diagflat", "tril", "triu", "meshgrid", "assign", "clone",
    "tril_indices", "triu_indices", "complex", "polar", "one_hot",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    if isinstance(data, Tensor):
        t = Tensor(data._data, dtype=dtype, stop_gradient=stop_gradient)
        return t
    from ..framework.core import ObservedFloat
    if isinstance(data, ObservedFloat):
        data._misuse("tensor creation")
    return Tensor(jnp.asarray(data, dtype=to_jax_dtype(dtype)),
                  stop_gradient=stop_gradient)


def zeros(shape, dtype="float32", name=None) -> Tensor:
    return Tensor(jnp.zeros(_shape(shape), to_jax_dtype(dtype or "float32")))


def ones(shape, dtype="float32", name=None) -> Tensor:
    return Tensor(jnp.ones(_shape(shape), to_jax_dtype(dtype or "float32")))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = "float32"
    return Tensor(jnp.full(_shape(shape), fill_value, to_jax_dtype(dtype)))


def empty(shape, dtype="float32", name=None) -> Tensor:
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None) -> Tensor:
    x = as_tensor(x)
    return Tensor(jnp.zeros_like(x._data, dtype=to_jax_dtype(dtype)))


def ones_like(x, dtype=None, name=None) -> Tensor:
    x = as_tensor(x)
    return Tensor(jnp.ones_like(x._data, dtype=to_jax_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    x = as_tensor(x)
    return Tensor(jnp.full_like(x._data, fill_value, dtype=to_jax_dtype(dtype)))


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    return Tensor(jnp.arange(start, end, step, dtype=to_jax_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=to_jax_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None) -> Tensor:
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.logspace(_v(start), _v(stop), int(_v(num)),
                               base=_v(base), dtype=to_jax_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype="float32", name=None) -> Tensor:
    return Tensor(jnp.eye(num_rows, num_columns, dtype=to_jax_dtype(dtype)))


def diag(x, offset=0, padding_value=0, name=None) -> Tensor:
    x = as_tensor(x)

    def fn(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a, dtype=bool), k=offset)
                out = jnp.where(mask, out, jnp.asarray(padding_value, a.dtype))
            return out
        return jnp.diagonal(a, offset=offset)
    return apply(fn, x, name="diag")


def diagflat(x, offset=0, name=None) -> Tensor:
    x = as_tensor(x)
    return apply(lambda a: jnp.diagflat(a, k=offset), x, name="diagflat")


def tril(x, diagonal=0, name=None) -> Tensor:
    x = as_tensor(x)
    return apply(lambda a: jnp.tril(a, k=diagonal), x, name="tril")


def triu(x, diagonal=0, name=None) -> Tensor:
    x = as_tensor(x)
    return apply(lambda a: jnp.triu(a, k=diagonal), x, name="triu")


def tril_indices(row, col=None, offset=0, dtype="int64") -> Tensor:
    col = row if col is None else col
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=to_jax_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64") -> Tensor:
    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=to_jax_dtype(dtype)))


def meshgrid(*args, name=None):
    args = [as_tensor(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    outs = apply(lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")),
                 *args, n_outputs=len(args), name="meshgrid")
    return list(outs)


def assign(x, output=None) -> Tensor:
    x = as_tensor(x)
    out = apply(lambda a: a + 0, x, name="assign")
    if output is not None:
        output.set_data(out._data)
        output._node = out._node
        output._out_idx = out._out_idx
        output.stop_gradient = out.stop_gradient
        return output
    return out


def clone(x, name=None) -> Tensor:
    return assign(x)


def complex(real, imag, name=None) -> Tensor:
    return apply(lambda r, i: jax.lax.complex(r, i), as_tensor(real),
                 as_tensor(imag), name="complex")


def polar(abs, angle, name=None) -> Tensor:
    return apply(lambda a, t: a * jnp.exp(1j * t.astype(jnp.complex64)),
                 as_tensor(abs), as_tensor(angle), name="polar")


def one_hot(x, num_classes, name=None) -> Tensor:
    x = as_tensor(x)
    return Tensor(jax.nn.one_hot(x._data, num_classes, dtype=jnp.float32))
