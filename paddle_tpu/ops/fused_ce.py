"""Fused linear + cross-entropy (the Liger/chunked-vocab trick).

The LM loss tail — ``logits = h @ W; ce(logits, labels)`` — materializes
a [N, V] logits tensor (bf16 fwd + f32 softmax + bf16 dlogits in bwd);
at N=4k, V=32k that is ~0.8GB of HBM traffic per step. This op never
materializes the full logits: the forward scans token chunks computing
only logsumexp + the target logit, and the custom VJP re-computes each
chunk's softmax on the fly, emitting dh rows and accumulating dW.
FLOPs are unchanged (plus one re-matmul, the classic remat trade);
peak memory drops from N*V to chunk*V.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fused_linear_cross_entropy"]


def _chunk_rows(v: int, target_bytes: int = 32 * 2 ** 20) -> int:
    """Rows per chunk so one f32 logits chunk is ~target_bytes (32MB
    measured best on the v5e 2.4B bench: 62.7% MFU vs 26.4% at 256MB
    chunks, which HBM-thrash against remat)."""
    return max(target_bytes // max(4 * v, 1), 16)


def _chunked(h, labels, v, ignore_index):
    """[N, D] -> [C, rows, D], padding N up to a multiple of the chunk
    rows (pad rows carry ignore_index, contributing nothing) — so a
    prime N never degrades to single-row chunks."""
    n = h.shape[0]
    rows = min(_chunk_rows(v), n) if n else 1
    c = -(-n // rows)
    pad = c * rows - n
    if pad:
        h = jnp.concatenate(
            [h, jnp.zeros((pad, h.shape[1]), h.dtype)], axis=0)
        labels = jnp.concatenate(
            [labels, jnp.full((pad,), ignore_index, labels.dtype)], axis=0)
    return (h.reshape(c, rows, h.shape[1]),
            labels.reshape(c, rows), pad)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_linear_cross_entropy(h, w, labels, ignore_index=-100):
    """mean CE of ``h @ w`` against ``labels`` without materializing
    logits. h: [N, D] (any float dtype), w: [D, V], labels: [N] int;
    rows with ``ignore_index`` contribute nothing."""
    loss, _ = _flce_fwd(h, w, labels, ignore_index)
    return loss


def _flce_fwd(h, w, labels, ignore_index):
    v = w.shape[1]
    hc, lc, _pad = _chunked(h, labels, v, ignore_index)

    def chunk(carry, xs):
        hh, ll = xs
        logits = (hh @ w).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        valid = ll != ignore_index
        safe = jnp.where(valid, ll, 0)
        tgt = jnp.take_along_axis(logits, safe[:, None], -1)[:, 0]
        per = jnp.where(valid, lse - tgt, 0.0)
        tot, cnt = carry
        return (tot + jnp.sum(per),
                cnt + jnp.sum(valid.astype(jnp.float32))), None

    (total, count), _ = lax.scan(chunk, (jnp.zeros(()), jnp.zeros(())),
                                 (hc, lc))
    loss = total / jnp.maximum(count, 1.0)
    return loss, (h, w, labels, count)


def _flce_bwd(ignore_index, res, g):
    h, w, labels, count = res
    n, v = h.shape[0], w.shape[1]
    hc, lc, _pad = _chunked(h, labels, v, ignore_index)
    scale = g / jnp.maximum(count, 1.0)

    def chunk(dw_acc, xs):
        hh, ll = xs
        logits = (hh @ w).astype(jnp.float32)
        p = jax.nn.softmax(logits, axis=-1)
        valid = (ll != ignore_index)
        safe = jnp.where(valid, ll, 0)
        onehot = jax.nn.one_hot(safe, v, dtype=jnp.float32)
        dlogits = (p - onehot) * valid[:, None].astype(jnp.float32) * scale
        dlogits = dlogits.astype(h.dtype)
        dh = dlogits @ w.T
        dw_acc = dw_acc + (hh.T @ dlogits).astype(jnp.float32)
        return dw_acc, dh

    dw0 = jnp.zeros(w.shape, jnp.float32)
    dw, dh_chunks = lax.scan(chunk, dw0, (hc, lc))
    dh = dh_chunks.reshape(-1, h.shape[1])[:n].astype(h.dtype)
    return dh, dw.astype(w.dtype), None


fused_linear_cross_entropy.defvjp(_flce_fwd, _flce_bwd)
