"""Fused linear + cross-entropy (the Liger/chunked-vocab trick).

The LM loss tail — ``logits = h @ W; ce(logits, labels)`` — materializes
a [N, V] logits tensor (bf16 fwd + f32 softmax + bf16 dlogits in bwd);
at N=4k, V=32k that is ~0.8GB of HBM traffic per step. This op never
materializes full logits.

Layout (round-3 rewrite): chunk over the VOCAB axis, not rows. The
first version scanned row chunks with a [D, V] f32 dW carry — 330MB
read+written every scan step plus thin (M=256) matmuls, measured 10x
slower than the plain CE tail. Vocab chunking keeps every matmul fat
([N, D] x [D, vc]), makes dW a STACKED per-chunk output (no carry
traffic), and the only carries are [N]-vectors (online logsumexp) in
forward and one [N, D] f32 dh accumulator in backward. The forward also
saves the [N] lse so backward does one pass, not two."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fused_linear_cross_entropy"]

#: vocab columns per chunk — one f32 [N, vc] logits block at N=4k is
#: 4096*4096*4 = 64MB live, and [D, vc] dW blocks stay MXU-tile aligned
_CHUNK_V = 4096


def _pad_w(w):
    v = w.shape[1]
    c = -(-v // _CHUNK_V)
    pad = c * _CHUNK_V - v
    if pad:
        w = jnp.concatenate(
            [w, jnp.zeros((w.shape[0], pad), w.dtype)], axis=1)
    return w, c, pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_linear_cross_entropy(h, w, labels, ignore_index=-100):
    """mean CE of ``h @ w`` against ``labels`` without materializing
    logits. h: [N, D] (any float dtype), w: [D, V], labels: [N] int;
    rows with ``ignore_index`` contribute nothing."""
    loss, _ = _flce_fwd(h, w, labels, ignore_index)
    return loss


def _flce_fwd(h, w, labels, ignore_index):
    n = h.shape[0]
    wp, c, _pad = _pad_w(w)
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0).astype(jnp.int32)

    def chunk(carry, ci):
        m, s, tgt = carry
        wc = lax.dynamic_slice(wp, (0, ci * _CHUNK_V),
                               (wp.shape[0], _CHUNK_V))
        logits = (h @ wc).astype(jnp.float32)        # [N, vc]
        # padded columns are exp(0)=1 garbage — mask them to -inf
        if _pad:
            col = ci * _CHUNK_V + jnp.arange(_CHUNK_V)
            logits = jnp.where(col[None, :] < w.shape[1], logits,
                               -jnp.inf)
        m_new = jnp.maximum(m, logits.max(-1))
        s = s * jnp.exp(m - m_new) \
            + jnp.exp(logits - m_new[:, None]).sum(-1)
        local = safe - ci * _CHUNK_V
        in_chunk = (local >= 0) & (local < _CHUNK_V)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, _CHUNK_V - 1)[:, None], -1)[:, 0]
        tgt = tgt + jnp.where(in_chunk, picked, 0.0)
        return (m_new, s, tgt), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32))
    (m, s, tgt), _ = lax.scan(chunk, init, jnp.arange(c))
    lse = m + jnp.log(s)
    count = jnp.sum(valid.astype(jnp.float32))
    per = jnp.where(valid, lse - tgt, 0.0)
    loss = jnp.sum(per) / jnp.maximum(count, 1.0)
    return loss, (h, w, labels, lse, count)


def _flce_bwd(ignore_index, res, g):
    h, w, labels, lse, count = res
    d, v = w.shape
    wp, c, pad = _pad_w(w)
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0).astype(jnp.int32)
    scale = (g / jnp.maximum(count, 1.0)).astype(jnp.float32)
    vmask = valid.astype(jnp.float32) * scale      # [N]

    def chunk(dh_acc, ci):
        wc = lax.dynamic_slice(wp, (0, ci * _CHUNK_V),
                               (wp.shape[0], _CHUNK_V))
        logits = (h @ wc).astype(jnp.float32)
        p = jnp.exp(logits - lse[:, None])          # softmax columns
        if pad:
            col = ci * _CHUNK_V + jnp.arange(_CHUNK_V)
            p = jnp.where(col[None, :] < v, p, 0.0)
        local = safe - ci * _CHUNK_V
        in_chunk = (local >= 0) & (local < _CHUNK_V)
        onehot = jax.nn.one_hot(jnp.where(in_chunk, local, _CHUNK_V),
                                _CHUNK_V, dtype=jnp.float32)
        dlogits = ((p - onehot) * vmask[:, None]).astype(h.dtype)
        dh_acc = dh_acc + (dlogits @ wc.T).astype(jnp.float32)
        dw_c = (h.T @ dlogits).astype(jnp.float32)  # [D, vc] stacked out
        return dh_acc, dw_c

    dh, dw_chunks = lax.scan(chunk, jnp.zeros(h.shape, jnp.float32),
                             jnp.arange(c))
    # [C, D, vc] -> [D, C*vc] -> unpad
    dw = jnp.transpose(dw_chunks, (1, 0, 2)).reshape(d, c * _CHUNK_V)
    if pad:
        dw = dw[:, :v]
    return dh.astype(h.dtype), dw.astype(w.dtype), None


fused_linear_cross_entropy.defvjp(_flce_fwd, _flce_bwd)
