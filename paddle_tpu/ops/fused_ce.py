"""Fused linear + cross-entropy (the Liger/chunked-vocab trick).

The LM loss tail — ``logits = h @ W; ce(logits, labels)`` — materializes
a [N, V] logits tensor (bf16 fwd + f32 softmax + bf16 dlogits in bwd);
at N=4k, V=32k that is ~0.8GB of HBM traffic per step. This op never
materializes full logits.

Layout (round-3 rewrite): chunk over the VOCAB axis, not rows. The
first version scanned row chunks with a [D, V] f32 dW carry — 330MB
read+written every scan step plus thin (M=256) matmuls, measured 10x
slower than the plain CE tail. Vocab chunking keeps every matmul fat
([N, D] x [D, vc]), makes dW a STACKED per-chunk output (no carry
traffic), and the only carries are [N]-vectors (online logsumexp) in
forward and one [N, D] f32 dh accumulator in backward. The forward also
saves the [N] lse so backward does one pass, not two.

Round-8 additions (the training-kernel suite PR):

- The chunk width is a tunable surface (``"fused_ce"``), resolved with
  the standard precedence: an explicit ``FLAGS_fused_ce_chunk_v``
  (env/set_flags) > tuner cache > the ``_CHUNK_V`` module default
  (tests still monkeypatch ``_CHUNK_V`` to shrink chunks).
- The per-chunk softmax stats (max/exp-sum/target-gather) and the
  backward's dlogits construction route through Pallas inner kernels
  (``ops/pallas/ce_chunk.py``) on TPU, so the scan body's elementwise
  work stays in VMEM instead of round-tripping the f32 logits block
  between HLOs; ``force_pallas_inner`` pins the kernels on for
  CPU-interpret parity tests (the ``fused_parity`` gate).
"""

from __future__ import annotations

import functools
import threading as _threading

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fused_linear_cross_entropy", "fused_ce_cost",
           "force_chunk_v", "force_pallas_inner"]

#: vocab columns per chunk (the surface DEFAULT). 1024 is the measured
#: peak-memory sweet spot at the bench tail geometry (N=8k, D=2k,
#: V=32k: 539MB vs 799MB at 4096 — the f32 logits block and its
#: elementwise temps scale with the chunk; below 1024 the matmuls
#: start going thin and the scan trip count balloons). [N, 1024] x
#: MXU tiles stay fat; the "fused_ce" tunable surface sweeps
#: 512-8192 so --autotune re-picks per shape/chip.
_CHUNK_V = 1024

_forced_tls = _threading.local()


class force_chunk_v:
    """Context manager pinning the vocab-chunk width for tuner trials
    (this thread only) — same contract as flash_attention.force_blocks:
    candidates pin HERE, not through set_flags (which would mark the
    flag user-explicit and defeat override > cache > default)."""

    def __init__(self, chunk_v):
        self._val = int(chunk_v)

    def __enter__(self):
        self._prev = getattr(_forced_tls, "chunk_v", None)
        _forced_tls.chunk_v = self._val
        return self

    def __exit__(self, *exc):
        _forced_tls.chunk_v = self._prev
        return False


class force_pallas_inner:
    """Force the Pallas chunk-stats/dlogits inner kernels regardless of
    backend (CPU runs them in interpret mode) — the fused_parity gate
    and the kernel-vs-oracle tests run under this."""

    def __enter__(self):
        self._prev = getattr(_forced_tls, "pallas_inner", None)
        _forced_tls.pallas_inner = True
        return self

    def __exit__(self, *exc):
        _forced_tls.pallas_inner = self._prev
        return False


def _resolve_chunk_v(d, v, dtype) -> int:
    """Chunk-width resolution: forced (trials) > explicit flag (env /
    set_flags — ``flag_source`` distinguishes) > tuner cache > the
    module default."""
    forced = getattr(_forced_tls, "chunk_v", None)
    if forced is not None:
        return int(forced)
    try:
        from ..framework import flags
        if flags.flag_source("FLAGS_fused_ce_chunk_v") != "default":
            val = int(flags.flag("FLAGS_fused_ce_chunk_v"))
            if val > 0:
                return val
    except KeyError:
        pass
    try:
        from ..tuner import lookup
        cfg = lookup("fused_ce", {"d": int(d), "v": int(v)}, str(dtype))
        if cfg:
            return int(cfg.get("chunk_v", _CHUNK_V))
    except Exception:
        pass
    return int(_CHUNK_V)


def _use_pallas_inner() -> bool:
    if getattr(_forced_tls, "pallas_inner", None):
        return True
    try:
        from ..framework import flags
        if not flags.flag("FLAGS_fused_ce_pallas_inner"):
            return False
    except KeyError:
        pass
    return jax.default_backend() == "tpu"


def _chunk_grid(v, chunk_v):
    """(cv, c): static chunk width (clamped to the vocab) and chunk
    count. Chunk ``ci`` covers columns ``[start, start + cv)`` with
    ``start = min(ci*cv, v - cv)`` — the LAST chunk's start clamps
    back so every slice stays in bounds and the weight is NEVER padded
    (the old ``_pad_w`` concatenated a full [D, V_pad] copy of w into
    temp memory every call); the tail chunk instead OVERLAPS its
    predecessor and masks the already-counted prefix columns
    (``col < lo``) out of the stats/grads."""
    cv = min(int(chunk_v), int(v))
    return cv, -(-int(v) // cv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_linear_cross_entropy(h, w, labels, ignore_index=-100):
    """mean CE of ``h @ w`` against ``labels`` without materializing
    logits. h: [N, D] (any float dtype), w: [D, V], labels: [N] int;
    rows with ``ignore_index`` contribute nothing (an all-ignored batch
    yields loss 0, not NaN)."""
    loss, _ = _flce_fwd(h, w, labels, ignore_index)
    return loss


def _flce_fwd(h, w, labels, ignore_index):
    n = h.shape[0]
    v = w.shape[1]
    cv, c = _chunk_grid(v, _resolve_chunk_v(w.shape[0], v, h.dtype))
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0).astype(jnp.int32)
    pallas_inner = _use_pallas_inner()

    def chunk(carry, ci):
        m, s, tgt = carry
        start = jnp.minimum(ci * cv, v - cv)
        lo = ci * cv - start          # overlap prefix, 0 except tail
        wc = lax.dynamic_slice(w, (0, start), (w.shape[0], cv))
        logits = (h @ wc).astype(jnp.float32)        # [N, vc]
        local = safe - start
        if pallas_inner:
            # one VMEM pass: chunk max / exp-sum / target gather (the
            # overlap prefix masked inside the kernel), then the
            # online-softmax carry update on [N] vectors only
            from .pallas.ce_chunk import chunk_stats
            m_c, s_c, t_c = chunk_stats(logits, local, lo)
            m_new = jnp.maximum(m, m_c)
            s = s * jnp.exp(m - m_new) + s_c * jnp.exp(m_c - m_new)
            tgt = tgt + t_c
            return (m_new, s, tgt), None
        col = jnp.arange(cv)
        lg = jnp.where(col[None, :] >= lo, logits, -jnp.inf)
        m_new = jnp.maximum(m, lg.max(-1))
        s = s * jnp.exp(m - m_new) \
            + jnp.where(col[None, :] >= lo,
                        jnp.exp(logits - m_new[:, None]), 0.0).sum(-1)
        in_chunk = (local >= lo) & (local < cv)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, cv - 1)[:, None], -1)[:, 0]
        tgt = tgt + jnp.where(in_chunk, picked, 0.0)
        return (m_new, s, tgt), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32))
    (m, s, tgt), _ = lax.scan(chunk, init, jnp.arange(c))
    lse = m + jnp.log(s)
    count = jnp.sum(valid.astype(jnp.float32))
    per = jnp.where(valid, lse - tgt, 0.0)
    loss = jnp.sum(per) / jnp.maximum(count, 1.0)
    return loss, (h, w, labels, lse, count)


def _flce_bwd(ignore_index, res, g):
    h, w, labels, lse, count = res
    d, v = w.shape
    cv, c = _chunk_grid(v, _resolve_chunk_v(d, v, h.dtype))
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0).astype(jnp.int32)
    scale = (g / jnp.maximum(count, 1.0)).astype(jnp.float32)
    vmask = valid.astype(jnp.float32) * scale      # [N]
    pallas_inner = _use_pallas_inner()

    def chunk(dh_acc, ci):
        start = jnp.minimum(ci * cv, v - cv)
        lo = ci * cv - start
        wc = lax.dynamic_slice(w, (0, start), (w.shape[0], cv))
        logits = (h @ wc).astype(jnp.float32)
        local = safe - start
        col = jnp.arange(cv)
        if pallas_inner:
            from .pallas.ce_chunk import chunk_dlogits
            dlogits = chunk_dlogits(logits, lse, local, vmask, lo,
                                    out_dtype=h.dtype)
        else:
            # iota-compare instead of jax.nn.one_hot: the f32
            # [N, cv+1] one-hot was a peak-memory term of its own
            p = jnp.where(col[None, :] >= lo,
                          jnp.exp(logits - lse[:, None]), 0.0)
            hit = ((col[None, :] == local[:, None])
                   & (col[None, :] >= lo)).astype(jnp.float32)
            dlogits = ((p - hit) * vmask[:, None]).astype(h.dtype)
        dh_acc = dh_acc + (dlogits @ wc.T).astype(jnp.float32)
        # [D, cv] stacked out, cast ONCE to the weight dtype here:
        # chunks partition the vocab axis (overlap prefix discarded in
        # the reconstruction below), so per-chunk casting applies the
        # same single f32->w.dtype rounding a final cast would — and
        # the stacked ys buffer is written once per step, NOT a scan
        # carry (CPU XLA double-buffers carries; an earlier [D, V]
        # dw carry measured ~2x this formulation's peak). The round-3
        # 10x-slowdown carry was an f32 full-buffer ADD — different
        # traffic pattern again.
        dw_c = (h.astype(jnp.float32).T
                @ dlogits.astype(jnp.float32)).astype(w.dtype)
        return dh_acc, dw_c

    dh, dw_chunks = lax.scan(chunk, jnp.zeros(h.shape, jnp.float32),
                             jnp.arange(c))
    if c == 1:
        dw = dw_chunks[0]
    else:
        # chunks 0..c-2 tile [0, (c-1)*cv); the clamped tail covers
        # [v - cv, v) — drop its (static-size) overlap prefix
        body = jnp.moveaxis(dw_chunks[:-1], 0, 1).reshape(d,
                                                          (c - 1) * cv)
        keep = (c - 1) * cv - (v - cv)
        dw = jnp.concatenate([body, dw_chunks[-1][:, keep:]], axis=1)
    return dh.astype(h.dtype), dw, None


fused_linear_cross_entropy.defvjp(_flce_fwd, _flce_bwd)


# -- tunable surface ---------------------------------------------------------

def _register_fused_ce_surface():
    from ..tuner.surface import TunableSurface, register_surface

    def _candidates(shape):
        v = int(shape.get("v", 1 << 30))
        return [{"chunk_v": cv} for cv in (512, 1024, 2048, 4096, 8192)
                if cv <= max(v, 1024)]

    register_surface(TunableSurface(
        name="fused_ce",
        params=("chunk_v",),
        default={"chunk_v": _CHUNK_V},
        candidates=_candidates,
        is_valid=lambda config, shape: (config["chunk_v"] % 128 == 0
                                        and config["chunk_v"] > 0),
        describe="Vocab-chunk width of the fused linear+cross-entropy "
                 "scan (trades matmul width against the live f32 "
                 "[N, chunk_v] logits block). Shape key: hidden d, "
                 "vocab v. FLAGS_fused_ce_chunk_v set explicitly "
                 "overrides any cached value."))


_register_fused_ce_surface()


def fused_ce_cost(n, d, v, train=False):
    """Static FLOPs/bytes for one fused-CE call (profiler cost-
    accounting surface). Model FLOPs only, like every estimator here:
    the backward's logits RE-matmul is real hardware work but remat-
    class recompute, deliberately not counted (profiler/cost module
    docstring)."""
    from ..profiler.cost import fused_linear_ce_cost
    return fused_linear_ce_cost(int(n), int(d), int(v), train=train)
