"""Fused SwiGLU Pallas kernel — ``silu(gate) * up`` in one VMEM pass.

The unfused functional lowers into sigmoid -> mul -> mul with the
``silu(gate)`` intermediate materialized (and saved for backward) in
HBM; at Llama intermediate sizes that is a full ``[N, H]`` activation
per MLP. The fused kernel reads gate/up once and writes only the
product; the custom VJP saves just the two INPUTS (which the matmuls
that produced them already keep live under dots_saveable remat) and
recomputes sigmoid on-chip in the backward kernel — dgate and dup come
out of one fused pass.

Same discipline as flash_attention/rms_norm: interpret mode everywhere
but TPU (the kernel path is what tests exercise), thread-local force
hook for tuner trials, tile sizes registered as the ``swiglu`` tunable
surface next to the knob.
"""

from __future__ import annotations

import functools
import threading as _threading

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._utils import interpret_mode as _interpret, no_x64 as _no_x64

__all__ = ["swiglu_fused", "swiglu_reference", "swiglu_cost",
           "force_swiglu_blocks"]


def swiglu_reference(gate, up):
    """Oracle: ``jax.nn.silu(gate) * up`` — exactly the unfused
    functional's math (silu computed in the input dtype)."""
    return jax.nn.silu(gate) * up


def _fwd_kernel(g_ref, u_ref, o_ref):
    g = g_ref[:].astype(jnp.float32)
    u = u_ref[:].astype(jnp.float32)
    o_ref[:] = (g * jax.nn.sigmoid(g) * u).astype(o_ref.dtype)


def _bwd_kernel(g_ref, u_ref, go_ref, dg_ref, du_ref):
    g = g_ref[:].astype(jnp.float32)
    u = u_ref[:].astype(jnp.float32)
    go = go_ref[:].astype(jnp.float32)
    sig = jax.nn.sigmoid(g)
    silu = g * sig
    # d silu(g) = sig * (1 + g * (1 - sig)). The literal is explicit
    # f32: weak python floats re-concretize as f64 when the interpret-
    # mode jaxpr lowers under an outer x64-enabled trace.
    one = jnp.float32(1.0)
    dg_ref[:] = (go * u * sig * (one + g * (one - sig))).astype(
        dg_ref.dtype)
    du_ref[:] = (go * silu).astype(du_ref.dtype)


_forced_tls = _threading.local()


class force_swiglu_blocks:
    """Context manager pinning (block_rows, block_cols) for trials
    (this thread only) — same contract as flash_attention.force_blocks."""

    def __init__(self, block_rows, block_cols):
        self._val = (int(block_rows), int(block_cols))

    def __enter__(self):
        self._prev = getattr(_forced_tls, "blocks", None)
        _forced_tls.blocks = self._val
        return self

    def __exit__(self, *exc):
        _forced_tls.blocks = self._prev
        return False


def _blocks(n_rows: int, h: int, dtype=None) -> tuple[int, int]:
    """(rows, cols) per program. 256x1024 is the static pick; the
    tuner cache ("swiglu" surface, keyed by the intermediate dim)
    overrides it when a sweep recorded a winner."""
    want = (256, 1024)
    forced = getattr(_forced_tls, "blocks", None)
    if forced is not None:
        want = forced
    else:
        from ...tuner import lookup
        cfg = lookup("swiglu", {"h": int(h)}, str(dtype))
        if cfg:
            want = (int(cfg.get("block_rows", want[0])),
                    int(cfg.get("block_cols", want[1])))
    br = min(want[0], -(-n_rows // 8) * 8)
    bc = min(want[1], -(-h // 128) * 128)
    return br, bc


def _pad2(a, n_pad, h_pad):
    if n_pad == a.shape[0] and h_pad == a.shape[1]:
        return a
    # explicit-dtype fill: jnp.pad's weak-int 0 re-concretizes as i64
    # under an outer x64-enabled trace and fails interpret lowering
    return jnp.pad(a, ((0, n_pad - a.shape[0]), (0, h_pad - a.shape[1])),
                   constant_values=a.dtype.type(0))


@jax.custom_vjp
def swiglu_fused(gate, up):
    """Fused ``silu(gate) * up``; any leading shape, elementwise over
    the last dim. Backward is one fused dgate/dup kernel from the raw
    inputs (no silu intermediate ever saved)."""
    return _swiglu_fwd_impl(gate, up)


def _swiglu_fwd_impl(gate, up):
    orig_shape = gate.shape
    h = orig_shape[-1]
    g2 = gate.reshape(-1, h)
    u2 = up.reshape(-1, h)
    n = g2.shape[0]
    br, bc = _blocks(n, h, gate.dtype)
    n_p = -(-n // br) * br
    h_p = -(-h // bc) * bc
    spec = pl.BlockSpec((br, bc), lambda i, j: (i, j))
    with _no_x64():
        out = pl.pallas_call(
            _fwd_kernel,
            grid=(n_p // br, h_p // bc),
            in_specs=[spec, spec],
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct((n_p, h_p), gate.dtype),
            interpret=_interpret(),
        )(_pad2(g2, n_p, h_p), _pad2(u2, n_p, h_p))
    return out[:n, :h].reshape(orig_shape)


def _swiglu_fwd(gate, up):
    return _swiglu_fwd_impl(gate, up), (gate, up)


def _swiglu_bwd(resids, go):
    gate, up = resids
    orig_shape = gate.shape
    h = orig_shape[-1]
    g2 = gate.reshape(-1, h)
    u2 = up.reshape(-1, h)
    go2 = go.reshape(-1, h)
    n = g2.shape[0]
    br, bc = _blocks(n, h, gate.dtype)
    n_p = -(-n // br) * br
    h_p = -(-h // bc) * bc
    spec = pl.BlockSpec((br, bc), lambda i, j: (i, j))
    with _no_x64():
        dg, du = pl.pallas_call(
            _bwd_kernel,
            grid=(n_p // br, h_p // bc),
            in_specs=[spec, spec, spec],
            out_specs=[spec, spec],
            out_shape=[jax.ShapeDtypeStruct((n_p, h_p), gate.dtype),
                       jax.ShapeDtypeStruct((n_p, h_p), up.dtype)],
            interpret=_interpret(),
        )(_pad2(g2, n_p, h_p), _pad2(u2, n_p, h_p),
          _pad2(go2, n_p, h_p))
    return (dg[:n, :h].reshape(orig_shape),
            du[:n, :h].reshape(orig_shape))


swiglu_fused.defvjp(_swiglu_fwd, _swiglu_bwd)


# -- tunable surface ---------------------------------------------------------

def _register_swiglu_surface():
    from ...tuner.surface import TunableSurface, register_surface

    register_surface(TunableSurface(
        name="swiglu",
        params=("block_rows", "block_cols"),
        default={"block_rows": 256, "block_cols": 1024},
        candidates=lambda shape: [
            {"block_rows": br, "block_cols": bc}
            for br in (128, 256, 512)
            for bc in (512, 1024, 2048)],
        is_valid=lambda config, shape: (
            config["block_rows"] % 8 == 0
            and config["block_cols"] % 128 == 0
            # bwd holds 5 blocks (g, u, go, dg, du) live in VMEM
            and 5 * config["block_rows"] * config["block_cols"] * 4
            <= 12 * 1024 * 1024),
        describe="Fused SwiGLU (rows x cols) tile of the fwd and the "
                 "dgate/dup bwd kernels (pure VPU, bandwidth-bound). "
                 "Shape key: intermediate dim h."))


_register_swiglu_surface()


def swiglu_cost(shape, train=False):
    """Static FLOPs/bytes for one fused swiglu over ``[..., h]``
    (profiler cost-accounting surface)."""
    import math

    from ...profiler.cost import swiglu_cost as _cost
    h = int(shape[-1])
    n = int(math.prod(int(s) for s in shape[:-1]))
    return _cost(n, h, train=train)
