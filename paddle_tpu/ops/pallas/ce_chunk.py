"""Pallas inner kernels for the chunked fused linear+cross-entropy.

``ops/fused_ce.py`` scans the vocab in chunks; the matmul producing
each ``[N, vc]`` logits block is XLA's job, but the per-chunk softmax
STATISTICS (chunk max, exp-sum, target gather) and the backward's
``dlogits`` construction each lower to several elementwise HLOs that
round-trip the f32 logits block through HBM between them. These
kernels keep the whole block in VMEM for one pass each:

- :func:`chunk_stats`: ``logits [N, vc]`` -> (m, s, t): the row max
  over valid columns, ``sum(exp(logits - m))``, and the target logit
  gathered by comparing a column iota against the row's local label
  (no one-hot materialized).
- :func:`chunk_dlogits`: ``(softmax(logits) - onehot(label)) * scale``
  for the backward, again without materializing the one-hot.

The chunk grid clamps the tail chunk's start back into bounds instead
of padding the weight (fused_ce._chunk_grid), so a chunk's first
``lo`` columns may OVERLAP the previous chunk: both kernels mask
``col < lo`` out (``lo`` is 0 everywhere but the tail).

Both run in interpret mode off-TPU (the oracle-parity tests exercise
exactly that path); ``fused_ce`` routes through them on TPU or when a
test forces them on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._utils import interpret_mode as _interpret, no_x64 as _no_x64

__all__ = ["chunk_stats", "chunk_dlogits"]

#: rows per program — the logits block is f32: 256 x 4096 x 4B = 4MB
#: per input block, well inside VMEM next to the [blk, 1] vectors
_BLOCK_ROWS = 256


def _stats_kernel(lo_ref, logits_ref, local_ref, m_ref, s_ref, t_ref):
    # literals are explicit f32: weak python floats re-concretize as f64
    # when the interpret-mode kernel jaxpr lowers under an outer
    # x64-enabled trace (the _utils.no_x64 scope covers only the
    # pallas_call trace itself)
    zero = jnp.float32(0.0)
    ninf = jnp.float32(-jnp.inf)
    x = logits_ref[:].astype(jnp.float32)            # [blk, vc]
    lo = lo_ref[0]
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    valid = col >= lo                 # overlap prefix already counted
    xm = jnp.where(valid, x, ninf)
    m = jnp.max(xm, axis=-1, keepdims=True)          # [blk, 1]
    # guard a fully-masked row: exp(-inf - -inf) is NaN and jnp.where
    # evaluates both branches — shift by a finite max instead
    m_safe = jnp.where(jnp.isfinite(m), m, zero)
    e = jnp.where(valid, jnp.exp(x - m_safe), zero)
    s_ref[:] = jnp.sum(e, axis=-1, keepdims=True)
    m_ref[:] = m
    # target gather: a row's local label matches at most one valid
    # column; out-of-chunk labels (negative or >= vc) match none
    match = valid & (col == local_ref[:])
    t_ref[:] = jnp.sum(jnp.where(match, x, zero), axis=-1, keepdims=True)


def _dlogits_kernel(lo_ref, logits_ref, lse_ref, local_ref, scale_ref,
                    o_ref):
    zero = jnp.float32(0.0)
    x = logits_ref[:].astype(jnp.float32)
    lo = lo_ref[0]
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    valid = col >= lo
    p = jnp.where(valid, jnp.exp(x - lse_ref[:].astype(jnp.float32)),
                  zero)
    onehot = (valid & (col == local_ref[:])).astype(jnp.float32)
    o_ref[:] = ((p - onehot)
                * scale_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _row_blk(n):
    return min(_BLOCK_ROWS, -(-n // 8) * 8)


def _pad_rows(a, n_pad):
    if n_pad == a.shape[0]:
        return a
    pads = ((0, n_pad - a.shape[0]),) + ((0, 0),) * (a.ndim - 1)
    # explicit-dtype fill: jnp.pad's weak-int 0 re-concretizes as i64
    # under an outer x64-enabled trace and fails interpret lowering
    return jnp.pad(a, pads, constant_values=a.dtype.type(0))


def chunk_stats(logits, local, lo):
    """Per-chunk online-softmax stats. ``logits [N, vc]`` (any float),
    ``local [N]`` int32 (the row's label minus the chunk's start
    column — any out-of-range value gathers nothing), ``lo`` scalar
    int32 (columns before it belong to the previous chunk — tail-
    overlap masking). Returns ``(m, s, t)`` f32 ``[N]`` vectors."""
    n, vc = logits.shape
    blk = _row_blk(n)
    n_p = -(-n // blk) * blk
    lo_arr = jnp.reshape(jnp.asarray(lo, jnp.int32), (1,))
    col2 = pl.BlockSpec((blk, 1), lambda i: (i, 0))
    with _no_x64():
        m, s, t = pl.pallas_call(
            _stats_kernel,
            grid=(n_p // blk,),
            in_specs=[pl.BlockSpec((1,), lambda i: (0,)),
                      pl.BlockSpec((blk, vc), lambda i: (i, 0)),
                      col2],
            out_specs=[col2, col2, col2],
            out_shape=[jax.ShapeDtypeStruct((n_p, 1), jnp.float32)] * 3,
            interpret=_interpret(),
        )(lo_arr, _pad_rows(logits, n_p),
          _pad_rows(local.astype(jnp.int32).reshape(-1, 1), n_p))
    return m[:n, 0], s[:n, 0], t[:n, 0]


def chunk_dlogits(logits, lse, local, scale, lo, out_dtype=None):
    """Backward inner: ``(softmax - onehot) * scale`` per chunk.
    ``lse [N]`` the saved log-sum-exp, ``scale [N]`` the per-row loss
    scale (0 for ignored rows), ``lo`` the overlap-prefix bound
    (columns before it emit 0 — the previous chunk owns them).
    Returns ``[N, vc]`` in ``out_dtype`` (default: logits dtype)."""
    n, vc = logits.shape
    out_dtype = logits.dtype if out_dtype is None else out_dtype
    blk = _row_blk(n)
    n_p = -(-n // blk) * blk
    lo_arr = jnp.reshape(jnp.asarray(lo, jnp.int32), (1,))
    col2 = pl.BlockSpec((blk, 1), lambda i: (i, 0))
    with _no_x64():
        out = pl.pallas_call(
            _dlogits_kernel,
            grid=(n_p // blk,),
            in_specs=[pl.BlockSpec((1,), lambda i: (0,)),
                      pl.BlockSpec((blk, vc), lambda i: (i, 0)),
                      col2, col2, col2],
            out_specs=pl.BlockSpec((blk, vc), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n_p, vc), out_dtype),
            interpret=_interpret(),
        )(lo_arr, _pad_rows(logits, n_p),
          _pad_rows(lse.astype(jnp.float32).reshape(-1, 1), n_p),
          _pad_rows(local.astype(jnp.int32).reshape(-1, 1), n_p),
          _pad_rows(scale.astype(jnp.float32).reshape(-1, 1), n_p))
    return out[:n]
