"""Shared Pallas kernel utilities."""

from __future__ import annotations


def interpret_mode():
    """Pallas kernels compile natively on TPU; everywhere else (CPU
    tests/CI) they run in interpret mode so the kernel path is always
    exercised."""
    import jax
    return jax.default_backend() != "tpu"


def no_x64():
    """Trace pallas kernels with x64 promotion OFF: the framework runs
    with jax_enable_x64 globally (explicit 64-bit dtypes must survive),
    but weak python literals inside a kernel then promote to i64/f64,
    which Mosaic cannot legalize (observed: infinite recursion in the
    lowering's dtype promotion). Kernel inputs carry explicit dtypes,
    so disabling x64 for the trace changes nothing semantically."""
    import jax
    if hasattr(jax, "enable_x64"):     # removed from the jax root
        return jax.enable_x64(False)   # namespace in newer releases
    from jax.experimental import enable_x64
    return enable_x64(False)
