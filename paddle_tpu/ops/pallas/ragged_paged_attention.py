"""Ragged paged-attention Pallas kernel for TPU serving.

ONE kernel for the whole mixed prefill+decode batching step (PAPERS.md
"Ragged Paged Attention"): the query operand is a flattened token
stream — slot ``b``'s tokens are the ``[start, length]`` window
``[b * C, b * C + lengths[b])`` of the stream, exposed here in its
uniform-stride ``[B, C, H, D]`` view — and every sequence, whether a
multi-token prefill chunk (s > 1), a single decode step (s == 1), or an
idle slot (s == 0), flows through the same grid. No separate prefill
and decode program families, so the serving engine compiles exactly one
batching-step signature.

Semantics (identical to the jnp oracle
``ops.paged_attention.ragged_paged_attention_reference``): the chunk's
k/v were already written into the paged pool at cache positions
``ctx[b] .. ctx[b] + lengths[b] - 1`` (``paged_prefill_write``; chunk
padding rides the reserved trash page 0), and query token ``j`` of
sequence ``b`` attends every cache position ``<= ctx[b] + j`` — full
paged history behind it, causal within the chunk. Rows ``j >=
lengths[b]`` output zeros.

Kernel structure (the jax paged-attention decode kernel's scalar-
prefetch idiom, generalized to ragged multi-token queries):

- grid ``(kv_head, sequence, q_block)`` — one program per kv head per
  sequence-block of the token stream;
- block tables / context lens / lengths ride scalar prefetch, so only
  the pages a sequence actually owns are streamed;
- K/V pools stay in HBM (``ANY`` memory space); each grid step DMAs
  ``kv_pages_per_block`` pages named in the block table into a
  double-buffered VMEM scratch (next block's copy overlaps the current
  block's compute) and accumulates with an online softmax in fp32.

Block sizes (``q_block``, ``kv_pages_per_block``) are a registered
tunable surface ("ragged_paged_attention") swept by ``bench.py
--autotune`` / the tuner CLI; explicit flags win over cached winners
(the flash_attention precedence contract).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._utils import interpret_mode as _interpret, no_x64 as _no_x64

__all__ = ["ragged_paged_attention", "force_ragged_blocks",
           "ragged_attention_cost"]

_NEG_INF = -1e30

# sweep hook: the trial engine pins candidate blocks here while it
# compiles fresh variants (thread-local, same contract as
# flash_attention.force_blocks — candidates must not ride set_flags).
import threading as _threading

_forced_tls = _threading.local()


class force_ragged_blocks:
    """Context manager pinning (q_block, kv_pages_per_block) for tuner
    trials (this thread only)."""

    def __init__(self, q_block, kv_pages_per_block):
        self._val = (int(q_block), int(kv_pages_per_block))

    def __enter__(self):
        self._prev = getattr(_forced_tls, "blocks", None)
        _forced_tls.blocks = self._val
        return self

    def __exit__(self, *exc):
        _forced_tls.blocks = self._prev
        return False


def _resolve_blocks(c, pages_per_seq, page, d, dtype, quant=False):
    """(q_block, kv_pages_per_block) for this shape, precedence: forced
    trial candidate > explicit user flag > tuner cache > default.
    Host-side at trace time — static ints selecting the compiled
    grid. Quantized pools add a ``kvq`` component to the shape sig so
    bf16 cache entries can't poison quantized configs (and vice versa);
    bf16 shapes keep the historical sig."""
    from ...framework import flags
    forced = getattr(_forced_tls, "blocks", None)
    if forced is not None:
        qb, g = forced
    else:
        qb = int(flags.flag("FLAGS_ragged_attn_q_block"))
        g = int(flags.flag("FLAGS_ragged_attn_kv_pages"))
        qb_explicit = flags.flag_source(
            "FLAGS_ragged_attn_q_block") != "default"
        g_explicit = flags.flag_source(
            "FLAGS_ragged_attn_kv_pages") != "default"
        if not (qb_explicit and g_explicit):
            from ...tuner import lookup
            shape_sig = {"c": int(c), "pages": int(pages_per_seq),
                         "page": int(page), "d": int(d)}
            if quant:
                shape_sig["kvq"] = 1
            cfg = lookup("ragged_paged_attention", shape_sig,
                         str(dtype))
            if cfg:
                if not qb_explicit:
                    qb = int(cfg.get("q_block", qb))
                if not g_explicit:
                    g = int(cfg.get("kv_pages_per_block", g))
    # clamp to the shape: q blocks never exceed the chunk, page blocks
    # never exceed the table row
    qb = max(1, min(qb, c))
    g = max(1, min(g, pages_per_seq))
    return qb, g


def _ragged_kernel(ctx_ref, len_ref, tbl_ref, q_ref, k_hbm_ref,
                   v_hbm_ref, o_ref, k_buf, v_buf, sem, *, scale,
                   page, q_block, g_pages, pages_per_seq):
    """One program: (kv head h, sequence b, q block qi). Streams the
    sequence's pages through the double-buffered VMEM scratch and
    accumulates an online softmax over them."""
    h = pl.program_id(0)
    b = pl.program_id(1)
    qi = pl.program_id(2)
    rep = q_ref.shape[1]           # q heads per kv head
    d = q_ref.shape[2]
    bk = g_pages * page            # keys per kv block
    ctx = ctx_ref[b]
    length = len_ref[b]
    q_start = qi * q_block         # first chunk token of this q block

    # rows past the valid count output zeros (also covers idle slots,
    # length == 0, whose programs skip the whole loop)
    o_ref[...] = jnp.zeros_like(o_ref)

    def dma_block(i, slot):
        """Async copies for kv block i into buffer `slot` — one copy
        per page named in the block table (clamped into the row; the
        overhang past ceil(n_kv/page) pages is masked out below).
        Each buffer slot owns its OWN semaphore: every page copy has
        the same byte count, so a shared counter would let block
        i+1's prefetch completions satisfy a wait for block i and
        hand compute a partially-copied buffer."""
        copies = []
        for gidx in range(g_pages):
            pidx = jnp.minimum(i * g_pages + gidx, pages_per_seq - 1)
            pid = tbl_ref[b * pages_per_seq + pidx]
            copies.append(pltpu.make_async_copy(
                k_hbm_ref.at[h, pid], k_buf.at[slot, gidx],
                sem.at[slot]))
            copies.append(pltpu.make_async_copy(
                v_hbm_ref.at[h, pid], v_buf.at[slot, gidx],
                sem.at[slot]))
        return copies

    @pl.when(q_start < length)
    def compute():  # noqa: ANN001 — pl.when body
        # last key any row of this block may see (+1): the block's last
        # valid token at chunk offset min(q_start + q_block, length) - 1
        n_kv = ctx + jnp.minimum(q_start + q_block, length)
        n_blocks = (n_kv + bk - 1) // bk

        for c in dma_block(0, 0):
            c.start()

        q = q_ref[...].astype(jnp.float32) * scale  # [q_block, rep, d]
        q2 = q.reshape(q_block * rep, d)

        def body(i, carry):
            acc, m_prev, l_prev = carry
            slot = jax.lax.rem(i, 2)
            nslot = jax.lax.rem(i + 1, 2)

            @pl.when(i + 1 < n_blocks)
            def _():
                for c in dma_block(i + 1, nslot):
                    c.start()

            for c in dma_block(i, slot):
                c.wait()
            k = k_buf[slot].reshape(bk, d).astype(jnp.float32)
            v = v_buf[slot].reshape(bk, d).astype(jnp.float32)
            s = jax.lax.dot_general(
                q2, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [qb*rep, bk]
            k_pos = i * bk + jax.lax.broadcasted_iota(
                jnp.int32, (q_block * rep, bk), 1)
            q_tok = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (q_block * rep, bk), 0) // rep
            # causal over the paged history + the row-validity mask
            # (rows past `length` stay fully masked -> zero output)
            valid = (k_pos <= ctx + q_tok) & (q_tok < length)
            s = jnp.where(valid, s, _NEG_INF)
            m_cur = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.where(valid, jnp.exp(s - m_new[:, None]), 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[:, None] + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return acc, m_new, l_new

        acc0 = jnp.zeros((q_block * rep, d), jnp.float32)
        m0 = jnp.full((q_block * rep,), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((q_block * rep,), jnp.float32)
        acc, m, l = jax.lax.fori_loop(0, n_blocks, body, (acc0, m0, l0))
        l = jnp.maximum(l, 1e-30)
        o_ref[...] = (acc / l[:, None]).reshape(
            q_block, rep, d).astype(o_ref.dtype)


def _ragged_quant_kernel(ctx_ref, len_ref, tbl_ref, q_ref, k_hbm_ref,
                         v_hbm_ref, ks_hbm_ref, vs_hbm_ref, o_ref,
                         k_buf, v_buf, ks_buf, vs_buf, sem, sem_s, *,
                         scale, page, q_block, g_pages, pages_per_seq):
    """Quantized-pool variant of :func:`_ragged_kernel`: the data pools
    are int8 (or fp8) and a page-parallel f32 scales pool rides the
    SAME block-table indirection — each grid step DMAs the scale pages
    alongside the data pages and dequantizes in VMEM right after the
    wait (``k = q_codes.astype(f32) * scale``), so the softmax body is
    numerically identical to the bf16 kernel's fp32 accumulation. Scale
    copies have a different byte count than data copies, so they ride
    their OWN per-slot semaphore (the shared-counter hazard in
    ``_ragged_kernel.dma_block`` applies per byte-count class)."""
    h = pl.program_id(0)
    b = pl.program_id(1)
    qi = pl.program_id(2)
    rep = q_ref.shape[1]           # q heads per kv head
    d = q_ref.shape[2]
    bk = g_pages * page            # keys per kv block
    ctx = ctx_ref[b]
    length = len_ref[b]
    q_start = qi * q_block         # first chunk token of this q block

    o_ref[...] = jnp.zeros_like(o_ref)

    def dma_block(i, slot):
        copies = []
        for gidx in range(g_pages):
            pidx = jnp.minimum(i * g_pages + gidx, pages_per_seq - 1)
            pid = tbl_ref[b * pages_per_seq + pidx]
            copies.append(pltpu.make_async_copy(
                k_hbm_ref.at[h, pid], k_buf.at[slot, gidx],
                sem.at[slot]))
            copies.append(pltpu.make_async_copy(
                v_hbm_ref.at[h, pid], v_buf.at[slot, gidx],
                sem.at[slot]))
            copies.append(pltpu.make_async_copy(
                ks_hbm_ref.at[h, pid], ks_buf.at[slot, gidx],
                sem_s.at[slot]))
            copies.append(pltpu.make_async_copy(
                vs_hbm_ref.at[h, pid], vs_buf.at[slot, gidx],
                sem_s.at[slot]))
        return copies

    @pl.when(q_start < length)
    def compute():  # noqa: ANN001 — pl.when body
        n_kv = ctx + jnp.minimum(q_start + q_block, length)
        n_blocks = (n_kv + bk - 1) // bk

        for c in dma_block(0, 0):
            c.start()

        q = q_ref[...].astype(jnp.float32) * scale  # [q_block, rep, d]
        q2 = q.reshape(q_block * rep, d)

        def body(i, carry):
            acc, m_prev, l_prev = carry
            slot = jax.lax.rem(i, 2)
            nslot = jax.lax.rem(i + 1, 2)

            @pl.when(i + 1 < n_blocks)
            def _():
                for c in dma_block(i + 1, nslot):
                    c.start()

            for c in dma_block(i, slot):
                c.wait()
            # dequant in VMEM, right after the DMA: one f32 scale per
            # (token, kv head) broadcast over the head dim
            k = (k_buf[slot].reshape(bk, d).astype(jnp.float32)
                 * ks_buf[slot].reshape(bk, 1))
            v = (v_buf[slot].reshape(bk, d).astype(jnp.float32)
                 * vs_buf[slot].reshape(bk, 1))
            s = jax.lax.dot_general(
                q2, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [qb*rep, bk]
            k_pos = i * bk + jax.lax.broadcasted_iota(
                jnp.int32, (q_block * rep, bk), 1)
            q_tok = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (q_block * rep, bk), 0) // rep
            valid = (k_pos <= ctx + q_tok) & (q_tok < length)
            s = jnp.where(valid, s, _NEG_INF)
            m_cur = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.where(valid, jnp.exp(s - m_new[:, None]), 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[:, None] + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return acc, m_new, l_new

        acc0 = jnp.zeros((q_block * rep, d), jnp.float32)
        m0 = jnp.full((q_block * rep,), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((q_block * rep,), jnp.float32)
        acc, m, l = jax.lax.fori_loop(0, n_blocks, body, (acc0, m0, l0))
        l = jnp.maximum(l, 1e-30)
        o_ref[...] = (acc / l[:, None]).reshape(
            q_block, rep, d).astype(o_ref.dtype)


def ragged_paged_attention(q, key_pages, value_pages, block_tables,
                           ctx_lens, lengths, scale=None, q_block=None,
                           kv_pages_per_block=None, k_scales=None,
                           v_scales=None):
    """Mixed prefill+decode paged attention over the flattened token
    stream (uniform-stride view).

    q            [B, C, H, D] — slot b's tokens are the stream window
                 [b*C, b*C + lengths[b]); rows past lengths[b] are
                 padding (zeroed in the output)
    key_pages /  [KVH, num_pages, page_size, D] page pools; the chunk's
    value_pages  k/v already written at ctx .. ctx+len-1
    block_tables [B, pages_per_seq] int32
    ctx_lens     [B] int32 — cache length BEFORE the chunk
    lengths      [B] int32 — valid stream tokens per slot (0 = idle,
                 1 = decode step, >1 = prefill chunk)
    k_scales /   optional [KVH, num_pages, page_size] f32 page-parallel
    v_scales     scales pools — when given, the data pools are int8/fp8
                 and the kernel dequantizes pages in VMEM after the DMA
    Returns [B, C, H, D].
    """
    b, c, h, d = q.shape
    kvh, _, page, _ = key_pages.shape
    rep = h // kvh
    pages_per_seq = block_tables.shape[1]
    quant = k_scales is not None
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    qb, g = _resolve_blocks(c, pages_per_seq, page, d, q.dtype,
                            quant=quant)
    if q_block is not None:
        qb = max(1, min(int(q_block), c))
    if kv_pages_per_block is not None:
        g = max(1, min(int(kv_pages_per_block), pages_per_seq))
    c_p = -(-c // qb) * qb
    if c_p != c:
        q = jnp.pad(q, ((0, 0), (0, c_p - c), (0, 0), (0, 0)))
    grid = (kvh, b, c_p // qb)
    kern = _ragged_quant_kernel if quant else _ragged_kernel
    any_spec = pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)
    in_specs = [
        # q: (slot, q block, kv-head group, head_dim)
        pl.BlockSpec((None, qb, rep, d),
                     lambda hh, bb, qq, *_: (bb, qq, hh, 0)),
        any_spec,       # key pages stay in HBM
        any_spec,       # value pages
    ]
    scratch = [
        pltpu.VMEM((2, g, page, d), key_pages.dtype),
        pltpu.VMEM((2, g, page, d), value_pages.dtype),
    ]
    operands = [q, key_pages, value_pages]
    if quant:
        in_specs += [any_spec, any_spec]            # scales pools
        scratch += [pltpu.VMEM((2, g, page), k_scales.dtype),
                    pltpu.VMEM((2, g, page), v_scales.dtype)]
        operands += [k_scales, v_scales]
    scratch.append(pltpu.SemaphoreType.DMA((2,)))   # one per slot
    if quant:
        # scale copies are a different byte count than page copies —
        # they need their own per-slot counter (see kernel docstring)
        scratch.append(pltpu.SemaphoreType.DMA((2,)))
    with _no_x64():
        out = pl.pallas_call(
            functools.partial(
                kern, scale=s, page=page, q_block=qb,
                g_pages=g, pages_per_seq=pages_per_seq),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=3,   # ctx, lengths, block tables
                grid=grid,
                in_specs=in_specs,
                out_specs=pl.BlockSpec(
                    (None, qb, rep, d),
                    lambda hh, bb, qq, *_: (bb, qq, hh, 0)),
                scratch_shapes=scratch,
            ),
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "arbitrary",
                                     "arbitrary")),
            out_shape=jax.ShapeDtypeStruct((b, c_p, h, d), q.dtype),
            interpret=_interpret(),
        )(ctx_lens.astype(jnp.int32), lengths.astype(jnp.int32),
          block_tables.astype(jnp.int32).reshape(-1), *operands)
    return out[:, :c]


# -- tunable surface ---------------------------------------------------------
# q_block / kv_pages_per_block candidate grid, registered next to the
# knob (the flash_attention pattern). No cost_fn: q blocks revisit the
# whole page list, so byte traffic scales with the q-block COUNT — the
# trial engine times every valid candidate rather than trusting a
# first-order roofline that would mispredict the DMA-overlap win of
# larger page blocks. Shape key: (c, pages, page, d).

def _register_ragged_surface():
    from ...tuner.surface import TunableSurface, register_surface

    def _candidates(shape):
        c = int(shape.get("c", 16))
        pages = int(shape.get("pages", 8))
        qbs = sorted({min(qb, c) for qb in (1, 8, 16, 32, 64, 128)
                      if qb <= max(c, 1)})
        gs = sorted({min(g, pages) for g in (1, 2, 4, 8, 16)
                     if g <= max(pages, 1)})
        return [{"q_block": qb, "kv_pages_per_block": g}
                for qb in qbs for g in gs]

    def _is_valid(config, shape):
        c = int(shape.get("c", 16))
        pages = int(shape.get("pages", 8))
        return (1 <= config["q_block"] <= max(c, 1)
                and 1 <= config["kv_pages_per_block"] <= max(pages, 1))

    register_surface(TunableSurface(
        name="ragged_paged_attention",
        params=("q_block", "kv_pages_per_block"),
        default={"q_block": 16, "kv_pages_per_block": 4},
        candidates=_candidates,
        is_valid=_is_valid,
        describe="Ragged paged-attention kernel blocks: stream tokens "
                 "per q program, KV pages per DMA block. Shape key: "
                 "c (chunk) / pages (per seq) / page (size) / d. "
                 "FLAGS_ragged_attn_q_block / _kv_pages set explicitly "
                 "override any cached value."))


_register_ragged_surface()


def ragged_attention_cost(q_shape, pool_shape, avg_ctx, lengths_sum=None,
                          pool_dtype=None):
    """Static FLOPs/bytes for one :func:`ragged_paged_attention` call
    (profiler cost-accounting surface): q [B, C, H, D], pool
    [KVH, pages, page, D]. Attention over an average history of
    ``avg_ctx`` keys per stream token; bytes count q/pages-touched/out
    only (the kernel never materializes scores). ``pool_dtype`` makes
    the page traffic quant-aware: int8 pools stream half the bytes of
    bf16, plus one f32 scale per (token, kv head) from the scales
    pool."""
    from ...profiler.cost import SectionCost
    b, c, h, d = (int(x) for x in q_shape)
    _, _, page, _ = (int(x) for x in pool_shape)
    toks = int(lengths_sum) if lengths_sum is not None else b * c
    flops = 4.0 * toks * h * d * float(avg_ctx)
    pages_touched = toks * -(-float(avg_ctx) // page)
    io_itemsize = 2  # q/out are bf16 on TPU
    pool_itemsize = (jnp.dtype(pool_dtype).itemsize
                     if pool_dtype is not None else 2)
    bytes_ = ((toks * h * d + toks * h * d) * io_itemsize
              + 2 * pages_touched * page * d * pool_itemsize)
    if pool_dtype is not None and pool_itemsize == 1:
        # quantized pools also stream the page-parallel f32 scales
        bytes_ += 2 * pages_touched * page * 4
    return SectionCost(flops=flops, bytes=bytes_)
