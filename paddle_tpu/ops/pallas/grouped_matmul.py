"""Megablocks-style grouped matmul for MoE expert FFNs (Pallas/TPU).

Reference parity: upstream Paddle's MoE runs capacity-based dispatch
kernels (``phi/kernels/gpu/moe_*``, SURVEY.md §2.1 EP row — mount empty,
no file:line cites); the *dropless* grouped-matmul formulation follows
the MegaBlocks direction named in SURVEY.md §2.3 ("Megablocks-style
Pallas grouped matmul") and PAPERS.md.

Why: the capacity formulation executes ``capacity_factor``× the
activated expert FLOPs as padding (measured on v5e: the dense [E, C, d]
einsum at cf=2.0 reaches 68.6 TF/s executed = 34.3 TF/s on activated
FLOPs; ``lax.ragged_dot`` is worse, 28.4 TF/s). Here tokens are sorted
by expert and each group is padded to a multiple of the row-tile ``bm``,
so every [bm, d] tile belongs to exactly ONE expert: the kernel is then
a plain MXU matmul per tile whose weight block only changes at group
boundaries (Pallas skips the HBM re-fetch while the block index is
unchanged — weights stream at ~E·d·h bytes per call, not nr·d·h).
Worst-case padding is E·(bm-1) rows (~6-12% at bench shapes vs 100%
for cf=2.0), and no token is ever dropped.

Layout contract (built by ``ops.moe.sort_rows_by_expert``):
- ``x``   [P, d]  — assignment rows sorted by expert, group-padded with
  zero rows so group *e* occupies tiles
  ``[tile_offset[e], tile_offset[e] + ceil(size[e]/bm))``; every expert
  owns >= 1 tile (so zero-token experts still get their dw written).
- ``tile_gid`` [P // bm] int32 — each row tile's expert id,
  non-decreasing.
- ``w``  [E, d, h].

``grouped_matmul(x, w, tile_gid)`` -> [P, h] with a custom VJP:
  dx = grouped_matmul_t(dy, w, tile_gid)          (contract over h)
  dw[e] = x[group e].T @ dy[group e]              (revisiting-accumulator
                                                   kernel)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._utils import interpret_mode as _interpret, no_x64 as _no_x64

__all__ = ["grouped_matmul", "grouped_matmul_t", "grouped_dw"]


def _pick_block(dim, want):
    """Largest block <= ``want`` that tiles ``dim`` exactly, preferring
    lane-aligned (multiples of 128) blocks; falls back to the whole dim
    (e.g. h=1408 at want=2048 -> 1408; d=3584 at want=2048 -> 1792)."""
    want = min(want, dim)
    if dim % want == 0:
        return want
    for b in range(want, 0, -1):
        if dim % b == 0 and b % 128 == 0:
            return b
    return dim


def _fwd_kernel(gid_ref, x_ref, w_ref, o_ref, *, transpose_rhs):
    x = x_ref[...]
    w = w_ref[...]  # (None, a, b) BlockSpec squeezes the expert dim
    dn = (((1,), (1,)), ((), ())) if transpose_rhs \
        else (((1,), (0,)), ((), ()))
    acc = lax.dot_general(x, w, dn, preferred_element_type=jnp.float32)
    o_ref[...] = acc.astype(o_ref.dtype)


def _gmm_call(x, w, tile_gid, transpose_rhs, bn):
    """y[t] = x[t] @ w[gid(t)] (or @ w[gid(t)].T when transpose_rhs).

    x [P, k_dim]; w [E, d, h] contracting d (or h when transposed);
    output [P, h] (or [P, d]). bn tiles the output feature dim; the
    contraction dim is whole (one MXU pass per tile)."""
    P, kdim = x.shape
    E = w.shape[0]
    out_dim = w.shape[1] if transpose_rhs else w.shape[2]
    nr = tile_gid.shape[0]
    bm = P // nr
    assert bm * nr == P, (P, nr)
    bn = _pick_block(out_dim, bn)
    nj = out_dim // bn

    if transpose_rhs:
        w_spec = pl.BlockSpec((None, bn, kdim),
                              lambda i, j, g: (g[i], j, 0))
    else:
        w_spec = pl.BlockSpec((None, kdim, bn),
                              lambda i, j, g: (g[i], 0, j))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nr, nj),
        in_specs=[
            pl.BlockSpec((bm, kdim), lambda i, j, g: (i, 0)),
            w_spec,
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, g: (i, j)),
    )
    with _no_x64():
        return pl.pallas_call(
            functools.partial(_fwd_kernel, transpose_rhs=transpose_rhs),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((P, out_dim), x.dtype),
            interpret=_interpret(),
        )(tile_gid, x, w)


def _dw_kernel(gid_ref, x_ref, dy_ref, o_ref, acc_ref, *, nr):
    r = pl.program_id(2)
    gid = gid_ref[r]
    first = (r == 0) | (gid != gid_ref[jnp.maximum(r - 1, 0)])
    last = (r == nr - 1) | (gid != gid_ref[jnp.minimum(r + 1, nr - 1)])

    @pl.when(first)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # [bm, bd].T @ [bm, bh] -> [bd, bh], f32 accumulation on the MXU
    acc_ref[...] += lax.dot_general(
        x_ref[...], dy_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(last)
    def _():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _dw_call(x, dy, tile_gid, n_experts, bd, bh):
    """dw[e] = x[group e].T @ dy[group e]  -> [E, d, h].

    Grid (nd, nh, nr) with the row sweep innermost: the [bd, bh] f32
    accumulator is zeroed at each group's first tile and flushed to the
    (gid, jd, jh) output block at its last — group tiles are contiguous,
    so the revisited output block is written exactly once before Pallas
    pages it out. Every expert owns >= 1 tile (zero rows for empty
    groups), so all E blocks get written."""
    P, d = x.shape
    h = dy.shape[1]
    nr = tile_gid.shape[0]
    bm = P // nr
    bd = _pick_block(d, bd)
    bh = _pick_block(h, bh)
    nd, nh = d // bd, h // bh

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nd, nh, nr),
        in_specs=[
            pl.BlockSpec((bm, bd), lambda jd, jh, r, g: (r, jd)),
            pl.BlockSpec((bm, bh), lambda jd, jh, r, g: (r, jh)),
        ],
        out_specs=pl.BlockSpec((None, bd, bh),
                               lambda jd, jh, r, g: (g[r], jd, jh)),
        scratch_shapes=[pltpu.VMEM((bd, bh), jnp.float32)],
    )
    with _no_x64():
        return pl.pallas_call(
            functools.partial(_dw_kernel, nr=nr),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((n_experts, d, h), x.dtype),
            interpret=_interpret(),
        )(tile_gid, x, dy)


# static defaults — the pre-tuner tiles (VERDICT r5: "no recorded
# sweep"); the tuner cache overrides them per shape via _tile_config
_DEFAULT_TILES = {"bn": 2048, "bd": 512, "bh": 2048}


def _tile_config(w_shape, dtype) -> dict:
    """Tuned bn/bd/bh for this [E, d, h] bank from the autotuner cache
    (user override > cache > _DEFAULT_TILES — paddle_tpu.tuner.lookup),
    host-side at trace time. Explicit keyword tiles at the call site
    bypass this entirely."""
    from ...tuner import lookup
    E, d, h = (int(s) for s in w_shape)
    cfg = dict(_DEFAULT_TILES)
    tuned = lookup("grouped_matmul", {"d": d, "h": h, "E": E}, str(dtype))
    if tuned:
        cfg.update({k: int(v) for k, v in tuned.items() if k in cfg})
    return cfg


def grouped_matmul_t(dy, w, tile_gid, bn=None):
    """dx for the grouped matmul: dy [P, h] @ w[gid].T -> [P, d]."""
    if bn is None:
        bn = _tile_config(w.shape, dy.dtype)["bn"]
    return _gmm_call(dy, w, tile_gid, transpose_rhs=True, bn=bn)


def grouped_dw(x, dy, tile_gid, n_experts, bd=None, bh=None):
    if bd is None or bh is None:
        cfg = _tile_config((n_experts, x.shape[1], dy.shape[1]), x.dtype)
        bd = cfg["bd"] if bd is None else bd
        bh = cfg["bh"] if bh is None else bh
    return _dw_call(x, dy, tile_gid, n_experts, bd=bd, bh=bh)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _gmm_core(x, w, tile_gid, bn, bd, bh):
    return _gmm_call(x, w, tile_gid, transpose_rhs=False, bn=bn)


def _gmm_core_fwd(x, w, tile_gid, bn, bd, bh):
    return _gmm_core(x, w, tile_gid, bn, bd, bh), (x, w, tile_gid)


def _gmm_core_bwd(bn, bd, bh, res, dy):
    x, w, tile_gid = res
    dx = grouped_matmul_t(dy, w, tile_gid, bn=bn)
    dw = grouped_dw(x, dy, tile_gid, w.shape[0], bd=bd, bh=bh)
    # tile_gid is routing data: int32 primal -> float0 cotangent
    return dx, dw.astype(w.dtype), np.zeros(tile_gid.shape,
                                            jax.dtypes.float0)


_gmm_core.defvjp(_gmm_core_fwd, _gmm_core_bwd)


def grouped_matmul(x, w, tile_gid, bn=None, bd=None, bh=None):
    """Differentiable grouped matmul: y[t] = x[t] @ w[tile_gid(t//bm)].

    tile_gid rides the custom_vjp as an explicit primal (saved in
    residuals) — a closure over it would leak its tracer across
    jax.checkpoint boundaries (use_recompute re-runs the bwd in a
    fresh trace).

    bn/bd/bh: output-feature tile (fwd + dx) and the dw [bd, bh]
    accumulator tiles. None (the normal path) resolves through the
    autotuner cache, falling back to the static defaults; the sweep
    CLI passes candidates explicitly. All three are static ints — they
    select the compiled Pallas grid, not runtime values."""
    cfg = None
    if bn is None or bd is None or bh is None:
        cfg = _tile_config(w.shape, x.dtype)
    bn = cfg["bn"] if bn is None else bn
    bd = cfg["bd"] if bd is None else bd
    bh = cfg["bh"] if bh is None else bh
    return _gmm_core(x, w, tile_gid, bn, bd, bh)


# -- tunable surface ---------------------------------------------------------
# Registered next to the knob it tunes (tuner subsystem contract): the
# bn/bd/bh tile grid, its validity rule, and a static cost model for
# roofline pruning. Shape key is the weight bank (d, h, E) — the tiles
# depend on feature dims, not on the routed row count P, so one cache
# entry serves every batch size of a model.

_NOMINAL_ROWS = 8192        # cost-model row count; cancels in pruning ratios


def _gmm_surface_cost(config, shape):
    """(flops, bytes) lower-bound inputs for one fwd+dx+dw trial under
    ``config``. FLOPs are tile-invariant (3 · 2PdH); bytes are NOT:
    the fwd/dx x-operand re-streams once per output-feature tile
    (h/bn resp. d/bn sweeps) and the dw kernel re-streams x and dy
    per [bd, bh] accumulator tile — small tiles are provably
    memory-bound-worse, which is exactly what the engine prunes."""
    d, h, E = shape["d"], shape["h"], shape["E"]
    P = _NOMINAL_ROWS
    bn = max(_pick_block(h, config["bn"]), 1)
    bn_dx = max(_pick_block(d, config["bn"]), 1)
    bd = max(_pick_block(d, config["bd"]), 1)
    bh = max(_pick_block(h, config["bh"]), 1)
    flops = 3 * 2.0 * P * d * h
    bank = E * d * h
    fwd_b = P * d * (-(-h // bn)) + bank + P * h
    dx_b = P * h * (-(-d // bn_dx)) + bank + P * d
    dw_b = P * d * (-(-h // bh)) + P * h * (-(-d // bd)) + bank
    return flops, 2.0 * (fwd_b + dx_b + dw_b)


def _register_gmm_surface():
    from ...tuner.surface import TunableSurface, register_surface

    def _candidates(shape):
        return [{"bn": bn, "bd": bd, "bh": bh}
                for bn in (512, 1024, 2048)
                for bd in (128, 256, 512)
                for bh in (512, 1024, 2048)]

    def _is_valid(config, shape):
        return all(config[k] >= 128 and config[k] % 128 == 0
                   for k in ("bn", "bd", "bh"))

    register_surface(TunableSurface(
        name="grouped_matmul",
        params=("bn", "bd", "bh"),
        default=dict(_DEFAULT_TILES),
        candidates=_candidates,
        is_valid=_is_valid,
        cost_fn=_gmm_surface_cost,
        describe="Pallas grouped-matmul tiles: fwd/dx output-feature "
                 "tile bn, dw accumulator tile [bd, bh]. Shape key: "
                 "d/h/E of the expert bank."))


_register_gmm_surface()


def grouped_matmul_cost(x_shape, w_shape, train=False):
    """Static FLOPs/bytes for one :func:`grouped_matmul` call (profiler
    cost-accounting surface): x [P, d] @ bank [E, d, h]. The weight
    bank streams HBM once per call (the block-revisit guarantee in the
    kernel design above), not once per row tile — the byte convention
    lives in profiler/cost.py; this is the kernel-side entry point.
    ``train=True`` adds the dx (grouped_matmul_t) + dw (grouped_dw)
    backward calls."""
    from ...profiler import cost as _cost
    P, d = int(x_shape[0]), int(x_shape[1])
    E, _, h = (int(s) for s in w_shape)
    fwd = _cost.grouped_matmul_cost(P, d, h, E)
    return fwd * 3 if train else fwd
