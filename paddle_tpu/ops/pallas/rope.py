"""Rotary position embedding (RoPE).

Reference: phi fused_rope kernel (UNVERIFIED). On TPU the rotate+multiply
is bandwidth-bound elementwise work that XLA fuses into the surrounding
matmuls, so the jnp formulation IS the fused kernel; a bespoke Pallas kernel
buys nothing here (measured wisdom from the pallas guide: don't hand-write
what XLA already fuses)."""

from __future__ import annotations

import functools

import jax.numpy as jnp

__all__ = ["build_sin_cos", "apply_rope", "rope_reference"]


@functools.lru_cache(maxsize=32)
def _sin_cos_np(seq_len: int, dim: int, base: float):
    import numpy as np
    inv = 1.0 / (base ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    t = np.arange(seq_len, dtype=np.float64)
    freqs = np.outer(t, inv)  # [S, D/2]
    return np.sin(freqs), np.cos(freqs)


def build_sin_cos(seq_len, dim, base=10000.0, dtype=jnp.float32):
    s, c = _sin_cos_np(int(seq_len), int(dim), float(base))
    return jnp.asarray(s, jnp.float32), jnp.asarray(c, jnp.float32)


def apply_rope(x, sin, cos, position_ids=None, neox=True):
    """x: [B, S, H, D]; sin/cos: [S, D/2] (fp32). Returns same dtype as x."""
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    if position_ids is not None:
        sin = jnp.take(sin, position_ids, axis=0)  # [B, S, D/2]
        cos = jnp.take(cos, position_ids, axis=0)
        sin = sin[:, :, None, :]
        cos = cos[:, :, None, :]
    else:
        sin = sin[None, :, None, :]
        cos = cos[None, :, None, :]
    d2 = xf.shape[-1] // 2
    if neox:
        x1 = xf[..., :d2]
        x2 = xf[..., d2:]
        out = jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    else:
        x1 = xf[..., 0::2]
        x2 = xf[..., 1::2]
        r1 = x1 * cos - x2 * sin
        r2 = x2 * cos + x1 * sin
        out = jnp.stack([r1, r2], axis=-1).reshape(xf.shape)
    return out.astype(orig_dtype)


rope_reference = apply_rope
