"""Fused RMSNorm Pallas kernel (role of phi fused rms_norm, UNVERIFIED).

Forward is a row-wise reduction + scale — one VMEM pass per block of rows.
Backward uses a custom VJP with a fused Pallas kernel for dx and an XLA
reduction for dw (dw is a full-rows reduction; XLA's tree reduction over
HBM is already optimal for it)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._utils import interpret_mode as _interpret, no_x64 as _no_x64



__all__ = ["rms_norm", "rms_norm_reference"]


def rms_norm_reference(x, w, eps=1e-6):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(ms + eps)).astype(x.dtype) * w


def _fwd_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    normed = x * jax.lax.rsqrt(ms + eps)
    o_ref[:] = (normed * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _dx_kernel(x_ref, w_ref, g_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    d = x.shape[-1]
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    gw = g * w
    # dx = inv * gw - x * inv^3 * mean(gw * x)
    dot = jnp.mean(gw * x, axis=-1, keepdims=True)
    o_ref[:] = (inv * gw - x * (inv ** 3) * dot).astype(o_ref.dtype)


# sweep hook (same contract as flash_attention.force_blocks): trials
# pin a candidate here instead of going through the tuner cache.
# Thread-local so one thread's trial never leaks into another's trace.
import threading as _threading

_forced_tls = _threading.local()


class force_rows_block:
    """Context manager pinning the rows-per-program block for trials
    (this thread only)."""

    def __init__(self, block_rows):
        self._val = int(block_rows)

    def __enter__(self):
        self._prev = getattr(_forced_tls, "rows_block", None)
        _forced_tls.rows_block = self._val
        return self

    def __exit__(self, *exc):
        _forced_tls.rows_block = self._prev
        return False


def _rows_block(n_rows: int, d: int | None = None, dtype=None) -> int:
    """Rows per program, clamped to the (8-aligned) row count. The 256
    default is the static pick; the tuner cache ("rms_norm" surface,
    keyed by feature dim) overrides it when a sweep recorded a winner."""
    want = 256
    forced = getattr(_forced_tls, "rows_block", None)
    if forced is not None:
        want = forced
    elif d is not None:
        from ...tuner import lookup
        cfg = lookup("rms_norm", {"d": int(d)}, str(dtype))
        if cfg:
            want = int(cfg.get("block_rows", want))
    return min(want, -(-n_rows // 8) * 8)


def _pad_rows(a, n_pad):
    if n_pad == a.shape[0]:
        return a
    return jnp.pad(a, ((0, n_pad - a.shape[0]), (0, 0)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, w, eps=1e-6):
    return _rms_fwd_impl(x, w, eps)


def _rms_fwd_impl(x, w, eps):
    orig_shape = x.shape
    d = orig_shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    blk = _rows_block(n, d, x.dtype)
    n_p = -(-n // blk) * blk  # pad rows to the block multiple
    with _no_x64():
        out = pl.pallas_call(
            functools.partial(_fwd_kernel, eps=eps),
            grid=(n_p // blk,),
            in_specs=[pl.BlockSpec((blk, d), lambda i: (i, 0)),
                      pl.BlockSpec((d,), lambda i: (0,))],
            out_specs=pl.BlockSpec((blk, d), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n_p, d), x.dtype),
            interpret=_interpret(),
        )(_pad_rows(x2, n_p), w)
    return out[:n].reshape(orig_shape)


def _rms_fwd(x, w, eps):
    return _rms_fwd_impl(x, w, eps), (x, w)


def _rms_bwd(eps, res, g):
    x, w = res
    orig_shape = x.shape
    d = orig_shape[-1]
    x2 = x.reshape(-1, d)
    g2 = g.reshape(-1, d)
    n = x2.shape[0]
    blk = _rows_block(n, d, x.dtype)
    n_p = -(-n // blk) * blk
    with _no_x64():
        dx = pl.pallas_call(
            functools.partial(_dx_kernel, eps=eps),
            grid=(n_p // blk,),
            in_specs=[pl.BlockSpec((blk, d), lambda i: (i, 0)),
                      pl.BlockSpec((d,), lambda i: (0,)),
                      pl.BlockSpec((blk, d), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((blk, d), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n_p, d), x.dtype),
            interpret=_interpret(),
        )(_pad_rows(x2, n_p), w, _pad_rows(g2, n_p))
    dx = dx[:n]
    # dw: reduction over all rows — XLA's job
    xf = x2.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(ms + eps)
    dw = jnp.sum(g2.astype(jnp.float32) * normed, axis=0).astype(w.dtype)
    return dx.reshape(orig_shape), dw


rms_norm.defvjp(_rms_fwd, _rms_bwd)


# -- tunable surface ---------------------------------------------------------

def _register_rms_surface():
    from ...tuner.surface import TunableSurface, register_surface

    register_surface(TunableSurface(
        name="rms_norm",
        params=("block_rows",),
        default={"block_rows": 256},
        candidates=lambda shape: [{"block_rows": b}
                                  for b in (64, 128, 256, 512, 1024)],
        is_valid=lambda config, shape: (config["block_rows"] % 8 == 0
                                        and config["block_rows"] > 0),
        describe="Rows per program of the fused RMSNorm fwd/dx kernels "
                 "(bandwidth-bound VMEM pass). Shape key: feature dim."))


_register_rms_surface()
