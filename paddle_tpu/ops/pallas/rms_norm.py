"""Fused RMSNorm Pallas kernel (role of phi fused rms_norm, UNVERIFIED).

Forward is a row-wise reduction + scale — one VMEM pass per block of rows.
Backward uses a custom VJP with a fused Pallas kernel for dx and an XLA
reduction for dw (dw is a full-rows reduction; XLA's tree reduction over
HBM is already optimal for it).

:func:`rms_norm_residual` is the Liger-style residual-add variant for the
decoder hot path: one VMEM pass reads ``x`` and ``res`` and writes BOTH
``y = rmsnorm(x + res) * w`` and ``r = x + res`` — the residual stream
never makes a separate HBM round trip through an add op. The backward
kernel fuses dx/dres (they are the same tensor: d(x+res) distributes)
with the rmsnorm dx math, so the pair costs one extra output, not an
extra pass."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._utils import interpret_mode as _interpret, no_x64 as _no_x64



__all__ = ["rms_norm", "rms_norm_reference", "rms_norm_residual",
           "rms_norm_residual_reference", "rms_norm_cost"]


def rms_norm_reference(x, w, eps=1e-6):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(ms + eps)).astype(x.dtype) * w


def _fwd_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    normed = x * jax.lax.rsqrt(ms + eps)
    o_ref[:] = (normed * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _dx_kernel(x_ref, w_ref, g_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    d = x.shape[-1]
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    gw = g * w
    # dx = inv * gw - x * inv^3 * mean(gw * x)
    dot = jnp.mean(gw * x, axis=-1, keepdims=True)
    o_ref[:] = (inv * gw - x * (inv ** 3) * dot).astype(o_ref.dtype)


# sweep hook (same contract as flash_attention.force_blocks): trials
# pin a candidate here instead of going through the tuner cache.
# Thread-local so one thread's trial never leaks into another's trace.
import threading as _threading

_forced_tls = _threading.local()


class force_rows_block:
    """Context manager pinning the rows-per-program block for trials
    (this thread only)."""

    def __init__(self, block_rows):
        self._val = int(block_rows)

    def __enter__(self):
        self._prev = getattr(_forced_tls, "rows_block", None)
        _forced_tls.rows_block = self._val
        return self

    def __exit__(self, *exc):
        _forced_tls.rows_block = self._prev
        return False


def _rows_block(n_rows: int, d: int | None = None, dtype=None) -> int:
    """Rows per program, clamped to the (8-aligned) row count. The 256
    default is the static pick; the tuner cache ("rms_norm" surface,
    keyed by feature dim) overrides it when a sweep recorded a winner."""
    want = 256
    forced = getattr(_forced_tls, "rows_block", None)
    if forced is not None:
        want = forced
    elif d is not None:
        from ...tuner import lookup
        cfg = lookup("rms_norm", {"d": int(d)}, str(dtype))
        if cfg:
            want = int(cfg.get("block_rows", want))
    return min(want, -(-n_rows // 8) * 8)


def _pad_rows(a, n_pad):
    if n_pad == a.shape[0]:
        return a
    # explicit-dtype fill: jnp.pad's weak-int 0 re-concretizes as i64
    # under an outer x64-enabled trace and fails interpret lowering
    return jnp.pad(a, ((0, n_pad - a.shape[0]), (0, 0)),
                   constant_values=a.dtype.type(0))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, w, eps=1e-6):
    return _rms_fwd_impl(x, w, eps)


def _rms_fwd_impl(x, w, eps):
    orig_shape = x.shape
    d = orig_shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    blk = _rows_block(n, d, x.dtype)
    n_p = -(-n // blk) * blk  # pad rows to the block multiple
    with _no_x64():
        out = pl.pallas_call(
            functools.partial(_fwd_kernel, eps=eps),
            grid=(n_p // blk,),
            in_specs=[pl.BlockSpec((blk, d), lambda i: (i, 0)),
                      pl.BlockSpec((d,), lambda i: (0,))],
            out_specs=pl.BlockSpec((blk, d), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n_p, d), x.dtype),
            interpret=_interpret(),
        )(_pad_rows(x2, n_p), w)
    return out[:n].reshape(orig_shape)


def _rms_fwd(x, w, eps):
    return _rms_fwd_impl(x, w, eps), (x, w)


def _rms_bwd(eps, res, g):
    x, w = res
    orig_shape = x.shape
    d = orig_shape[-1]
    x2 = x.reshape(-1, d)
    g2 = g.reshape(-1, d)
    n = x2.shape[0]
    blk = _rows_block(n, d, x.dtype)
    n_p = -(-n // blk) * blk
    with _no_x64():
        dx = pl.pallas_call(
            functools.partial(_dx_kernel, eps=eps),
            grid=(n_p // blk,),
            in_specs=[pl.BlockSpec((blk, d), lambda i: (i, 0)),
                      pl.BlockSpec((d,), lambda i: (0,)),
                      pl.BlockSpec((blk, d), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((blk, d), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n_p, d), x.dtype),
            interpret=_interpret(),
        )(_pad_rows(x2, n_p), w, _pad_rows(g2, n_p))
    dx = dx[:n]
    # dw: reduction over all rows — XLA's job
    xf = x2.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(ms + eps)
    dw = jnp.sum(g2.astype(jnp.float32) * normed, axis=0).astype(w.dtype)
    return dx.reshape(orig_shape), dw


rms_norm.defvjp(_rms_fwd, _rms_bwd)


# -- tunable surface ---------------------------------------------------------

def _register_rms_surface():
    from ...tuner.surface import TunableSurface, register_surface

    register_surface(TunableSurface(
        name="rms_norm",
        params=("block_rows",),
        default={"block_rows": 256},
        candidates=lambda shape: [{"block_rows": b}
                                  for b in (64, 128, 256, 512, 1024)],
        is_valid=lambda config, shape: (config["block_rows"] % 8 == 0
                                        and config["block_rows"] > 0),
        describe="Rows per program of the fused RMSNorm fwd/dx kernels "
                 "(bandwidth-bound VMEM pass). Shape key: feature dim."))


_register_rms_surface()


# ===========================================================================
# Fused RMSNorm + residual (the decoder-layer pair: ``r = x + res;
# y = rmsnorm(r) * w`` in one VMEM pass, both outputs written)
# ===========================================================================


def rms_norm_residual_reference(x, res, w, eps=1e-6):
    """Oracle: residual add in the INPUT dtype (exactly what the
    unfused ``x + res`` followed by ``rms_norm`` computes), then the
    f32 norm — interpret-mode parity tests pin the kernel to this."""
    r = x + res
    rf = r.astype(jnp.float32)
    ms = jnp.mean(jnp.square(rf), axis=-1, keepdims=True)
    y = (rf * jax.lax.rsqrt(ms + eps)).astype(r.dtype) * w
    return y, r


def _fwd_res_kernel(x_ref, res_ref, w_ref, y_ref, r_ref, *, eps):
    # the add happens in the INPUT dtype (bit-parity with the unfused
    # ``x + res``), the norm in f32 — same accumulation discipline as
    # the plain kernel above
    r = x_ref[:] + res_ref[:]
    r_ref[:] = r
    rf = r.astype(jnp.float32)
    ms = jnp.mean(jnp.square(rf), axis=-1, keepdims=True)
    normed = rf * jax.lax.rsqrt(ms + eps)
    y_ref[:] = (normed * w_ref[:].astype(jnp.float32)).astype(y_ref.dtype)


def _dres_kernel(x_ref, res_ref, w_ref, gy_ref, gr_ref, o_ref, *, eps):
    # d(x+res) through the norm + the residual-stream grad in one pass:
    # dh = rms_dx(gy) + gr, and dx == dres == dh
    r = (x_ref[:] + res_ref[:]).astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    gy = gy_ref[:].astype(jnp.float32)
    ms = jnp.mean(jnp.square(r), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    gw = gy * w
    dot = jnp.mean(gw * r, axis=-1, keepdims=True)
    dh = inv * gw - r * (inv ** 3) * dot + gr_ref[:].astype(jnp.float32)
    o_ref[:] = dh.astype(o_ref.dtype)


class force_residual_rows_block:
    """Context manager pinning the rows-per-program block of the
    residual variant for trials (this thread only)."""

    def __init__(self, block_rows):
        self._val = int(block_rows)

    def __enter__(self):
        self._prev = getattr(_forced_tls, "res_rows_block", None)
        _forced_tls.res_rows_block = self._val
        return self

    def __exit__(self, *exc):
        _forced_tls.res_rows_block = self._prev
        return False


def _res_rows_block(n_rows: int, d: int | None = None, dtype=None) -> int:
    """Rows per program for the residual variant ("rms_norm_residual"
    surface — tuned separately from the plain kernel: the extra
    input/output streams shift the VMEM sweet spot)."""
    want = 256
    forced = getattr(_forced_tls, "res_rows_block", None)
    if forced is not None:
        want = forced
    elif d is not None:
        from ...tuner import lookup
        cfg = lookup("rms_norm_residual", {"d": int(d)}, str(dtype))
        if cfg:
            want = int(cfg.get("block_rows", want))
    return min(want, -(-n_rows // 8) * 8)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def rms_norm_residual(x, res, w, eps=1e-6):
    """``(rmsnorm(x + res) * w, x + res)`` in one fused pass. Both
    outputs are differentiable (the second feeds the residual stream);
    backward fuses the norm's dx with the residual grad — dx and dres
    are one tensor."""
    return _rms_res_fwd_impl(x, res, w, eps)


def _rms_res_fwd_impl(x, res, w, eps):
    orig_shape = x.shape
    d = orig_shape[-1]
    x2 = x.reshape(-1, d)
    r2 = res.reshape(-1, d)
    n = x2.shape[0]
    blk = _res_rows_block(n, d, x.dtype)
    n_p = -(-n // blk) * blk
    with _no_x64():
        y, r = pl.pallas_call(
            functools.partial(_fwd_res_kernel, eps=eps),
            grid=(n_p // blk,),
            in_specs=[pl.BlockSpec((blk, d), lambda i: (i, 0)),
                      pl.BlockSpec((blk, d), lambda i: (i, 0)),
                      pl.BlockSpec((d,), lambda i: (0,))],
            out_specs=[pl.BlockSpec((blk, d), lambda i: (i, 0)),
                       pl.BlockSpec((blk, d), lambda i: (i, 0))],
            out_shape=[jax.ShapeDtypeStruct((n_p, d), x.dtype),
                       jax.ShapeDtypeStruct((n_p, d), x.dtype)],
            interpret=_interpret(),
        )(_pad_rows(x2, n_p), _pad_rows(r2, n_p), w)
    return (y[:n].reshape(orig_shape), r[:n].reshape(orig_shape))


def _rms_res_fwd(x, res, w, eps):
    return _rms_res_fwd_impl(x, res, w, eps), (x, res, w)


def _rms_res_bwd(eps, resids, gs):
    x, res, w = resids
    gy, gr = gs
    orig_shape = x.shape
    d = orig_shape[-1]
    x2 = x.reshape(-1, d)
    r2 = res.reshape(-1, d)
    gy2 = gy.reshape(-1, d)
    gr2 = gr.reshape(-1, d)
    n = x2.shape[0]
    blk = _res_rows_block(n, d, x.dtype)
    n_p = -(-n // blk) * blk
    with _no_x64():
        dh = pl.pallas_call(
            functools.partial(_dres_kernel, eps=eps),
            grid=(n_p // blk,),
            in_specs=[pl.BlockSpec((blk, d), lambda i: (i, 0)),
                      pl.BlockSpec((blk, d), lambda i: (i, 0)),
                      pl.BlockSpec((d,), lambda i: (0,)),
                      pl.BlockSpec((blk, d), lambda i: (i, 0)),
                      pl.BlockSpec((blk, d), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((blk, d), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n_p, d), x.dtype),
            interpret=_interpret(),
        )(_pad_rows(x2, n_p), _pad_rows(r2, n_p), w,
          _pad_rows(gy2, n_p), _pad_rows(gr2, n_p))
    dh = dh[:n].reshape(orig_shape)
    # dw: full-rows reduction — XLA's job (same split as the plain bwd)
    hf = (x2 + r2).astype(jnp.float32)
    ms = jnp.mean(jnp.square(hf), axis=-1, keepdims=True)
    normed = hf * jax.lax.rsqrt(ms + eps)
    dw = jnp.sum(gy2.astype(jnp.float32) * normed, axis=0).astype(w.dtype)
    return dh, dh, dw


rms_norm_residual.defvjp(_rms_res_fwd, _rms_res_bwd)


def _register_rms_residual_surface():
    from ...tuner.surface import TunableSurface, register_surface

    register_surface(TunableSurface(
        name="rms_norm_residual",
        params=("block_rows",),
        default={"block_rows": 256},
        candidates=lambda shape: [{"block_rows": b}
                                  for b in (64, 128, 256, 512, 1024)],
        is_valid=lambda config, shape: (config["block_rows"] % 8 == 0
                                        and config["block_rows"] > 0),
        describe="Rows per program of the fused RMSNorm+residual "
                 "fwd/dh kernels (two streams in, two out — tuned "
                 "separately from plain rms_norm). Shape key: feature "
                 "dim."))


_register_rms_residual_surface()


def rms_norm_cost(x_shape, residual=False, train=False):
    """Static FLOPs/bytes for one (residual-)rmsnorm call (profiler
    cost-accounting surface): x ``[..., d]``. Bandwidth-bound by
    construction — the fused pass reads each stream once and writes
    each output once; the residual variant adds one input and one
    output stream but zero extra passes."""
    import math

    from ...profiler.cost import rms_norm_cost as _cost
    d = int(x_shape[-1])
    n = int(math.prod(int(s) for s in x_shape[:-1]))
    return _cost(n, d, residual=residual, train=train)
