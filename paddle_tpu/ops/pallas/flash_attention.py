"""Flash attention Pallas kernel for TPU.

Role of the reference's flash-attn CUDA integration
(phi fused attention kernels, UNVERIFIED). Layout: [B, S, H, D] in/out
(paddle convention); internally blocks over (batch*heads, q_blocks) with an
online-softmax accumulation loop over kv blocks — the classic TPU flash
forward. Backward is HAND-WRITTEN Pallas too (``_dkv_kernel`` /
``_dq_kernel`` below): bf16 operands with fp32 accumulation, recomputing
per-block logits from the saved log-sum-exp so memory stays O(S·D) (no
S×S materialization). Block sizes come from
``FLAGS_flash_attn_block_q/kv``; the best setting is config-dependent —
on v5e, 256/512 beats 512/512 by ~2 MFU points under remat at hidden
2560, while 512/512 won at the 0.89B sweet spot (see BASELINE.md for
the current tuning record).

GQA/MQA (fewer kv heads than q heads) is handled by repeating kv heads."""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._utils import interpret_mode as _interpret, no_x64 as _no_x64



__all__ = ["flash_attention", "flash_attention_reference"]

_NEG_INF = -1e30

# sweep hook: the trial engine pins candidate blocks here (via
# force_blocks) while it compiles fresh variants — candidates must not
# ride set_flags, which would mark the flags user-explicit and defeat
# the override>cache>default precedence afterwards. THREAD-LOCAL: a
# tune-on-first-call search on one thread must not leak its trial
# blocks into unrelated traces on another.
import threading as _threading

_forced_tls = _threading.local()


class force_blocks:
    """Context manager pinning (block_q, block_kv) for trials (this
    thread only)."""

    def __init__(self, block_q, block_kv):
        self._val = (int(block_q), int(block_kv))

    def __enter__(self):
        self._prev = getattr(_forced_tls, "blocks", None)
        _forced_tls.blocks = self._val
        return self

    def __exit__(self, *exc):
        _forced_tls.blocks = self._prev
        return False


def _resolve_blocks(sq, sk, d, dtype):
    """(block_q, block_kv) for this shape, precedence (documented in
    framework/flags.py): forced trial candidate > explicit user flag
    (env or set_flags) > tuner cache > flag default. Host-side at
    trace time — blocks are static ints selecting the compiled grid."""
    from ...framework import flags
    forced = getattr(_forced_tls, "blocks", None)
    if forced is not None:
        return forced
    bq = int(flags.flag("FLAGS_flash_attn_block_q"))
    bkv = int(flags.flag("FLAGS_flash_attn_block_kv"))
    bq_explicit = flags.flag_source("FLAGS_flash_attn_block_q") != "default"
    bkv_explicit = flags.flag_source("FLAGS_flash_attn_block_kv") \
        != "default"
    if not (bq_explicit and bkv_explicit):
        from ...tuner import lookup
        cfg = lookup("flash_attention",
                     {"sq": int(sq), "sk": int(sk), "d": int(d)},
                     str(dtype))
        if cfg:
            if not bq_explicit:
                bq = int(cfg.get("block_q", bq))
            if not bkv_explicit:
                bkv = int(cfg.get("block_kv", bkv))
    return bq, bkv


def flash_attention_reference(q, k, v, causal=False, scale=None):
    """[B, S, H, D] reference (fp32 softmax)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_len_q, seq_len_k):
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale  # [block_q, d]
    # bottom-right-aligned causal offset (standard flash/decode semantics):
    # query i may see keys k_pos <= i + (seq_len_k - seq_len_q)
    causal_offset = seq_len_k - seq_len_q

    def body(start_k, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.dslice(start_k * block_k, block_k),
                  slice(None)].astype(jnp.float32)
        v = v_ref[pl.dslice(start_k * block_k, block_k),
                  slice(None)].astype(jnp.float32)
        s = q @ k.T  # [block_q, block_k]
        k_pos = start_k * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < seq_len_k  # mask padded keys
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & (q_pos + causal_offset >= k_pos)
        s = jnp.where(valid, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + p @ v
        return acc, m_new, l_new

    d = q_ref.shape[-1]
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    n_k_blocks = -(-seq_len_k // block_k)  # padded kv block count
    if causal:
        # only kv blocks up to this q block's last visible key
        # participate (weak python ints keep int32 here; the pallas_call
        # is traced under _no_x64)
        last_visible = (qi + 1) * block_q + causal_offset
        nk = (last_visible + (block_k - 1)) // block_k
        num_k = jnp.minimum(jnp.maximum(nk, 0), n_k_blocks)
    else:
        num_k = n_k_blocks
    acc, m, l = jax.lax.fori_loop(0, num_k, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l[:, None]).astype(o_ref.dtype)
    # stats ride a 128-lane last dim (TPU tiling requires the last block
    # dim be 128-divisible; same convention as jax's official kernel)
    lse_ref[:] = jnp.broadcast_to((m + jnp.log(l))[:, None],
                                  (block_q, 128))


def _round_up(n, m):
    return -(-n // m) * m


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, scale=None):
    out, _ = _flash_fwd(q, k, v, causal, scale)
    return out


def _flash_fwd(q, k, v, causal, scale):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    if hk != h:  # GQA: repeat kv heads
        rep = h // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    bq, bkv = _resolve_blocks(sq, sk, d, q.dtype)
    block_q = min(bq, _round_up(sq, 8))
    block_k = min(bkv, _round_up(sk, 128))
    # [B, S, H, D] -> [B*H, S, D], padded to block multiples (the kernel
    # masks padded key positions; padded query rows are sliced off)
    sq_p = _round_up(sq, block_q)
    sk_p = _round_up(sk, block_k)
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    if sq_p != sq:
        qh = jnp.pad(qh, ((0, 0), (0, sq_p - sq), (0, 0)))
    if sk_p != sk:
        kh = jnp.pad(kh, ((0, 0), (0, sk_p - sk), (0, 0)))
        vh = jnp.pad(vh, ((0, 0), (0, sk_p - sk), (0, 0)))
    grid = (b * h, sq_p // block_q)
    with _no_x64():
        out, lse = pl.pallas_call(
            functools.partial(_fwd_kernel, scale=s, causal=causal,
                              block_q=block_q, block_k=block_k,
                              seq_len_q=sq, seq_len_k=sk),
            grid=grid,
            in_specs=[
                pl.BlockSpec((None, block_q, d),
                             lambda bh, qi: (bh, qi, 0)),
                pl.BlockSpec((None, sk_p, d), lambda bh, qi: (bh, 0, 0)),
                pl.BlockSpec((None, sk_p, d), lambda bh, qi: (bh, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block_q, d),
                             lambda bh, qi: (bh, qi, 0)),
                pl.BlockSpec((None, block_q, 128),
                             lambda bh, qi: (bh, qi, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
                jax.ShapeDtypeStruct((b * h, sq_p, 128), jnp.float32),
            ],
            interpret=_interpret(),
        )(qh, kh, vh)
    out4 = out[:, :sq].reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return out4, lse[:, :sq, 0]


def _fwd_rule(q, k, v, causal, scale):
    out, lse = _flash_fwd(q, k, v, causal, scale)
    return out, (q, k, v, out, lse)


def _dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                seq_len_q, seq_len_k):
    """One program owns one [block_k, d] kv block; loops over q blocks.
    Matmuls keep bf16 operands with fp32 accumulation (MXU-native)."""
    ki = pl.program_id(1)
    causal_offset = seq_len_k - seq_len_q
    k = k_ref[:]   # [block_k, d] input dtype
    v = v_ref[:]
    d = k_ref.shape[-1]

    def body(qi, carry):
        dk_acc, dv_acc = carry
        q = q_ref[pl.dslice(qi * block_q, block_q), slice(None)]
        g = g_ref[pl.dslice(qi * block_q, block_q), slice(None)]
        # lse/delta ride a lane-broadcast [sq_p, 128] layout (the fwd lse
        # convention — TPU tiling wants 128-lane tiles; reshaping across
        # lanes is an unsupported Mosaic shape cast, so read one column)
        lse = lse_ref[pl.dslice(qi * block_q, block_q), 0:1]
        delta = delta_ref[pl.dslice(qi * block_q, block_q), 0:1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = (q_pos < seq_len_q) & (k_pos < seq_len_k)
        if causal:
            valid = valid & (q_pos + causal_offset >= k_pos)
        s = jnp.where(valid, s, _NEG_INF)
        # fully-masked rows have lse ~= -1e30, so exp(s - lse) would be 1
        # for masked entries — mask p explicitly, don't rely on s - lse
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        pb = p.astype(k.dtype)
        # dv += p^T @ g ; dp = g @ v^T ; ds = p*(dp-delta)*scale
        dv_acc = dv_acc + jax.lax.dot_general(
            pb, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    n_q_blocks = -(-seq_len_q // block_q)
    if causal:
        # first q block whose last row can see this kv block
        first = (ki * block_k - causal_offset) // block_q
        q_start = jnp.clip(first, 0, n_q_blocks)
    else:
        q_start = 0
    acc0 = (jnp.zeros((block_k, d), jnp.float32),
            jnp.zeros((block_k, d), jnp.float32))
    dk, dv = jax.lax.fori_loop(q_start, n_q_blocks, body, acc0)
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref, *,
               scale, causal, block_q, block_k, seq_len_q, seq_len_k):
    """One program owns one [block_q, d] q block; loops over kv blocks."""
    qi = pl.program_id(1)
    causal_offset = seq_len_k - seq_len_q
    q = q_ref[:]
    g = g_ref[:]
    lse = lse_ref[:, 0:1]       # [block_q, 1] from the lane-broadcast tile
    delta = delta_ref[:, 0:1]
    d = q_ref.shape[-1]

    def body(ki, dq_acc):
        k = k_ref[pl.dslice(ki * block_k, block_k), slice(None)]
        v = v_ref[pl.dslice(ki * block_k, block_k), slice(None)]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < seq_len_k
        if causal:
            valid = valid & (q_pos + causal_offset >= k_pos)
        s = jnp.where(valid, s, _NEG_INF)
        # explicit mask: see _dkv_kernel (fully-masked rows break s - lse)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dq_acc = dq_acc + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dq_acc

    n_k_blocks = -(-seq_len_k // block_k)
    if causal:
        last_visible = (qi + 1) * block_q + causal_offset
        nk = (last_visible + (block_k - 1)) // block_k
        num_k = jnp.minimum(jnp.maximum(nk, 0), n_k_blocks)
    else:
        num_k = n_k_blocks
    dq = jax.lax.fori_loop(0, num_k, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k_full, v_full, out, lse, g, causal, s):
    """Pallas backward: dkv kernel (grid over kv blocks) + dq kernel (grid
    over q blocks). All operands bf16 on the MXU, fp32 accumulators."""
    b, sq, h, d = q.shape
    sk = k_full.shape[1]
    bq, bkv = _resolve_blocks(sq, sk, d, q.dtype)
    # both block dims round up to 128 multiples: q blocks because the
    # lse/delta side inputs ride 128-lane tiles, kv blocks because the
    # dkv grid is sk_p/block_k programs and a non-divisor block would
    # leave trailing kv rows with no program (uninitialized dk/dv)
    block_q = min(_round_up(bq, 128), _round_up(sq, 128))
    block_k = min(_round_up(bkv, 128), _round_up(sk, 128))
    sq_p = _round_up(sq, block_q)
    sk_p = _round_up(sk, block_k)
    bh = b * h

    def to_bh(x, s_len, s_pad):
        x = x.transpose(0, 2, 1, 3).reshape(bh, s_len, x.shape[-1])
        if s_pad != s_len:
            x = jnp.pad(x, ((0, 0), (0, s_pad - s_len), (0, 0)))
        return x

    qh = to_bh(q, sq, sq_p)
    kh = to_bh(k_full, sk, sk_p)
    vh = to_bh(v_full, sk, sk_p)
    gh = to_bh(g.astype(q.dtype), sq, sq_p)
    oh = to_bh(out, sq, sq_p)
    # delta = rowsum(g * out) in fp32; lse arrives as [bh, sq]
    delta = jnp.sum(gh.astype(jnp.float32) * oh.astype(jnp.float32), -1)
    lse_p = lse if lse.shape[1] == sq_p else jnp.pad(
        lse, ((0, 0), (0, sq_p - sq)))
    # lane-broadcast the per-row stats to 128-lane tiles (fwd lse
    # convention; Mosaic can't reshape across lanes)
    lse_p = jnp.broadcast_to(lse_p[..., None], (bh, sq_p, 128))
    delta = jnp.broadcast_to(delta[..., None], (bh, sq_p, 128))

    kw = dict(scale=s, causal=causal, block_q=block_q, block_k=block_k,
              seq_len_q=sq, seq_len_k=sk)
    with _no_x64():
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, **kw),
            grid=(bh, sk_p // block_k),
            in_specs=[
                pl.BlockSpec((None, sq_p, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, sq_p, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, sq_p, 128), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, sq_p, 128), lambda i, j: (i, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, sk_p, d), k_full.dtype),
                jax.ShapeDtypeStruct((bh, sk_p, d), v_full.dtype),
            ],
            interpret=_interpret(),
        )(qh, kh, vh, gh, lse_p, delta)
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, **kw),
            grid=(bh, sq_p // block_q),
            in_specs=[
                pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, sk_p, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, sk_p, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, block_q, 128),
                             lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, block_q, 128),
                             lambda i, j: (i, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, sq_p, d), q.dtype),
            ],
            interpret=_interpret(),
        )(qh, kh, vh, gh, lse_p, delta)[0]
    dq4 = dq[:, :sq].reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    dk4 = dk[:, :sk].reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    dv4 = dv[:, :sk].reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    return dq4, dk4, dv4


def _bwd_rule(causal, scale, res, g):
    q, k, v, out, lse = res
    from ...framework import flags
    if flags.flag("FLAGS_flash_attn_pallas_bwd"):
        b, sq, h, d = q.shape
        hk = k.shape[2]
        rep = h // hk
        k_full = jnp.repeat(k, rep, axis=2) if rep != 1 else k
        v_full = jnp.repeat(v, rep, axis=2) if rep != 1 else v
        s = scale if scale is not None else 1.0 / math.sqrt(d)
        dq4, dk4, dv4 = _flash_bwd_pallas(q, k_full, v_full, out,
                                          lse.reshape(b * h, sq), g,
                                          causal, s)
        if rep != 1:
            sk = k.shape[1]
            dk4 = dk4.reshape(b, sk, hk, rep, d).sum(3)
            dv4 = dv4.reshape(b, sk, hk, rep, d).sum(3)
        return (dq4.astype(q.dtype), dk4.astype(k.dtype),
                dv4.astype(v.dtype))
    return _bwd_rule_scan(causal, scale, res, g)


def _bwd_rule_scan(causal, scale, res, g):
    """Blockwise recompute backward (fp32 accumulation, O(S·D) memory)."""
    q, k, v, out, lse = res
    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    rep = h // hk
    if rep != 1:
        k_full = jnp.repeat(k, rep, axis=2)
        v_full = jnp.repeat(v, rep, axis=2)
    else:
        k_full, v_full = k, v
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # [B,H,S,D] fp32
    qh = q.transpose(0, 2, 1, 3).astype(jnp.float32)
    kh = k_full.transpose(0, 2, 1, 3).astype(jnp.float32)
    vh = v_full.transpose(0, 2, 1, 3).astype(jnp.float32)
    gh = g.transpose(0, 2, 1, 3).astype(jnp.float32)
    oh = out.transpose(0, 2, 1, 3).astype(jnp.float32)
    lse_h = lse.reshape(b, h, sq)
    delta = jnp.sum(gh * oh, axis=-1)  # [B,H,Sq]

    # pad the key axis to the block multiple and mask padded keys —
    # never shrink the block (an odd sk would otherwise degrade to
    # block=1, i.e. a sequential per-position scan)
    block = 512
    sk_p = _round_up(sk, block)
    if sk_p != sk:
        kh = jnp.pad(kh, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        vh = jnp.pad(vh, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    n_blocks = sk_p // block

    def kv_block(carry, i):
        dq_acc = carry
        ks = jax.lax.dynamic_slice_in_dim(kh, i * block, block, 2)
        vs = jax.lax.dynamic_slice_in_dim(vh, i * block, block, 2)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qh, ks) * s
        k_pos = i * block + jax.lax.broadcasted_iota(
            jnp.int32, (sq, block), 1)
        valid = k_pos < sk  # padded keys contribute nothing
        if causal:
            q_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, block), 0)
            # bottom-right aligned, matching the forward kernel
            valid = valid & (q_pos + (sk - sq) >= k_pos)
        logits = jnp.where(valid[None, None], logits, _NEG_INF)
        # explicit mask: fully-masked rows have lse ~= -1e30 and would
        # otherwise yield p = exp(0) = 1 on masked entries
        p = jnp.where(valid[None, None],
                      jnp.exp(logits - lse_h[..., None]), 0.0)
        dv_i = jnp.einsum("bhqk,bhqd->bhkd", p, gh)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gh, vs)
        ds = p * (dp - delta[..., None]) * s
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, ks)
        dk_i = jnp.einsum("bhqk,bhqd->bhkd", ds, qh)
        return dq_acc, (dk_i, dv_i)

    dq0 = jnp.zeros_like(qh)
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        kv_block, dq0, jnp.arange(n_blocks))
    # [n_blocks, B, H, block, D] -> [B, H, Sk_p, D] -> slice true Sk
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(b, h, sk_p, d)[:, :, :sk]
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(b, h, sk_p, d)[:, :, :sk]
    if rep != 1:  # sum over repeated query-head groups
        dk = dk.reshape(b, hk, rep, sk, d).sum(2)
        dv = dv.reshape(b, hk, rep, sk, d).sum(2)
    dq4 = dq.transpose(0, 2, 1, 3).astype(q.dtype)
    dk4 = dk.transpose(0, 2, 1, 3).astype(k.dtype)
    dv4 = dv.transpose(0, 2, 1, 3).astype(v.dtype)
    return dq4, dk4, dv4


flash_attention.defvjp(_fwd_rule, _bwd_rule)


# -- tunable surface ---------------------------------------------------------
# block_q/block_kv candidate grid, registered next to the knob. No
# cost_fn: flash byte traffic is block-invariant to first order (K/V
# blocks revisit across q programs — the BlockSpec index map is
# qi-independent), so the roofline cannot prove any candidate worse;
# every valid candidate gets timed. Shape key: (sq, sk, d).

def _register_flash_surface():
    from ...tuner.surface import TunableSurface, register_surface

    def _candidates(shape):
        return [{"block_q": bq, "block_kv": bkv}
                for bq in (128, 256, 512)
                for bkv in (128, 256, 512, 1024)]

    def _is_valid(config, shape):
        # fwd needs q blocks sublane-aligned, kv blocks lane-aligned;
        # the bwd kernels round both up to 128 so keep the grid there
        return (config["block_q"] % 128 == 0
                and config["block_kv"] % 128 == 0
                and config["block_q"] <= max(shape.get("sq", 1 << 30), 128)
                and config["block_kv"] <= max(shape.get("sk", 1 << 30),
                                              128))

    register_surface(TunableSurface(
        name="flash_attention",
        params=("block_q", "block_kv"),
        default={"block_q": 256, "block_kv": 512},
        candidates=_candidates,
        is_valid=_is_valid,
        describe="Flash-attention Pallas q/kv block sizes (fwd online-"
                 "softmax grid + hand-written bwd). Shape key: sq/sk/"
                 "head_dim. FLAGS_flash_attn_block_q/kv set explicitly "
                 "override any cached value."))


_register_flash_surface()


def flash_attention_cost(q_shape, kv_seq=None, causal=False, train=False):
    """Static FLOPs/bytes for one :func:`flash_attention` call (profiler
    cost-accounting surface): q [B, Sq, H, D]. Flash never materializes
    the [Sq, Sk] score matrix, so bytes count only q/k/v in + out —
    exactly why the kernel moves attention to the compute-bound side of
    the roofline. ``train=True`` multiplies by 3.5 (bwd recomputes the
    logits once on top of the 2x grad matmuls)."""
    from ...profiler.cost import attention_cost
    b, sq, h, d = (int(s) for s in q_shape)
    c = attention_cost(b, sq, h, d, kv_len=kv_seq, causal=causal)
    return c * 3.5 if train else c
