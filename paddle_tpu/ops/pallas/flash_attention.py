"""Flash attention Pallas kernel for TPU.

Role of the reference's flash-attn CUDA integration
(phi fused attention kernels, UNVERIFIED). Layout: [B, S, H, D] in/out
(paddle convention); internally blocks over (batch*heads, q_blocks) with an
online-softmax accumulation loop over kv blocks — the classic TPU flash
forward. Backward is a blockwise lax.scan recompute using the saved
log-sum-exp: memory stays O(S·D) (no S×S materialization) while XLA fuses
the per-block matmuls onto the MXU; a fully hand-scheduled Pallas backward
is a later optimization (PAPERS.md Liger-style).

GQA/MQA (fewer kv heads than q heads) is handled by repeating kv heads."""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "flash_attention_reference"]

_NEG_INF = -1e30


def flash_attention_reference(q, k, v, causal=False, scale=None):
    """[B, S, H, D] reference (fp32 softmax)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_len_q, seq_len_k):
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale  # [block_q, d]
    # bottom-right-aligned causal offset (standard flash/decode semantics):
    # query i may see keys k_pos <= i + (seq_len_k - seq_len_q)
    causal_offset = seq_len_k - seq_len_q

    def body(start_k, carry):
        acc, m_prev, l_prev = carry
        k = pl.load(k_ref, (pl.dslice(start_k * block_k, block_k),
                            slice(None))).astype(jnp.float32)
        v = pl.load(v_ref, (pl.dslice(start_k * block_k, block_k),
                            slice(None))).astype(jnp.float32)
        s = q @ k.T  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = start_k * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos + causal_offset >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + p @ v
        return acc, m_new, l_new

    d = q_ref.shape[-1]
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    if causal:
        # only kv blocks up to this q block's last visible key participate
        last_visible = (qi + 1) * block_q + causal_offset
        num_k = jnp.clip(
            jax.lax.div(last_visible + block_k - 1, block_k),
            0, seq_len_k // block_k)
    else:
        num_k = seq_len_k // block_k
    acc, m, l = jax.lax.fori_loop(0, num_k, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(l)


def _pick_block(seq_len, preferred):
    b = min(preferred, seq_len)
    while seq_len % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, scale=None):
    out, _ = _flash_fwd(q, k, v, causal, scale)
    return out


def _flash_fwd(q, k, v, causal, scale):
    from ...framework import flags
    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    if hk != h:  # GQA: repeat kv heads
        rep = h // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = _pick_block(sq, int(flags.flag("FLAGS_flash_attn_block_q")))
    block_k = _pick_block(sk, int(flags.flag("FLAGS_flash_attn_block_kv")))
    # [B, S, H, D] -> [B*H, S, D]
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    grid = (b * h, sq // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=s, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len_q=sq,
                          seq_len_k=sk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, sk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, block_q), lambda bh, qi: (bh, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq), jnp.float32),
        ],
    )(qh, kh, vh)
    out4 = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return out4, lse


def _fwd_rule(q, k, v, causal, scale):
    out, lse = _flash_fwd(q, k, v, causal, scale)
    return out, (q, k, v, out, lse)


def _bwd_rule(causal, scale, res, g):
    """Blockwise recompute backward (fp32 accumulation, O(S·D) memory)."""
    q, k, v, out, lse = res
    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    rep = h // hk
    if rep != 1:
        k_full = jnp.repeat(k, rep, axis=2)
        v_full = jnp.repeat(v, rep, axis=2)
    else:
        k_full, v_full = k, v
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # [B,H,S,D] fp32
    qh = q.transpose(0, 2, 1, 3).astype(jnp.float32)
    kh = k_full.transpose(0, 2, 1, 3).astype(jnp.float32)
    vh = v_full.transpose(0, 2, 1, 3).astype(jnp.float32)
    gh = g.transpose(0, 2, 1, 3).astype(jnp.float32)
    oh = out.transpose(0, 2, 1, 3).astype(jnp.float32)
    lse_h = lse.reshape(b, h, sq)
    delta = jnp.sum(gh * oh, axis=-1)  # [B,H,Sq]

    block = 512
    while sk % block and block > 1:
        block //= 2
    n_blocks = sk // block

    def kv_block(carry, i):
        dq_acc = carry
        ks = jax.lax.dynamic_slice_in_dim(kh, i * block, block, 2)
        vs = jax.lax.dynamic_slice_in_dim(vh, i * block, block, 2)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qh, ks) * s
        if causal:
            q_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, block), 0)
            k_pos = i * block + jax.lax.broadcasted_iota(
                jnp.int32, (sq, block), 1)
            # bottom-right aligned, matching the forward kernel
            logits = jnp.where(
                (q_pos + (sk - sq))[None, None] >= k_pos[None, None],
                logits, _NEG_INF)
        p = jnp.exp(logits - lse_h[..., None])  # [B,H,Sq,block]
        dv_i = jnp.einsum("bhqk,bhqd->bhkd", p, gh)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gh, vs)
        ds = p * (dp - delta[..., None]) * s
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, ks)
        dk_i = jnp.einsum("bhqk,bhqd->bhkd", ds, qh)
        return dq_acc, (dk_i, dv_i)

    dq0 = jnp.zeros_like(qh)
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        kv_block, dq0, jnp.arange(n_blocks))
    # [n_blocks, B, H, block, D] -> [B, H, Sk, D]
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(b, h, sk, d)
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(b, h, sk, d)
    if rep != 1:  # sum over repeated query-head groups
        dk = dk.reshape(b, hk, rep, sk, d).sum(2)
        dv = dv.reshape(b, hk, rep, sk, d).sum(2)
    dq4 = dq.transpose(0, 2, 1, 3).astype(q.dtype)
    dk4 = dk.transpose(0, 2, 1, 3).astype(k.dtype)
    dv4 = dv.transpose(0, 2, 1, 3).astype(v.dtype)
    return dq4, dk4, dv4


flash_attention.defvjp(_fwd_rule, _bwd_rule)
