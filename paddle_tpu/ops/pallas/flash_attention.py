"""Flash attention Pallas kernel for TPU.

Role of the reference's flash-attn CUDA integration
(phi fused attention kernels, UNVERIFIED). Layout: [B, S, H, D] in/out
(paddle convention); internally blocks over (batch*heads, q_blocks) with an
online-softmax accumulation loop over kv blocks — the classic TPU flash
forward. Backward is a blockwise lax.scan recompute using the saved
log-sum-exp: memory stays O(S·D) (no S×S materialization) while XLA fuses
the per-block matmuls onto the MXU; a fully hand-scheduled Pallas backward
is a later optimization (PAPERS.md Liger-style).

GQA/MQA (fewer kv heads than q heads) is handled by repeating kv heads."""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._utils import interpret_mode as _interpret, no_x64 as _no_x64



__all__ = ["flash_attention", "flash_attention_reference"]

_NEG_INF = -1e30


def flash_attention_reference(q, k, v, causal=False, scale=None):
    """[B, S, H, D] reference (fp32 softmax)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_len_q, seq_len_k):
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale  # [block_q, d]
    # bottom-right-aligned causal offset (standard flash/decode semantics):
    # query i may see keys k_pos <= i + (seq_len_k - seq_len_q)
    causal_offset = seq_len_k - seq_len_q

    def body(start_k, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.dslice(start_k * block_k, block_k),
                  slice(None)].astype(jnp.float32)
        v = v_ref[pl.dslice(start_k * block_k, block_k),
                  slice(None)].astype(jnp.float32)
        s = q @ k.T  # [block_q, block_k]
        k_pos = start_k * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < seq_len_k  # mask padded keys
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & (q_pos + causal_offset >= k_pos)
        s = jnp.where(valid, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + p @ v
        return acc, m_new, l_new

    d = q_ref.shape[-1]
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    n_k_blocks = -(-seq_len_k // block_k)  # padded kv block count
    if causal:
        # only kv blocks up to this q block's last visible key
        # participate (weak python ints keep int32 here; the pallas_call
        # is traced under _no_x64)
        last_visible = (qi + 1) * block_q + causal_offset
        nk = (last_visible + (block_k - 1)) // block_k
        num_k = jnp.minimum(jnp.maximum(nk, 0), n_k_blocks)
    else:
        num_k = n_k_blocks
    acc, m, l = jax.lax.fori_loop(0, num_k, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l[:, None]).astype(o_ref.dtype)
    # stats ride a 128-lane last dim (TPU tiling requires the last block
    # dim be 128-divisible; same convention as jax's official kernel)
    lse_ref[:] = jnp.broadcast_to((m + jnp.log(l))[:, None],
                                  (block_q, 128))


def _round_up(n, m):
    return -(-n // m) * m


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, scale=None):
    out, _ = _flash_fwd(q, k, v, causal, scale)
    return out


def _flash_fwd(q, k, v, causal, scale):
    from ...framework import flags
    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    if hk != h:  # GQA: repeat kv heads
        rep = h // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = min(int(flags.flag("FLAGS_flash_attn_block_q")),
                  _round_up(sq, 8))
    block_k = min(int(flags.flag("FLAGS_flash_attn_block_kv")),
                  _round_up(sk, 128))
    # [B, S, H, D] -> [B*H, S, D], padded to block multiples (the kernel
    # masks padded key positions; padded query rows are sliced off)
    sq_p = _round_up(sq, block_q)
    sk_p = _round_up(sk, block_k)
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    if sq_p != sq:
        qh = jnp.pad(qh, ((0, 0), (0, sq_p - sq), (0, 0)))
    if sk_p != sk:
        kh = jnp.pad(kh, ((0, 0), (0, sk_p - sk), (0, 0)))
        vh = jnp.pad(vh, ((0, 0), (0, sk_p - sk), (0, 0)))
    grid = (b * h, sq_p // block_q)
    with _no_x64():
        out, lse = pl.pallas_call(
            functools.partial(_fwd_kernel, scale=s, causal=causal,
                              block_q=block_q, block_k=block_k,
                              seq_len_q=sq, seq_len_k=sk),
            grid=grid,
            in_specs=[
                pl.BlockSpec((None, block_q, d),
                             lambda bh, qi: (bh, qi, 0)),
                pl.BlockSpec((None, sk_p, d), lambda bh, qi: (bh, 0, 0)),
                pl.BlockSpec((None, sk_p, d), lambda bh, qi: (bh, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block_q, d),
                             lambda bh, qi: (bh, qi, 0)),
                pl.BlockSpec((None, block_q, 128),
                             lambda bh, qi: (bh, qi, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
                jax.ShapeDtypeStruct((b * h, sq_p, 128), jnp.float32),
            ],
            interpret=_interpret(),
        )(qh, kh, vh)
    out4 = out[:, :sq].reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return out4, lse[:, :sq, 0]


def _fwd_rule(q, k, v, causal, scale):
    out, lse = _flash_fwd(q, k, v, causal, scale)
    return out, (q, k, v, out, lse)


def _bwd_rule(causal, scale, res, g):
    """Blockwise recompute backward (fp32 accumulation, O(S·D) memory)."""
    q, k, v, out, lse = res
    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    rep = h // hk
    if rep != 1:
        k_full = jnp.repeat(k, rep, axis=2)
        v_full = jnp.repeat(v, rep, axis=2)
    else:
        k_full, v_full = k, v
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # [B,H,S,D] fp32
    qh = q.transpose(0, 2, 1, 3).astype(jnp.float32)
    kh = k_full.transpose(0, 2, 1, 3).astype(jnp.float32)
    vh = v_full.transpose(0, 2, 1, 3).astype(jnp.float32)
    gh = g.transpose(0, 2, 1, 3).astype(jnp.float32)
    oh = out.transpose(0, 2, 1, 3).astype(jnp.float32)
    lse_h = lse.reshape(b, h, sq)
    delta = jnp.sum(gh * oh, axis=-1)  # [B,H,Sq]

    # pad the key axis to the block multiple and mask padded keys —
    # never shrink the block (an odd sk would otherwise degrade to
    # block=1, i.e. a sequential per-position scan)
    block = 512
    sk_p = _round_up(sk, block)
    if sk_p != sk:
        kh = jnp.pad(kh, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        vh = jnp.pad(vh, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    n_blocks = sk_p // block

    def kv_block(carry, i):
        dq_acc = carry
        ks = jax.lax.dynamic_slice_in_dim(kh, i * block, block, 2)
        vs = jax.lax.dynamic_slice_in_dim(vh, i * block, block, 2)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qh, ks) * s
        k_pos = i * block + jax.lax.broadcasted_iota(
            jnp.int32, (sq, block), 1)
        valid = k_pos < sk  # padded keys contribute nothing
        if causal:
            q_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, block), 0)
            # bottom-right aligned, matching the forward kernel
            valid = valid & (q_pos + (sk - sq) >= k_pos)
        logits = jnp.where(valid[None, None], logits, _NEG_INF)
        p = jnp.exp(logits - lse_h[..., None])  # [B,H,Sq,block]
        dv_i = jnp.einsum("bhqk,bhqd->bhkd", p, gh)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gh, vs)
        ds = p * (dp - delta[..., None]) * s
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, ks)
        dk_i = jnp.einsum("bhqk,bhqd->bhkd", ds, qh)
        return dq_acc, (dk_i, dv_i)

    dq0 = jnp.zeros_like(qh)
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        kv_block, dq0, jnp.arange(n_blocks))
    # [n_blocks, B, H, block, D] -> [B, H, Sk_p, D] -> slice true Sk
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(b, h, sk_p, d)[:, :, :sk]
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(b, h, sk_p, d)[:, :, :sk]
    if rep != 1:  # sum over repeated query-head groups
        dk = dk.reshape(b, hk, rep, sk, d).sum(2)
        dv = dv.reshape(b, hk, rep, sk, d).sum(2)
    dq4 = dq.transpose(0, 2, 1, 3).astype(q.dtype)
    dk4 = dk.transpose(0, 2, 1, 3).astype(k.dtype)
    dv4 = dv.transpose(0, 2, 1, 3).astype(v.dtype)
    return dq4, dk4, dv4


flash_attention.defvjp(_fwd_rule, _bwd_rule)
