"""Pallas TPU kernels — the role of PHI's hand-written CUDA fusion kernels
(SURVEY.md §2.1: fused_attention/flash-attn, rms_norm, fused_rope →
"Pallas kernels for flash-attn/rope/rms-norm").

Each module exposes a jnp reference implementation (used on CPU and as the
numerics oracle in tests) and a Pallas kernel used on TPU when
FLAGS_enable_pallas_kernels is set."""

from . import (ce_chunk, flash_attention, ragged_paged_attention,
               rms_norm, rope, swiglu)

__all__ = ["ce_chunk", "flash_attention", "ragged_paged_attention",
           "rms_norm", "rope", "swiglu"]
