"""Op library — the role of Paddle's PHI op set + ``python/paddle/tensor/``
(~2000 APIs; SURVEY.md §2.2).  Each op is a pure jax function dispatched
through ``framework.core.apply`` so eager autograd, state tracking, and
to_static tracing all share one path.  XLA plays the role of PHI's per-backend
kernels (SURVEY.md §2.1: "XLA:CPU via jax (free)").
"""

from . import creation, math, manipulation, linalg, logic, search, stat, random_ops  # noqa
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .random_ops import *  # noqa: F401,F403
from .einsum_op import einsum  # noqa: F401

from .tensor_methods import install_tensor_methods
install_tensor_methods()
