"""Mixture-of-Experts core — pure jax, shape-static, TPU-first.

Reference parity: ``paddle/incubate/distributed/models/moe`` (MoELayer,
top-k gate, all-to-all dispatch/combine, aux load-balance loss) and the
phi ``moe_*`` GPU dispatch kernels (SURVEY.md §2.1 EP row, §2.3 EP).
Reference mount was empty; no file:line citations available.

TPU-native design — NOT a port of the token-index scatter kernels:

- Gating/dispatch is the GShard/Switch *capacity* formulation: one-hot
  dispatch masks built with cumsum position counters, so every shape is
  static under jit (no ragged scatter; dropped tokens are handled by the
  capacity factor exactly as in the reference's capacity mode).
- Expert compute is a *grouped matmul* over a stacked expert weight bank
  ([E, d, h] einsum) — big, batched MXU work instead of per-expert loops.
- Expert parallelism is an ``lax.all_to_all`` pair over the 'expert' mesh
  axis inside shard_map: tokens travel to their expert's device and back,
  exactly the reference's NCCL all-to-all but compiled into the program
  so XLA overlaps it with the gate/combine math.
- The auxiliary load-balance loss (mean fraction × mean prob, ×E) and the
  router z-loss follow the standard formulations.
"""

from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["top_k_gating", "top_k_gating_idx", "moe_dispatch_combine",
           "moe_ffn_grouped", "moe_forward", "moe_forward_ep",
           "sort_rows_by_expert", "moe_forward_dropless", "moe_ablation"]


# -- section ablation (profiler.breakdown step-attribution harness) --------
#
# The breakdown harness compiles one program variant per knocked-out
# section; the knockout is a TRACE-TIME decision read from this
# thread-local, so a variant's compiled program simply lacks the
# section. Replacement subgraphs keep every shape/dtype and carry a
# data dependence on the inputs (``_dep0``) so XLA cannot constant-fold
# them away — numerics are garbage under ablation BY DESIGN; only
# timing is meaningful.

_ablation_tl = threading.local()


def _ablated() -> frozenset:
    return getattr(_ablation_tl, "sections", frozenset())


@contextlib.contextmanager
def moe_ablation(sections):
    """Knock out named MoE sections ('gating' | 'sort' | 'a2a' |
    'expert_matmul') for programs TRACED inside this context. Timing
    harness use only (profiler.breakdown); outputs are not meaningful."""
    prev = _ablated()
    _ablation_tl.sections = frozenset(sections)
    try:
        yield
    finally:
        _ablation_tl.sections = prev


def _dep0(x):
    """int32 zero that DEPENDS on ``x``: added to the static replacement
    arrays so the ablated subgraph stays in the compiled program."""
    return (x.reshape(-1)[0] * 0).astype(jnp.int32)


def _ablation_gating(x, T, E, k, capacity):
    """Static round-robin routing standing in for the learned gate:
    same shapes/dtypes as :func:`top_k_gating_idx`'s outputs."""
    z0 = _dep0(x)
    gate_idx = (jnp.arange(T * k, dtype=jnp.int32).reshape(T, k) + z0) % E
    gate_vals = jnp.full((T, k), 1.0 / k, jnp.float32) \
        + z0.astype(jnp.float32)
    pos = (jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[:, None] % max(capacity, 1),
        (T, k)) + z0)
    keep = pos < capacity
    zero = z0.astype(jnp.float32) * 0.0
    return gate_idx, gate_vals, pos, keep, zero, zero


def top_k_gating(logits, k, capacity, norm_topk_prob=True):
    """Top-k softmax gating with capacity-bounded dispatch tensors.

    logits: [T, E] router outputs (fp32 recommended).
    Returns (dispatch [T, E, C] bool, combine [T, E, C] float,
    aux_loss scalar, z_loss scalar).
    """
    T, E = logits.shape
    logits = logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = lax.top_k(probs, k)          # [T, k]
    if norm_topk_prob:
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, -1, keepdims=True), 1e-9)

    # one-hot per assignment: [T, k, E]
    assign = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)
    # position of each assignment within its expert queue, counting down
    # the token dim then the k dim (priority: token order, then rank)
    flat = assign.reshape(T * k, E)                     # row-major (t, k)
    pos = jnp.cumsum(flat, axis=0) - flat               # positions 0-based
    pos = pos.reshape(T, k, E)
    within_cap = pos < capacity
    keep = assign * within_cap                          # [T, k, E]

    # aux load-balance loss (Switch): E * sum_e(frac_assign_e * mean_prob_e)
    me = jnp.mean(probs, axis=0)                        # [E]
    ce = jnp.sum(jax.nn.one_hot(gate_idx, E), axis=(0, 1)) / (T * k)
    aux_loss = E * jnp.sum(me * ce)
    z_loss = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))

    # dispatch/combine: [T, E, C]
    C = capacity
    pos_cap = jnp.clip(pos, 0, C - 1).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos_cap, C, dtype=jnp.float32)  # [T,k,E,C]
    disp_k = keep[..., None] * pos_onehot               # [T, k, E, C]
    dispatch = jnp.sum(disp_k, axis=1)                  # [T, E, C]
    combine = jnp.sum(disp_k * gate_vals[:, :, None, None], axis=1)
    return dispatch, combine, aux_loss, z_loss


def top_k_gating_idx(logits, k, capacity, norm_topk_prob=True):
    """Index-form top-k gating — identical routing/drop semantics to
    :func:`top_k_gating` (same row-major (t, k) queue priority) but
    returns per-assignment INDICES instead of one-hot [T, E, C]
    dispatch/combine tensors. At chip scale the one-hot form is the
    bottleneck: the tensors are O(T·E·C) memory and the dispatch
    einsums cost 2·cf·k·T²·d FLOPs — several times the expert matmuls
    themselves. The index form moves O(T·k·d) bytes with a
    scatter/gather pair instead (the TPU-idiomatic dispatch).

    Returns (gate_idx [T,k] int32, gate_vals [T,k] fp32,
    pos [T,k] int32 queue position, keep [T,k] bool, aux, z).
    """
    T, E = logits.shape
    logits = logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = lax.top_k(probs, k)          # [T, k]
    if norm_topk_prob:
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, -1, keepdims=True), 1e-9)

    assign = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)  # [T, k, E]
    flat = assign.reshape(T * k, E)
    pos_e = jnp.cumsum(flat, axis=0) - flat             # [T*k, E]
    pos = jnp.sum(pos_e.reshape(T, k, E) * assign, axis=-1)  # [T, k]
    pos = pos.astype(jnp.int32)
    keep = pos < capacity

    me = jnp.mean(probs, axis=0)
    ce = jnp.sum(assign, axis=(0, 1)) / (T * k)
    aux_loss = E * jnp.sum(me * ce)
    z_loss = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    return gate_idx.astype(jnp.int32), gate_vals, pos, keep, aux_loss, \
        z_loss


def _dispatch_gather(x, gate_idx, pos, keep, E, C):
    """Build the [E, C, d] expert input bank by scatter+gather.

    Each kept assignment (t, i) owns the unique slot e*C + pos; a
    scatter writes its token index there (sentinel T elsewhere), and a
    gather from zero-padded x fills the bank. Returns (xd [E,C,d],
    slot [T,k] int32 clamped to a trash slot for drops)."""
    T, k = gate_idx.shape
    d = x.shape[-1]
    slot = gate_idx * C + jnp.minimum(pos, C - 1)       # [T, k]
    slot = jnp.where(keep, slot, E * C)                 # trash slot
    token_of = jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[:, None], (T, k))
    token_idx = jnp.full((E * C + 1,), T, dtype=jnp.int32)
    token_idx = token_idx.at[slot.reshape(-1)].set(
        token_of.reshape(-1), mode="drop")
    x_pad = jnp.concatenate([x, jnp.zeros((1, d), x.dtype)], axis=0)
    xd = x_pad[token_idx[:E * C]].reshape(E, C, d)
    return xd, slot


def _combine_gather(out, slot, gate_vals, keep, x_dtype):
    """Inverse of :func:`_dispatch_gather`: gather each assignment's
    expert output by slot and weight by its gate value."""
    E_C, d = out.shape[0] * out.shape[1], out.shape[-1]
    out_pad = jnp.concatenate(
        [out.reshape(E_C, d),
         jnp.zeros((1, d), out.dtype)], axis=0)
    y_k = out_pad[slot]                                  # [T, k, d]
    w = (gate_vals * keep).astype(y_k.dtype)[..., None]
    return jnp.sum(y_k * w, axis=1).astype(x_dtype)


def moe_dispatch_combine(x, dispatch, combine, expert_fn):
    """Dense (single-device) capacity dispatch: x [T, d] -> [T, d].
    One-hot tensor form (kept for the public OpTest surface; the
    forward paths below use the index form)."""
    xd = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    out = expert_fn(xd)                                 # [E, C, d]
    return jnp.einsum("tec,ecd->td", combine.astype(out.dtype), out)


def moe_ffn_grouped(xd, w_gate, w_up, w_down, act=jax.nn.silu):
    """Grouped SwiGLU FFN over the expert dim: xd [E, C, d],
    w_gate/w_up [E, d, h], w_down [E, h, d]."""
    g = jnp.einsum("ecd,edh->ech", xd, w_gate)
    u = jnp.einsum("ecd,edh->ech", xd, w_up)
    h = act(g) * u
    return jnp.einsum("ech,ehd->ecd", h, w_down)


def moe_forward(x, router_w, expert_fn, k=2, capacity_factor=1.25,
                norm_topk_prob=True):
    """Single-device MoE block: x [T, d], router_w [d, E].
    Returns (out [T, d], aux_loss, z_loss)."""
    T = x.shape[0]
    E = router_w.shape[1]
    ab = _ablated()
    capacity = max(int(capacity_factor * k * T / E), 1)
    if "gating" in ab:
        gate_idx, gate_vals, pos, keep, aux, z = _ablation_gating(
            x, T, E, k, capacity)
    else:
        logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
        gate_idx, gate_vals, pos, keep, aux, z = top_k_gating_idx(
            logits, k, capacity, norm_topk_prob)
    if "sort" in ab:
        # skip the scatter/gather dispatch: a broadcast row bank + a
        # static (in-range) slot map, data-dependent so it survives XLA
        z0 = _dep0(x)
        xd = jnp.broadcast_to(x[0][None, None, :], (E, capacity,
                                                    x.shape[-1])) \
            + z0.astype(x.dtype)
        slot = (jnp.arange(T * k, dtype=jnp.int32).reshape(T, k)
                % (E * capacity)) + z0
    else:
        xd, slot = _dispatch_gather(x, gate_idx, pos, keep, E, capacity)
    out = xd if "expert_matmul" in ab else expert_fn(xd)   # [E, C, d]
    y = _combine_gather(out, slot, gate_vals, keep, x.dtype)
    return y, aux, z


def sort_rows_by_expert(gate_idx, n_experts, bm=128):
    """Expert-sorted, group-padded row layout for the Pallas grouped
    matmul (``ops.pallas.grouped_matmul`` — see its layout contract).

    gate_idx: [T, k] int32 expert assignments. Returns
    (perm [R] int32, tile_gid [nr] int32, P) where R = T*k,
    P = (ceil(R/bm) + n_experts) * bm (static), nr = P // bm, and
    ``perm[r]`` is the padded-layout position of unsorted assignment
    row r (rows of expert e occupy a contiguous, bm-aligned span;
    every expert owns >= 1 tile so empty groups still flush their dw).

    All index arithmetic is 1-D int32 (two small scatters); the [*, d]
    data movement stays gathers — TPU-friendly."""
    T, k = gate_idx.shape
    R = T * k
    E = n_experts
    e_flat = gate_idx.reshape(-1).astype(jnp.int32)
    order = jnp.argsort(e_flat, stable=True)        # sorted row -> row
    e_sorted = e_flat[order]
    counts = jnp.zeros((E,), jnp.int32).at[e_flat].add(1)
    padded = jnp.maximum(-(-counts // bm) * bm, bm)  # >= 1 tile each
    offs = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    offs_p = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(padded)[:-1]])
    # padded position of sorted row j: group start + rank within group
    pos_p = offs_p[e_sorted] + (
        jnp.arange(R, dtype=jnp.int32) - offs[e_sorted])
    # perm[r] = padded position of unsorted row r (invert the sort by
    # scattering: perm[order[j]] = pos_p[j])
    perm = jnp.zeros((R,), jnp.int32).at[order].set(pos_p)
    # static capacity: sum(padded) <= R + E*bm, rounded up to a whole
    # number of tiles (R itself need not be bm-aligned)
    P = (-(-R // bm) + E) * bm
    nr = P // bm
    ends = jnp.cumsum(padded)
    tile_gid = jnp.searchsorted(
        ends, jnp.arange(nr, dtype=jnp.int32) * bm, side="right")
    tile_gid = jnp.minimum(tile_gid, E - 1).astype(jnp.int32)
    return perm, tile_gid, P


def moe_forward_dropless(x, router_w, w_gate, w_up, w_down, k=2,
                         norm_topk_prob=True, bm=128, act=jax.nn.silu):
    """Dropless MoE block over the Pallas grouped matmul: x [T, d].

    No capacity, no token drops (the MegaBlocks formulation,
    SURVEY.md §2.3 EP row): assignment rows are expert-sorted into the
    group-padded layout and the three SwiGLU matmuls run as grouped
    MXU matmuls whose weight blocks change only at group boundaries.
    Executed FLOPs exceed activated by <= E*bm/(T*k) padding (~6-12% at
    bench shapes) vs capacity_factor× for the capacity path.
    Returns (out [T, d], aux_loss, z_loss) like :func:`moe_forward`."""
    from .pallas.grouped_matmul import grouped_matmul

    T, d = x.shape
    E = router_w.shape[1]
    ab = _ablated()
    if "gating" in ab:
        gate_idx, gate_vals, _pos, _keep, aux, z = _ablation_gating(
            x, T, E, k, T * k)
    else:
        logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
        # capacity = T*k keeps every assignment (pos < T*k always): the
        # SAME router math as the capacity paths by construction — the
        # dropless-vs-capacity equivalence tests rest on this sharing
        gate_idx, gate_vals, _pos, _keep, aux, z = top_k_gating_idx(
            logits, k, capacity=T * k, norm_topk_prob=norm_topk_prob)

    if "sort" in ab:
        # static identity-ish layout standing in for the argsort/cumsum
        # index machinery (gathers stay — 'sort' measures index build)
        z0 = _dep0(gate_idx)
        R = T * k
        P = (-(-R // bm) + E) * bm
        nr = P // bm
        perm = jnp.arange(R, dtype=jnp.int32) + z0
        tile_gid = (jnp.arange(nr, dtype=jnp.int32) % E) + z0
    else:
        perm, tile_gid, P = sort_rows_by_expert(gate_idx, E, bm=bm)
    # inverse map padded position -> source token (sentinel T = zero row)
    src = jnp.full((P,), T, jnp.int32).at[perm].set(
        jnp.arange(T * k, dtype=jnp.int32) // k)
    x_pad = jnp.concatenate([x, jnp.zeros((1, d), x.dtype)], axis=0)
    x_p = x_pad[src]                                    # [P, d] gather
    # two grouped matmuls, not a fused [E, d, 2h] concat: the concat
    # would materialize a full copy of every expert bank per forward
    # (+ its remat re-forwards + the VJP residual) on a config that is
    # already HBM-bound. A pre-fused gate|up PARAMETER would avoid the
    # copy but breaks the w_gate/w_up state_dict layout; revisit only
    # if an on-chip A/B shows the wider-N kernel paying for it.
    if "expert_matmul" in ab:
        # rank-1 stand-ins: keep [P, h]/[P, d] shapes and a grad path to
        # x and the banks without the MXU work
        g = x_p[:, :1] * w_gate[0, 0][None, :].astype(x.dtype)
        u = x_p[:, :1] * w_up[0, 0][None, :].astype(x.dtype)
        y_p = (act(g) * u)[:, :1] * w_down[0, 0][None, :].astype(x.dtype)
    else:
        g = grouped_matmul(x_p, w_gate, tile_gid)
        u = grouped_matmul(x_p, w_up, tile_gid)
        y_p = grouped_matmul((act(g) * u).astype(x.dtype), w_down,
                             tile_gid)
    y_k = y_p[perm].reshape(T, k, d)                    # gather back
    w = gate_vals.astype(y_k.dtype)[..., None]
    return jnp.sum(y_k * w, axis=1).astype(x.dtype), aux, z


def moe_forward_ep(x, router_w, expert_fn_local, axis_name, k=2,
                   capacity_factor=1.25, norm_topk_prob=True):
    """Expert-parallel MoE inside shard_map over ``axis_name``.

    x: [T_local, d] this device's tokens. router_w [d, E] replicated.
    expert_fn_local([E_local, C_total, d]) -> same shape — computes this
    device's experts on all devices' tokens (weights already local).
    Two all-to-alls move token slots expert-ward and back (the NCCL
    alltoall pair of the reference, compiled over ICI).
    """
    ep = lax.psum(1, axis_name)
    T = x.shape[0]
    E = router_w.shape[1]
    if E % ep:
        raise ValueError(f"num_experts {E} not divisible by ep degree {ep}")
    ab = _ablated()
    capacity = max(int(capacity_factor * k * T / E), 1)
    if "gating" in ab:
        gate_idx, gate_vals, pos, keep, aux, z = _ablation_gating(
            x, T, E, k, capacity)
    else:
        logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
        gate_idx, gate_vals, pos, keep, aux, z = top_k_gating_idx(
            logits, k, capacity, norm_topk_prob)
    xd, slot = _dispatch_gather(x, gate_idx, pos, keep, E, capacity)
    if "a2a" in ab:
        # local reshape standing in for the token movement: identical
        # [E/ep, ep*C, d] shape, zero ICI traffic
        xd = xd.reshape(E // ep, ep * capacity, x.shape[-1])
    else:
        # send each expert-slice to its owner; receive every device's
        # slots for the local experts: [E, C, d] -> [E/ep, ep*C, d]
        xd = lax.all_to_all(xd, axis_name, split_axis=0, concat_axis=1,
                            tiled=True)
    out = xd if "expert_matmul" in ab else expert_fn_local(xd)
    if "a2a" in ab:
        out = out.reshape(E, capacity, x.shape[-1])
    else:
        out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=0,
                             tiled=True)                # [E, C, d]
    y = _combine_gather(out, slot, gate_vals, keep, x.dtype)
    # aux losses are per-device estimates; average over the ep group
    aux = lax.pmean(aux, axis_name)
    z = lax.pmean(z, axis_name)
    return y, aux, z
