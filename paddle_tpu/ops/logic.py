"""Comparison / logical / bitwise ops (paddle/tensor/logic.py parity,
UNVERIFIED). Comparisons are non-differentiable; they bypass the tape."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor
from .common import as_tensor

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "logical_and", "logical_or", "logical_xor",
    "logical_not", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_left_shift", "bitwise_right_shift", "is_empty", "is_tensor",
]


def _cmp(jfn, name):
    def op(x, y, name=None):
        xd = x._data if isinstance(x, Tensor) else x
        yd = y._data if isinstance(y, Tensor) else y
        from ..framework.segment import current_recorder, SegValue
        rec = current_recorder()
        if isinstance(xd, SegValue) or isinstance(yd, SegValue):
            if rec is not None:
                # compile-around-break: record instead of calling jnp on
                # a placeholder (jax rejects __jax_array__ coercion)
                return Tensor(rec.record(jfn, [xd, yd], 1, name)[0])
            # escaped placeholder outside segment mode (e.g. a param
            # mutated by a segmented step): materialize first
            if isinstance(xd, SegValue):
                xd = xd.force()
            if isinstance(yd, SegValue):
                yd = yd.force()
        return Tensor(jfn(xd, yd))
    op.__name__ = name
    return op


equal = _cmp(jnp.equal, "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")
bitwise_left_shift = _cmp(jnp.left_shift, "bitwise_left_shift")
bitwise_right_shift = _cmp(jnp.right_shift, "bitwise_right_shift")


def logical_not(x, out=None, name=None):
    return Tensor(jnp.logical_not(as_tensor(x)._data))


def bitwise_not(x, out=None, name=None):
    return Tensor(jnp.bitwise_not(as_tensor(x)._data))


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(as_tensor(x)._data, as_tensor(y)._data))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(as_tensor(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x, name=None):
    from .common import as_tensor as _at
    import jax.numpy as _jnp
    return bool(_jnp.issubdtype(_at(x).dtype, _jnp.complexfloating))


def is_floating_point(x, name=None):
    from .common import as_tensor as _at
    import jax.numpy as _jnp
    return bool(_jnp.issubdtype(_at(x).dtype, _jnp.floating))


def is_integer(x, name=None):
    from .common import as_tensor as _at
    import jax.numpy as _jnp
    return bool(_jnp.issubdtype(_at(x).dtype, _jnp.integer))


def less(x, y, name=None):
    return less_than(x, y, name=name)


__all__ += ["is_complex", "is_floating_point", "is_integer", "less"]
