"""Statistics ops (paddle/tensor/stat.py parity, UNVERIFIED)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply
from .common import as_tensor

__all__ = ["std", "var", "median", "nanmedian", "quantile", "nanquantile",
           "numel", "histogramdd"]


def _axes(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = as_tensor(x)
    return apply(lambda a: jnp.var(a, axis=_axes(axis),
                                   ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x, name="var")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = as_tensor(x)
    return apply(lambda a: jnp.std(a, axis=_axes(axis),
                                   ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x, name="std")


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    x = as_tensor(x)

    def fn(a):
        if mode == "avg":
            return jnp.median(a, axis=_axes(axis), keepdims=keepdim)
        # 'min' mode: lower of the two middles
        ax = _axes(axis)
        if ax is None:
            s = jnp.sort(a.reshape(-1))
            return s[(s.shape[0] - 1) // 2]
        s = jnp.sort(a, axis=ax)
        idx = (s.shape[ax] - 1) // 2
        out = jnp.take(s, idx, axis=ax)
        return jnp.expand_dims(out, ax) if keepdim else out
    return apply(fn, x, name="median")


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    x = as_tensor(x)
    return apply(lambda a: jnp.nanmedian(a, axis=_axes(axis),
                                         keepdims=keepdim), x,
                 name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    x = as_tensor(x)
    qv = q.jax() if isinstance(q, Tensor) else jnp.asarray(q)

    def fn(a):
        return jnp.quantile(a, qv, axis=_axes(axis), keepdims=keepdim,
                            method=interpolation)
    return apply(fn, x, name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    x = as_tensor(x)
    qv = q.jax() if isinstance(q, Tensor) else jnp.asarray(q)
    return apply(lambda a: jnp.nanquantile(a, qv, axis=_axes(axis),
                                           keepdims=keepdim,
                                           method=interpolation),
                 x, name="nanquantile")


def numel(x, name=None):
    return Tensor(jnp.asarray(as_tensor(x).size, dtype=jnp.int64))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    x = as_tensor(x)
    w = np.asarray(as_tensor(weights)._data) if weights is not None else None
    hist, edges = np.histogramdd(np.asarray(x._data), bins=bins,
                                 range=ranges, density=density, weights=w)
    return Tensor(jnp.asarray(hist)), [Tensor(jnp.asarray(e)) for e in edges]
