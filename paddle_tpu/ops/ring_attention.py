"""Ring attention + Ulysses (SEP) context parallelism — pure jax core.

Reference parity: PaddleNLP ``ring_flash_attention.py`` (RingFlashAttention:
NCCL P2P ring of K/V blocks with online-softmax rescale, causal
load-balanced variant) and the fleet 'sep' axis Ulysses all-to-all
head<->seq reshuffle (SURVEY.md §2.3 CP/ring + Ulysses rows; §5
long-context). Reference mount was empty; behavior reconstructed, no
file:line citations available.

TPU-native design (NOT a port of the NCCL send/recv pattern):

- The K/V ring is a ``lax.ppermute`` rotation over a named mesh axis inside
  ``shard_map`` — the classic TPU ring-attention layout where transfers ride
  ICI neighbor links and XLA's latency-hiding scheduler overlaps the
  collective-permute with the per-chunk attention compute.
- Per-chunk partial softmax statistics (row max ``m``, row sum ``l``,
  unnormalized accumulator) are merged online in fp32, so the full S×S
  score matrix never materializes and the result is exact attention.
- Causal masking is computed from *global token positions*, and the key
  positions travel the ring alongside K/V. That makes the kernel layout-
  agnostic: the load-balanced ("zigzag") placement — rank r holds chunks
  (r, 2n-1-r) of the sequence so every rank does equal causal work — needs
  no special-cased mask logic.
- The whole loop is a ``lax.scan``; jax reverse-mode differentiates it (the
  transpose of ``ppermute`` is the reversed permutation), so the backward
  pass is an automatically-derived reverse ring.

Everything here is shape-static and jit/shard_map-friendly.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "allgather_attention",
    "chunked_attention",
    "zigzag_reorder",
    "zigzag_restore",
    "zigzag_positions",
]

_NEG_INF = -1e30


def _repeat_kv(q, k, v):
    """GQA/MQA: repeat kv heads up to the query head count."""
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def _chunk_partials(qf, k_c, v_c, q_pos, k_pos, scale, causal,
                    k_valid=None):
    """Partial attention of local queries against one K/V chunk.

    qf: [B, Sq, H, D] fp32; k_c/v_c: [B, Sk, H, D] fp32;
    q_pos: [Sq] int32 global positions; k_pos: [Sk];
    k_valid: optional [Sk] bool — False marks padded key columns.
    Returns (m, l, acc): row max [B,H,Sq], row sumexp [B,H,Sq],
    unnormalized accumulator [B,H,Sq,D].
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, k_c,
                        preferred_element_type=jnp.float32) * scale
    mask = None
    if causal:
        mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
    if k_valid is not None:
        vm = k_valid[None, None, None, :]
        mask = vm if mask is None else (mask & vm)
    if mask is not None:
        logits = jnp.where(mask, logits, _NEG_INF)
    m = jnp.max(logits, axis=-1)
    p = jnp.exp(logits - m[..., None])
    if mask is not None:
        # fully-masked rows have m == _NEG_INF and p == 1 everywhere;
        # zero them so they contribute nothing to l/acc
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v_c,
                     preferred_element_type=jnp.float32)
    return m, l, acc


def _merge_partials(carry, partials):
    """Online-softmax merge of one chunk's partials into the running
    (acc, m, l) — the numerically delicate rescale, kept in ONE place
    for the ring / chunked / allgather variants."""
    acc, m, l = carry
    m_j, l_j, acc_j = partials
    m_new = jnp.maximum(m, m_j)
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(m_j - m_new)
    acc = acc * alpha[..., None] + acc_j * beta[..., None]
    l = l * alpha + l_j * beta
    return acc, m_new, l


def _zigzag_local_positions(idx, seq_local, degree):
    """Global positions of this rank's tokens under zigzag placement:
    rank r holds chunks r and 2n-1-r of 2n equal chunks."""
    c = seq_local // 2
    front = idx * c + jnp.arange(c, dtype=jnp.int32)
    back = (2 * degree - 1 - idx) * c + jnp.arange(c, dtype=jnp.int32)
    return jnp.concatenate([front, back])


def ring_attention(q, k, v, axis_name, causal=False, scale=None,
                   placement="contiguous"):
    """Exact attention over a sequence sharded along ``axis_name``.

    q/k/v: local chunks [B, S_local, H, D] ([B, S_local, H_kv, D] for k/v;
    GQA kv heads are repeated). Must be called inside ``shard_map`` (or any
    context where ``axis_name`` is a bound mesh axis).

    placement: 'contiguous' — rank r holds tokens [r*S, (r+1)*S);
    'zigzag' — rank r holds chunks (r, 2n-1-r) of 2n chunks (the causal
    load-balanced layout; use :func:`zigzag_reorder` on the host side).
    """
    orig_dtype = q.dtype
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    k, v = _repeat_kv(q, k, v)
    sk = k.shape[1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    if placement == "zigzag":
        if sq % 2 or sk % 2:
            raise ValueError("zigzag placement needs an even local length")
        q_pos = _zigzag_local_positions(idx, sq, n)
        k_pos0 = _zigzag_local_positions(idx, sk, n)
    elif placement == "contiguous":
        q_pos = idx * sq + jnp.arange(sq, dtype=jnp.int32)
        k_pos0 = idx * sk + jnp.arange(sk, dtype=jnp.int32)
    else:
        raise ValueError(f"unknown placement {placement!r}")

    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        acc, m, l, k_c, v_c, kp = carry
        acc, m, l = _merge_partials(
            (acc, m, l),
            _chunk_partials(qf, k_c, v_c, q_pos, kp, s, causal))
        # rotate the K/V chunk (and its positions) one step around the ring;
        # XLA's async collective-permute overlaps this with the merge math
        k_c = lax.ppermute(k_c, axis_name, perm)
        v_c = lax.ppermute(v_c, axis_name, perm)
        kp = lax.ppermute(kp, axis_name, perm)
        return (acc, m, l, k_c, v_c, kp), None

    def _vary(x):
        # Mark freshly-created carry state as device-varying so the scan
        # carry type matches its outputs. The outputs vary over the ring
        # axis AND over every axis the q/k/v inputs already vary on —
        # e.g. 'pipe' when this ring runs inside the compiled pipeline
        # engine's manual region (the 5D hybrid).
        from ..framework._vma import pvary_missing
        return pvary_missing(x, (axis_name,), like=qf)

    carry0 = (
        _vary(jnp.zeros((b, h, sq, d), jnp.float32)),
        _vary(jnp.full((b, h, sq), _NEG_INF, jnp.float32)),
        _vary(jnp.zeros((b, h, sq), jnp.float32)),
        k.astype(jnp.float32),
        v.astype(jnp.float32),
        k_pos0,
    )
    (acc, m, l, *_), _ = lax.scan(step, carry0, None, length=n)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(orig_dtype)


def allgather_attention(q, k, v, axis_name, causal=False, scale=None):
    """Context parallelism via K/V all-gather (the Llama-3-style CP):
    each rank attends its LOCAL query chunk against the FULL gathered
    K/V with global positions.

    vs ring: one ``lax.all_gather`` instead of a ppermute rotation scan —
    no rotation state, so it is safe inside the explicit pipeline tick
    engines' pipe-varying ``lax.switch`` branches where the ring's
    rotation collapses (see docs/ring_under_tick_engines.md). Degree is
    unbounded (Ulysses is capped at num_heads). COST: K/V memory is the
    GLOBAL sequence per device (the ring keeps S_local) and the gather
    is one S_global transfer instead of overlapped S_local hops.
    """
    orig_dtype = q.dtype
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    q_pos = idx * sq + jnp.arange(sq, dtype=jnp.int32)
    # gather and KEEP the compact kv heads (S_global x kv_heads is the
    # documented memory bound); GQA repeat happens per S_local chunk
    # inside the scan, never on the full gathered arrays
    k_full = lax.all_gather(k, axis_name, axis=1, tiled=True)
    v_full = lax.all_gather(v, axis_name, axis=1, tiled=True)
    qf = q.astype(jnp.float32)

    # online-softmax over S_local-sized chunks of the gathered K/V (the
    # ring's merge math without rotation state): peak score memory is
    # O(Sq_local x Sk_local), not O(Sq_local x S_global)
    def step(carry, j):
        k_c = lax.dynamic_slice_in_dim(k_full, j * sk, sk, 1)
        v_c = lax.dynamic_slice_in_dim(v_full, j * sk, sk, 1)
        k_c, v_c = _repeat_kv(q, k_c, v_c)
        kp = j * sk + jnp.arange(sk, dtype=jnp.int32)
        carry = _merge_partials(
            carry,
            _chunk_partials(qf, k_c.astype(jnp.float32),
                            v_c.astype(jnp.float32), q_pos, kp, s,
                            causal))
        return carry, None

    from ..framework._vma import pvary_missing

    def _vary(x):
        return pvary_missing(x, (axis_name,), like=qf)

    carry0 = (
        _vary(jnp.zeros((b, h, sq, d), jnp.float32)),
        _vary(jnp.full((b, h, sq), _NEG_INF, jnp.float32)),
        _vary(jnp.zeros((b, h, sq), jnp.float32)),
    )
    (acc, m, l), _ = lax.scan(step, carry0, jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(orig_dtype)


def chunked_attention(q, k, v, causal=True, scale=None, chunk=256):
    """Single-device blockwise attention with ONLINE softmax — exact
    attention in O(Sq·chunk) score memory instead of the O(Sq·Sk)
    matrix an einsum+softmax materializes.

    Built for MLA-shaped heads (DeepSeek-V2): q/k share a head dim that
    differs from v's (`models/deepseek.py` — the q/k vs v asymmetry that
    breaks the flash kernel's equal-head-dim contract). q/k:
    [B, Sq, H, Dqk] / [B, Sk, H, Dqk]; v: [B, Sk, H, Dv]; GQA kv heads
    are repeated. The KV chunk loop is the same online-merge math as
    the ppermute ring above (shared ``_chunk_partials``) — a "ring" of
    local chunks instead of devices, run as one ``lax.scan`` so jax
    reverse-mode gives the blockwise backward automatically."""
    orig_dtype = q.dtype
    b, sq, h, dqk = q.shape
    k, v = _repeat_kv(q, k, v)
    sk = k.shape[1]
    dv = v.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(dqk)
    c = min(int(chunk), sk)
    n = -(-sk // c)
    pad = n * c - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    # [n, B, c, H, D] chunk-major so the scan consumes leading dim
    kc = jnp.moveaxis(k.reshape(b, n, c, h, dqk), 1, 0)
    vc = jnp.moveaxis(v.reshape(b, n, c, h, dv), 1, 0)
    q_pos = jnp.arange(sq, dtype=jnp.int32)
    qf = q.astype(jnp.float32)

    def step(carry, inp):
        acc, m, l = carry
        k_c, v_c, j = inp
        k_pos = j * c + jnp.arange(c, dtype=jnp.int32)
        # padded tail columns (k_pos >= sk) are masked in both modes
        acc, m, l = _merge_partials(
            (acc, m, l),
            _chunk_partials(qf, k_c, v_c, q_pos, k_pos, s,
                            causal=causal, k_valid=k_pos < sk))
        return (acc, m, l), None

    carry0 = (jnp.zeros((b, h, sq, dv), jnp.float32),
              jnp.full((b, h, sq), _NEG_INF, jnp.float32),
              jnp.zeros((b, h, sq), jnp.float32))
    (acc, m, l), _ = lax.scan(
        step, carry0, (kc, vc, jnp.arange(n, dtype=jnp.int32)))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(orig_dtype)


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None,
                      attn_fn=None):
    """Ulysses (DeepSpeed-style) SEP attention: all-to-all swaps the
    sequence shard for a head shard, full-sequence attention runs on local
    heads, and a second all-to-all swaps back.

    q/k/v: local chunks [B, S_local, H, D]. Head count must be divisible by
    the sep degree (kv heads are repeated first for GQA). ``attn_fn``
    defaults to an exact fp32-softmax attention; pass a flash kernel for
    TPU perf.
    """
    n = lax.psum(1, axis_name)
    k, v = _repeat_kv(q, k, v)
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses needs num_heads ({q.shape[2]}) divisible by the sep "
            f"degree ({n})")
    # [B, S/n, H, D] -> [B, S, H/n, D]
    qs = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    ks = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vs = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    if attn_fn is None:
        attn_fn = _default_attn_fn()
    out = attn_fn(qs, ks, vs, causal, scale)
    # [B, S, H/n, D] -> [B, S/n, H, D]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def _default_attn_fn():
    """Ulysses local-attention default: the Pallas flash kernel on TPU
    (O(S) memory — the whole point of SEP long-context), exact fp32
    softmax elsewhere (CPU tests / oracle)."""
    import jax as _jax

    if _jax.devices()[0].platform != "tpu":
        return _exact_attention

    def flash(qs, ks, vs, causal, scale):
        from .pallas.flash_attention import flash_attention
        return flash_attention(qs, ks, vs, causal=causal, scale=scale)
    return flash


def _exact_attention(q, k, v, causal, scale):
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# -- host-side zigzag layout helpers ---------------------------------------

def zigzag_reorder(x, degree, axis=1):
    """Reorder a *global* sequence so that contiguous equal shards over the
    sep axis realize the load-balanced causal placement: the sequence is cut
    into 2n chunks and rank r's shard is chunks (r, 2n-1-r)."""
    seq = x.shape[axis]
    if seq % (2 * degree):
        raise ValueError(f"seq {seq} not divisible by 2*degree {2 * degree}")
    chunks = jnp.split(jnp.asarray(x), 2 * degree, axis=axis)
    order = []
    for r in range(degree):
        order += [chunks[r], chunks[2 * degree - 1 - r]]
    return jnp.concatenate(order, axis=axis)


def zigzag_restore(x, degree, axis=1):
    """Inverse of :func:`zigzag_reorder`."""
    chunks = jnp.split(jnp.asarray(x), 2 * degree, axis=axis)
    restored = [None] * (2 * degree)
    for r in range(degree):
        restored[r] = chunks[2 * r]
        restored[2 * degree - 1 - r] = chunks[2 * r + 1]
    return jnp.concatenate(restored, axis=axis)


def zigzag_positions(seq_len, degree):
    """Global position of each token in the zigzag-reordered sequence
    (host-side; e.g. for RoPE applied before sharding)."""
    import numpy as np
    c = seq_len // (2 * degree)
    pos = []
    for r in range(degree):
        pos.append(np.arange(r * c, (r + 1) * c))
        pos.append(np.arange((2 * degree - 1 - r) * c,
                             (2 * degree - r) * c))
    return np.concatenate(pos).astype(np.int32)
