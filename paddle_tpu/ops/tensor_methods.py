"""Install paddle-style methods and operators on ``Tensor``.

Paddle monkey-patches ``paddle.Tensor`` with the tensor-module functions
(python/paddle/tensor/__init__.py `tensor_method_func`, UNVERIFIED); we do
the same so ``x.sum(axis=1)``, ``x @ y``, ``x[...]`` behave identically.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import (Tensor, apply, to_jax_dtype, tape_alias,
                              tape_rebind)
from . import creation, linalg, logic, manipulation, math, search, stat, \
    random_ops
from .common import as_tensor


def _binary_op(fn, reverse=False):
    def op(self, other):
        if reverse:
            return fn(other, self)
        return fn(self, other)
    return op


def _index_fn(item):
    """Normalize a paddle-style index (may contain Tensors) to jax index."""
    if isinstance(item, tuple):
        return tuple(_index_fn(i) for i in item)
    if isinstance(item, Tensor):
        return item._data
    if isinstance(item, list):
        return jnp.asarray(item)
    return item


def _getitem(self, item):
    idx = _index_fn(item)
    if isinstance(idx, Tensor):
        idx = idx._data
    # boolean-mask indexing produces dynamic shapes → host fallback like
    # paddle's masked_select
    def has_bool(ix):
        if isinstance(ix, tuple):
            return any(has_bool(i) for i in ix)
        return hasattr(ix, "dtype") and ix.dtype == jnp.bool_
    if has_bool(idx):
        import numpy as np
        data = np.asarray(self._data)[
            tuple(np.asarray(i) if hasattr(i, "dtype") else i for i in idx)
            if isinstance(idx, tuple) else np.asarray(idx)]
        return Tensor(jnp.asarray(data))
    return apply(lambda a: a[idx], self, name="getitem")


def _setitem(self, item, value):
    idx = _index_fn(item)
    alias = tape_alias(self)
    if isinstance(value, Tensor):
        out = apply(lambda a, v: a.at[idx].set(v.astype(a.dtype)), alias,
                    value, name="setitem")
    else:
        out = apply(lambda a: a.at[idx].set(value), alias, name="setitem")
    tape_rebind(self, out)


def install_tensor_methods() -> None:
    T = Tensor

    # ---- operators --------------------------------------------------------
    T.__add__ = _binary_op(math.add)
    T.__radd__ = _binary_op(math.add, reverse=True)
    T.__sub__ = _binary_op(math.subtract)
    T.__rsub__ = _binary_op(math.subtract, reverse=True)
    T.__mul__ = _binary_op(math.multiply)
    T.__rmul__ = _binary_op(math.multiply, reverse=True)
    T.__truediv__ = _binary_op(math.divide)
    T.__rtruediv__ = _binary_op(math.divide, reverse=True)
    T.__floordiv__ = _binary_op(math.floor_divide)
    T.__rfloordiv__ = _binary_op(math.floor_divide, reverse=True)
    T.__mod__ = _binary_op(math.mod)
    T.__rmod__ = _binary_op(math.mod, reverse=True)
    T.__pow__ = _binary_op(math.pow)
    T.__rpow__ = _binary_op(math.pow, reverse=True)
    T.__matmul__ = _binary_op(linalg.matmul)
    T.__rmatmul__ = _binary_op(linalg.matmul, reverse=True)
    T.__neg__ = lambda self: math.neg(self)
    T.__abs__ = lambda self: math.abs(self)
    T.__invert__ = lambda self: logic.logical_not(self) \
        if self.dtype == jnp.bool_ else logic.bitwise_not(self)
    T.__eq__ = _binary_op(logic.equal)
    T.__ne__ = _binary_op(logic.not_equal)
    T.__lt__ = _binary_op(logic.less_than)
    T.__le__ = _binary_op(logic.less_equal)
    T.__gt__ = _binary_op(logic.greater_than)
    T.__ge__ = _binary_op(logic.greater_equal)
    T.__and__ = _binary_op(logic.bitwise_and)
    T.__or__ = _binary_op(logic.bitwise_or)
    T.__xor__ = _binary_op(logic.bitwise_xor)
    T.__lshift__ = _binary_op(logic.bitwise_left_shift)
    T.__rshift__ = _binary_op(logic.bitwise_right_shift)
    T.__getitem__ = _getitem
    T.__setitem__ = _setitem

    # ---- methods from op modules -----------------------------------------
    modules = [math, manipulation, linalg, logic, search, stat, creation,
               random_ops]
    skip = {"to_tensor", "zeros", "ones", "full", "empty", "arange",
            "linspace", "logspace", "eye", "meshgrid", "tril_indices",
            "triu_indices", "rand", "randn", "randint", "uniform", "normal",
            "gaussian", "randperm", "standard_normal", "is_tensor",
            "one_hot"}
    for mod in modules:
        for name in getattr(mod, "__all__", []):
            if name in skip:
                continue
            fn = getattr(mod, name)
            if callable(fn) and not hasattr(T, name):
                setattr(T, name, fn)

    # ---- explicit methods with tensor-first semantics --------------------
    T.astype = lambda self, dtype: manipulation.cast(self, dtype)
    T.cast = lambda self, dtype: manipulation.cast(self, dtype)
    T.item = Tensor.item
    T.matmul = lambda self, y, transpose_x=False, transpose_y=False, name=None: \
        linalg.matmul(self, y, transpose_x, transpose_y)
    T.mm = lambda self, y, name=None: linalg.matmul(self, y)
    T.dot = lambda self, y, name=None: linalg.dot(self, y)
    T.one_hot = lambda self, num_classes: creation.one_hot(self, num_classes)

    def _cuda(self, device_id=None, blocking=True):
        return self
    T.cuda = _cuda
    T.cpu = lambda self: self
    T.pin_memory = lambda self: self
    T.to = _to

    # in-place aliases used by optimizers / user code; the functional op
    # runs on a tape_alias so the rebound tensor isn't its own parent
    T.add_ = lambda self, y: tape_rebind(self, math.add(tape_alias(self), y))
    T.subtract_ = lambda self, y: tape_rebind(
        self, math.subtract(tape_alias(self), y))
    T.multiply_ = lambda self, y: tape_rebind(
        self, math.multiply(tape_alias(self), y))
    T.divide_ = lambda self, y: tape_rebind(
        self, math.divide(tape_alias(self), y))
    T.scale_ = lambda self, scale=1.0, bias=0.0, bias_after_scale=True, act=None: \
        tape_rebind(self, math.scale(tape_alias(self), scale, bias,
                                     bias_after_scale))
    T.clip_ = lambda self, min=None, max=None: tape_rebind(
        self, math.clip(tape_alias(self), min, max))
    T.zero_ = lambda self: _inplace_nograd(self, jnp.zeros_like(self._data))
    T.fill_ = lambda self, value: _inplace_nograd(
        self, jnp.full_like(self._data, value))
    T.exp_ = lambda self: tape_rebind(self, math.exp(tape_alias(self)))
    T.sqrt_ = lambda self: tape_rebind(self, math.sqrt(tape_alias(self)))
    T.rsqrt_ = lambda self: tape_rebind(self, math.rsqrt(tape_alias(self)))
    T.index_add_ = lambda self, index, axis, value: tape_rebind(
        self, manipulation.index_add(tape_alias(self), index, axis, value))
    T.index_put_ = lambda self, indices, value, accumulate=False: \
        tape_rebind(self, manipulation.index_put(
            tape_alias(self), indices, value, accumulate))
    T.scatter_ = lambda self, index, updates, overwrite=True: tape_rebind(
        self, manipulation.scatter(tape_alias(self), index, updates,
                                   overwrite))
    T.erfinv_ = lambda self, name=None: tape_rebind(
        self, math.erfinv(tape_alias(self)))
    def _relu_(self, name=None):
        # delegate to the ONE relu kernel (jax.nn.relu: grad 0 at x==0;
        # jnp.maximum would split the tie and give 0.5)
        from ..nn.functional.activation import relu_ as f_relu_
        return f_relu_(self)
    T.relu_ = _relu_
    T.put_along_axis_ = lambda self, indices, values, axis, \
        reduce="assign", include_self=True, broadcast=True: tape_rebind(
        self, manipulation.put_along_axis(
            tape_alias(self), indices, values, axis, reduce,
            include_self, broadcast))
    T.ndimension = lambda self: len(self.shape)
    # jax arrays are immutable; every in-place Tensor op rebinds, so the
    # version counter the reference exposes is structurally 0
    T.inplace_version = property(lambda self: 0)
    T.gradient = _gradient
    T.copy_ = _copy_
    T.set_value = _set_value
    T.get_tensor = lambda self: self
    T.value = lambda self: self
    T.uniform_ = random_ops.uniform_
    T.normal_ = random_ops.normal_
    T.exponential_ = random_ops.exponential_
    T.log_normal_ = _log_normal_
    T.apply_ = _apply_
    T.apply = lambda self, func: func(self)
    T.nbytes = property(lambda self: int(
        self._data.size * self._data.dtype.itemsize))
    # jax arrays are always dense row-major (XLA owns layout)
    T.is_contiguous = lambda self: True
    T.contiguous = lambda self: self
    T.coalesce = lambda self, name=None: self  # dense tensors: identity


def _inplace_nograd(t: Tensor, data) -> Tensor:
    t.set_data(data)
    return t


def _log_normal_(self, mean=1.0, std=2.0, name=None):
    """In-place log-normal fill: exp(N(mean, std)) (paddle parity)."""
    from ..framework import random as fr
    key = fr.default_generator.next_key()
    import jax
    draw = jax.random.normal(key, self._data.shape) * std + mean
    return _inplace_nograd(self, jnp.exp(draw).astype(self._data.dtype))


def _apply_(self, func):
    """In-place elementwise python-function map (paddle Tensor.apply_):
    func receives and returns a Tensor; the result overwrites self."""
    out = func(self)
    data = out._data if isinstance(out, Tensor) else jnp.asarray(out)
    return _inplace_nograd(self, data.astype(self._data.dtype))


def _gradient(self):
    """Legacy ``Tensor.gradient()``: the accumulated grad as a numpy
    array (None when no grad), paddle 1.x-era API kept for parity."""
    import numpy as np
    g = self.grad
    return None if g is None else np.asarray(g.numpy())


def _copy_(self, other, blocking=True):
    src = other._data if isinstance(other, Tensor) else jnp.asarray(other)
    self.set_data(src.astype(self.dtype))
    return self


def _set_value(self, value):
    src = value._data if isinstance(value, Tensor) else jnp.asarray(value)
    self.set_data(jnp.asarray(src, dtype=self.dtype).reshape(self._data.shape)
                  if src.size == self.size else src.astype(self.dtype))
    return self


def _to(self, *args, **kwargs):
    dtype = kwargs.get("dtype")
    for a in args:
        if isinstance(a, str) and (a in ("cpu",) or ":" in a or a in
                                   ("gpu", "tpu", "xpu", "cuda")):
            continue  # single-device program; placement handled by jax
        elif a is not None and not isinstance(a, bool):
            dtype = a
    if dtype is not None:
        return manipulation.cast(self, dtype)
    return self
