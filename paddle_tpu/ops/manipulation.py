"""Shape / layout / indexing manipulation ops
(paddle/tensor/manipulation.py parity, UNVERIFIED)."""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import (Tensor, apply, to_jax_dtype, tape_alias,
                              tape_rebind)
from .common import as_tensor

__all__ = [
    "reshape", "reshape_", "transpose", "cast", "concat", "stack", "split",
    "chunk", "squeeze", "squeeze_", "unsqueeze", "unsqueeze_", "flatten",
    "flip", "roll", "tile", "expand", "expand_as", "broadcast_to",
    "broadcast_tensors", "gather", "gather_nd", "scatter", "scatter_nd",
    "scatter_nd_add", "index_select", "index_sample", "index_add", "index_put",
    "take_along_axis", "put_along_axis", "slice", "strided_slice", "unbind",
    "unstack", "tensordot", "moveaxis", "swapaxes", "rot90", "as_strided",
    "repeat_interleave", "masked_select", "masked_fill", "masked_scatter",
    "clone", "flatten_", "tolist", "unique", "unique_consecutive",
    "split_sections", "crop", "pad", "shard_index", "view", "view_as",
    "atleast_1d", "atleast_2d", "atleast_3d", "diff", "rot90",
    "tensor_split", "hsplit", "vsplit", "dsplit", "hstack", "vstack",
    "row_stack", "dstack", "column_stack", "unflatten", "unfold",
    "as_complex", "as_real", "diag_embed", "fill_diagonal_",
    "fill_diagonal_tensor", "fill_diagonal_tensor_", "select_scatter",
    "slice_scatter", "index_fill", "index_fill_", "masked_fill_",
    "masked_scatter_", "block_diag", "cartesian_prod", "combinations",
    "vander", "take",
]


def _norm_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape._data))
    out = []
    for s in shape:
        if isinstance(s, Tensor):
            out.append(int(s.item()))
        elif isinstance(s, (int, np.integer)):
            out.append(int(s))
        else:
            # symbolic dim (jax.export shape polymorphism) — pass through
            out.append(s)
    return tuple(out)


def reshape(x, shape, name=None):
    x = as_tensor(x)
    shape = _norm_shape(shape)
    return apply(lambda a: jnp.reshape(a, shape), x, name="reshape")


def reshape_(x, shape, name=None):
    return tape_rebind(x, reshape(tape_alias(x), shape))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    # dtype view is a BITCAST (paddle Tensor.view(dtype) reinterprets the
    # bytes), not a value cast; element count rescales by the width ratio
    x = as_tensor(x)
    jd = to_jax_dtype(shape_or_dtype)
    src_size = jnp.dtype(x.dtype).itemsize
    dst_size = jnp.dtype(jd).itemsize

    def fn(a):
        if src_size == dst_size:
            return jax.lax.bitcast_convert_type(a, jd)
        if src_size > dst_size:  # narrowing adds a trailing axis; fold it
            out = jax.lax.bitcast_convert_type(a, jd)
            return out.reshape(a.shape[:-1] +
                               (a.shape[-1] * (src_size // dst_size),))
        ratio = dst_size // src_size
        if a.shape[-1] % ratio:
            raise ValueError(
                f"view({jd}): last dim {a.shape[-1]} not divisible by "
                f"width ratio {ratio}")
        out = a.reshape(a.shape[:-1] + (a.shape[-1] // ratio, ratio))
        return jax.lax.bitcast_convert_type(out, jd)
    return apply(fn, x, name="view", differentiable=False)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def transpose(x, perm=None, name=None):
    x = as_tensor(x)
    if perm is None:
        perm = list(range(x.ndim))[::-1]
    perm = [int(p) for p in perm]
    return apply(lambda a: jnp.transpose(a, perm), x, name="transpose")


def moveaxis(x, source, destination, name=None):
    return apply(lambda a: jnp.moveaxis(a, source, destination), as_tensor(x),
                 name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda a: jnp.swapaxes(a, axis0, axis1), as_tensor(x),
                 name="swapaxes")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), as_tensor(x),
                 name="rot90")


def cast(x, dtype, name=None):
    x = as_tensor(x)
    jd = to_jax_dtype(dtype)
    return apply(lambda a: a.astype(jd), x, name="cast")


def concat(x, axis=0, name=None):
    ts = [as_tensor(t) for t in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply(lambda *xs: jnp.concatenate(xs, axis=int(axis)), *ts,
                 name="concat")


def stack(x, axis=0, name=None):
    ts = [as_tensor(t) for t in x]
    return apply(lambda *xs: jnp.stack(xs, axis=int(axis)), *ts, name="stack")


def split(x, num_or_sections, axis=0, name=None):
    x = as_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    axis = int(axis)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        n = num_or_sections
        if dim % n != 0:
            raise ValueError(
                f"split: dimension {axis} of size {dim} is not divisible "
                f"by num_or_sections={n}")
        sizes = [dim // n] * n
    else:
        sizes = [int(s.item()) if isinstance(s, Tensor) else int(s)
                 for s in num_or_sections]
        n_unknown = builtins.sum(1 for s in sizes if s in (-1,))
        if n_unknown:
            known = builtins.sum(s for s in sizes if s != -1)
            sizes = [dim - known if s == -1 else s for s in sizes]
    offsets = np.cumsum([0] + sizes)

    def fn(a):
        return tuple(jax.lax.slice_in_dim(a, int(offsets[i]),
                                          int(offsets[i + 1]), axis=axis)
                     for i in range(len(sizes)))
    outs = apply(fn, x, n_outputs=len(sizes), name="split")
    return list(outs)


split_sections = split


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def _norm_axes(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (int, np.integer)):
        axis = [axis]
    return tuple(int(a) % ndim if int(a) >= 0 else int(a) for a in axis)


def squeeze(x, axis=None, name=None):
    x = as_tensor(x)
    ax = _norm_axes(axis, x.ndim)
    if ax is not None:
        ax = tuple(a for a in ax if x.shape[a] == 1)
        if not ax:
            return apply(lambda a: a, x, name="squeeze")
    return apply(lambda a: jnp.squeeze(a, axis=ax), x, name="squeeze")


def squeeze_(x, axis=None, name=None):
    return tape_rebind(x, squeeze(tape_alias(x), axis))


def unsqueeze(x, axis, name=None):
    x = as_tensor(x)
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    axes = [axis] if isinstance(axis, (int, np.integer)) else list(axis)
    axes = [int(a) for a in axes]

    def fn(a):
        out = a
        for ax in axes:
            out = jnp.expand_dims(out, ax)
        return out
    return apply(fn, x, name="unsqueeze")


def unsqueeze_(x, axis, name=None):
    return tape_rebind(x, unsqueeze(tape_alias(x), axis))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = as_tensor(x)
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    new_shape = x.shape[:s] + [-1] + x.shape[e + 1:]
    if nd == 0:
        new_shape = [1]
    return apply(lambda a: jnp.reshape(a, new_shape), x, name="flatten")


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return tape_rebind(x, flatten(tape_alias(x), start_axis, stop_axis))


def flip(x, axis, name=None):
    if isinstance(axis, (int, np.integer)):
        axis = [axis]
    axis = tuple(int(a) for a in axis)
    return apply(lambda a: jnp.flip(a, axis=axis), as_tensor(x), name="flip")


def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, Tensor):
        shifts = shifts.tolist()
    return apply(lambda a: jnp.roll(a, shifts, axis=axis), as_tensor(x),
                 name="roll")


def tile(x, repeat_times, name=None):
    repeat_times = _norm_shape(repeat_times)
    return apply(lambda a: jnp.tile(a, repeat_times), as_tensor(x),
                 name="tile")


def expand(x, shape, name=None):
    x = as_tensor(x)
    shape = _norm_shape(shape)
    tgt = []
    xshape = ([1] * (len(shape) - x.ndim)) + x.shape
    for s, xs in zip(shape, xshape):
        tgt.append(xs if s == -1 else s)
    return apply(lambda a: jnp.broadcast_to(a, tuple(tgt)), x, name="expand")


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    ts = [as_tensor(t) for t in inputs]
    outs = apply(lambda *xs: tuple(jnp.broadcast_arrays(*xs)), *ts,
                 n_outputs=len(ts), name="broadcast_tensors")
    return list(outs)


def gather(x, index, axis=0, name=None):
    x, index = as_tensor(x), as_tensor(index)
    if isinstance(axis, Tensor):
        axis = int(axis.item())

    def fn(a, idx):
        return jnp.take(a, idx.reshape(-1) if idx.ndim > 1 else idx,
                        axis=int(axis))
    return apply(fn, x, index, name="gather")


def gather_nd(x, index, name=None):
    x, index = as_tensor(x), as_tensor(index)

    def fn(a, idx):
        k = idx.shape[-1]
        return a[tuple(jnp.moveaxis(idx, -1, 0))] if k == a.ndim else \
            a[tuple(jnp.moveaxis(idx, -1, 0))]
    return apply(fn, x, index, name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    x, index, updates = as_tensor(x), as_tensor(index), as_tensor(updates)

    def fn(a, idx, upd):
        idx = idx.reshape(-1)
        if overwrite:
            return a.at[idx].set(upd)
        return a.at[idx].add(upd)
    return apply(fn, x, index, updates, name="scatter")


def scatter_nd(index, updates, shape, name=None):
    index, updates = as_tensor(index), as_tensor(updates)
    shape = _norm_shape(shape)

    def fn(idx, upd):
        zeros = jnp.zeros(shape, upd.dtype)
        return zeros.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return apply(fn, index, updates, name="scatter_nd")


def scatter_nd_add(x, index, updates, name=None):
    x, index, updates = as_tensor(x), as_tensor(index), as_tensor(updates)

    def fn(a, idx, upd):
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return apply(fn, x, index, updates, name="scatter_nd_add")


def index_select(x, index, axis=0, name=None):
    x, index = as_tensor(x), as_tensor(index)
    return apply(lambda a, i: jnp.take(a, i, axis=int(axis)), x, index,
                 name="index_select")


def index_sample(x, index, name=None):
    x, index = as_tensor(x), as_tensor(index)
    return apply(lambda a, i: jnp.take_along_axis(a, i, axis=1), x, index,
                 name="index_sample")


def index_add(x, index, axis, value, name=None):
    x, index, value = as_tensor(x), as_tensor(index), as_tensor(value)

    def fn(a, i, v):
        am = jnp.moveaxis(a, int(axis), 0)
        vm = jnp.moveaxis(v, int(axis), 0)
        return jnp.moveaxis(am.at[i].add(vm), 0, int(axis))
    return apply(fn, x, index, value, name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    x = as_tensor(x)
    value = as_tensor(value)
    idx_ts = [as_tensor(i) for i in indices]

    def fn(a, v, *idx):
        if accumulate:
            return a.at[tuple(idx)].add(v)
        return a.at[tuple(idx)].set(v)
    return apply(fn, x, value, *idx_ts, name="index_put")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    arr, indices = as_tensor(arr), as_tensor(indices)
    return apply(lambda a, i: jnp.take_along_axis(a, i, axis=int(axis)),
                 arr, indices, name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    arr, indices = as_tensor(arr), as_tensor(indices)
    values = as_tensor(values)

    def fn(a, i, v):
        v = jnp.broadcast_to(v, i.shape) if broadcast else v
        if reduce == "add":
            return jnp.put_along_axis(a, i, jnp.take_along_axis(a, i, axis=int(axis)) + v, axis=int(axis), inplace=False) \
                if hasattr(jnp, "put_along_axis") else _pala(a, i, v, int(axis), "add")
        if reduce in ("mul", "multiply"):
            return _pala(a, i, jnp.take_along_axis(a, i, axis=int(axis)) * v,
                         int(axis), "assign")
        return _pala(a, i, v, int(axis), "assign")
    return apply(fn, arr, indices, values, name="put_along_axis")


def _pala(a, i, v, axis, mode):
    am = jnp.moveaxis(a, axis, 0)
    im = jnp.moveaxis(i, axis, 0)
    vm = jnp.moveaxis(jnp.broadcast_to(v, i.shape), axis, 0)
    grid = jnp.indices(im.shape)
    idx = (im,) + tuple(grid[k] for k in range(1, im.ndim))
    if mode == "add":
        out = am.at[idx].add(vm)
    else:
        out = am.at[idx].set(vm)
    return jnp.moveaxis(out, 0, axis)


def slice(input, axes, starts, ends, name=None):
    input = as_tensor(input)
    starts = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in starts]
    ends = [int(e.item()) if isinstance(e, Tensor) else int(e) for e in ends]

    def fn(a):
        idx = [jnp.s_[:]] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = jnp.s_[s:e]
        return a[tuple(idx)]
    return apply(fn, input, name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = as_tensor(x)

    def fn(a):
        idx = [jnp.s_[:]] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = jnp.s_[s:e:st]
        return a[tuple(idx)]
    return apply(fn, x, name="strided_slice")


def as_strided(x, shape, stride, offset=0, name=None):
    x = as_tensor(x)

    def fn(a):
        flat = a.reshape(-1)
        idx = np.zeros(tuple(shape), dtype=np.int64) + offset
        for d, (sh, st) in enumerate(zip(shape, stride)):
            ix = np.arange(sh) * st
            idx += ix.reshape([-1 if i == d else 1 for i in range(len(shape))])
        return flat[jnp.asarray(idx)]
    return apply(fn, x, name="as_strided")


def unbind(input, axis=0, name=None):
    input = as_tensor(input)
    n = input.shape[int(axis)]

    def fn(a):
        return tuple(jnp.squeeze(s, axis=int(axis))
                     for s in jnp.split(a, n, axis=int(axis)))
    return list(apply(fn, input, n_outputs=n, name="unbind"))


unstack = unbind


def tensordot(x, y, axes=2, name=None):
    def _conv(ax):
        if isinstance(ax, Tensor):
            return ax.tolist()
        return ax
    return apply(lambda a, b: jnp.tensordot(a, b, axes=_conv(axes)),
                 as_tensor(x), as_tensor(y), name="tensordot")


def repeat_interleave(x, repeats, axis=None, name=None):
    x = as_tensor(x)
    if isinstance(repeats, Tensor):
        # per-element repeats produce a data-dependent output shape; the
        # total must be concrete (jnp.repeat needs total_repeat_length
        # under tracing, which we cannot know) — eager-only, like paddle's
        # dynamic-shape ops under to_static (graph break)
        if isinstance(repeats._data, jax.core.Tracer):
            raise jax.errors.ConcretizationTypeError(
                repeats._data,
                "repeat_interleave with tensor repeats has a data-dependent "
                "output shape and cannot be traced; it falls back to eager "
                "under to_static")
        total = int(np.asarray(repeats._data).sum())
        return apply(lambda a, r: jnp.repeat(
            a, r, axis=axis, total_repeat_length=total),
            x, repeats, name="repeat_interleave")
    return apply(lambda a: jnp.repeat(a, repeats, axis=axis), x,
                 name="repeat_interleave")


def masked_select(x, mask, name=None):
    x, mask = as_tensor(x), as_tensor(mask)
    # dynamic shape: materialize on host (eager-only op, like paddle's)
    data = np.asarray(x._data)[np.asarray(mask._data)]
    return Tensor(jnp.asarray(data))


def masked_fill(x, mask, value, name=None):
    x, mask = as_tensor(x), as_tensor(mask)
    if isinstance(value, Tensor):
        return apply(lambda a, m, v: jnp.where(m, v.astype(a.dtype), a),
                     x, mask, value, name="masked_fill")
    return apply(lambda a, m: jnp.where(m, jnp.asarray(value, a.dtype), a),
                 x, mask, name="masked_fill")


def masked_scatter(x, mask, value, name=None):
    x, mask, value = as_tensor(x), as_tensor(mask), as_tensor(value)
    xd, md, vd = (np.asarray(t._data) for t in (x, mask, value))
    out = xd.copy()
    out[md] = vd.reshape(-1)[: int(md.sum())]
    return Tensor(jnp.asarray(out))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    x = as_tensor(x)
    res = np.unique(np.asarray(x._data), return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not (return_index or return_inverse or return_counts):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    x = np.asarray(as_tensor(x)._data)
    if axis is None:
        x = x.reshape(-1)
    keep = np.ones(x.shape[0], dtype=bool)
    keep[1:] = np.any(x[1:] != x[:-1], axis=tuple(range(1, x.ndim))) \
        if x.ndim > 1 else x[1:] != x[:-1]
    out = [Tensor(jnp.asarray(x[keep]))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        out.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, x.shape[0]))
        out.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return out[0] if len(out) == 1 else tuple(out)


def crop(x, shape=None, offsets=None, name=None):
    x = as_tensor(x)
    shape = _norm_shape(shape)
    offsets = [0] * x.ndim if offsets is None else \
        [int(o.item()) if isinstance(o, Tensor) else int(o) for o in offsets]
    shape = [x.shape[i] - offsets[i] if s == -1 else s
             for i, s in enumerate(shape)]

    def fn(a):
        return jax.lax.slice(a, offsets,
                             [o + s for o, s in zip(offsets, shape)])
    return apply(fn, x, name="crop")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = as_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]

    def fn(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            # full-rank paddle format: per-dim (before, after), dim order
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # partial spec applies to trailing spatial dims, reversed pairs
            k = len(pad) // 2
            widths = [(0, 0)] * nd
            if data_format.endswith("C") and nd >= 3:  # NHWC-style
                spatial = list(range(1, nd - 1))[-k:]
            else:
                spatial = list(range(nd))[-k:]
            for j, d in enumerate(reversed(spatial)):
                widths[d] = (pad[2 * j], pad[2 * j + 1])
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, widths, mode=jmode, constant_values=value)
        return jnp.pad(a, widths, mode=jmode)
    return apply(fn, x, name="pad")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    input = as_tensor(input)
    size = index_num // nshards

    def fn(a):
        shard = a // size
        return jnp.where(shard == shard_id, a % size, ignore_value)
    return apply(fn, input, name="shard_index")


def atleast_1d(*inputs, name=None):
    outs = [apply(jnp.atleast_1d, as_tensor(x), name="atleast_1d")
            for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply(jnp.atleast_2d, as_tensor(x), name="atleast_2d")
            for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply(jnp.atleast_3d, as_tensor(x), name="atleast_3d")
            for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [as_tensor(x)]
    if prepend is not None:
        args.append(as_tensor(prepend))
    if append is not None:
        args.append(as_tensor(append))

    def fn(a, *rest):
        i = 0
        pre = app = None
        if prepend is not None:
            pre = rest[i]; i += 1
        if append is not None:
            app = rest[i]
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)
    return apply(fn, *args, name="diff")


def clone(x, name=None):
    from .creation import clone as _clone
    return _clone(x)


def tolist(x):
    return as_tensor(x).tolist()


# ---- split/stack family long tail -----------------------------------------

def tensor_split(x, num_or_indices, axis=0, name=None):
    """paddle.tensor_split: uneven splits allowed (numpy array_split)."""
    x = as_tensor(x)
    axis = int(axis)
    if isinstance(num_or_indices, int):
        n = num_or_indices
        size = x.shape[axis]
        base, extra = divmod(size, n)
        sizes = [base + (1 if i < extra else 0) for i in range(n)]
        bounds = np.cumsum(sizes)[:-1].tolist()
    else:
        bounds = [int(i) for i in num_or_indices]
    outs = apply(lambda a: tuple(jnp.split(a, bounds, axis=axis)), x,
                 n_outputs=len(bounds) + 1, name="tensor_split")
    return list(outs)


def hsplit(x, num_or_indices, name=None):
    x = as_tensor(x)
    if x.ndim < 1:
        raise ValueError("hsplit expects at least a 1-D tensor")
    return tensor_split(x, num_or_indices, axis=0 if x.ndim == 1 else 1)


def vsplit(x, num_or_indices, name=None):
    x = as_tensor(x)
    if x.ndim < 2:
        raise ValueError("vsplit expects at least a 2-D tensor")
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    x = as_tensor(x)
    if x.ndim < 3:
        raise ValueError("dsplit expects at least a 3-D tensor")
    return tensor_split(x, num_or_indices, axis=2)


def hstack(x, name=None):
    ts = [atleast_1d(as_tensor(t)) for t in x]
    axis = 0 if ts[0].ndim <= 1 else 1
    return concat(ts, axis=axis)


def vstack(x, name=None):
    ts = [atleast_2d(as_tensor(t)) for t in x]
    return concat(ts, axis=0)


row_stack = vstack


def dstack(x, name=None):
    ts = [atleast_3d(as_tensor(t)) for t in x]
    return concat(ts, axis=2)


def column_stack(x, name=None):
    ts = []
    for t in x:
        t = as_tensor(t)
        if t.ndim <= 1:
            t = reshape(t, [-1, 1])
        ts.append(t)
    return concat(ts, axis=1)


def unflatten(x, axis, shape, name=None):
    x = as_tensor(x)
    axis = int(axis) % max(x.ndim, 1)
    shape = _norm_shape(shape)
    new_shape = list(x.shape[:axis]) + list(shape) + list(x.shape[axis + 1:])
    return reshape(x, new_shape)


def unfold(x, axis, size, step, name=None):
    """Sliding windows along ``axis`` (paddle.Tensor.unfold): output gains
    a trailing window dim of length ``size``."""
    x = as_tensor(x)
    axis = int(axis) % x.ndim
    n = (x.shape[axis] - int(size)) // int(step) + 1

    def fn(a):
        idx = (np.arange(n)[:, None] * int(step) +
               np.arange(int(size))[None, :])
        win = jnp.take(a, jnp.asarray(idx.reshape(-1)), axis=axis)
        win = jnp.reshape(
            win, a.shape[:axis] + (n, int(size)) + a.shape[axis + 1:])
        return jnp.moveaxis(win, axis + 1, -1)
    return apply(fn, x, name="unfold")


# ---- complex views ---------------------------------------------------------

def as_complex(x, name=None):
    """[..., 2] float -> [...] complex (paddle.as_complex)."""
    x = as_tensor(x)
    return apply(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x,
                 name="as_complex")


def as_real(x, name=None):
    x = as_tensor(x)
    return apply(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1),
                 x, name="as_real")


# ---- diagonal / scatter-style writes --------------------------------------

def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    x = as_tensor(input)

    def fn(a):
        n = a.shape[-1] + builtins.abs(int(offset))
        nd = a.ndim + 1
        d1, d2 = int(dim1) % nd, int(dim2) % nd
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        ii = jnp.arange(a.shape[-1])
        rows = ii + builtins.max(-int(offset), 0)
        cols = ii + builtins.max(int(offset), 0)
        base = base.at[..., rows, cols].set(a)
        # embedded plane currently at (-2, -1); move to (dim1, dim2)
        perm = [i for i in range(nd) if i not in (d1, d2)]
        out_axes = sorted((d1, d2))
        full = list(range(nd - 2)) + [nd - 2, nd - 1]
        dest = perm + [d1, d2]
        inv = [0] * nd
        for src, dst in zip(full, dest):
            inv[dst] = src
        return jnp.transpose(base, inv)
    return apply(fn, x, name="diag_embed")


def _diag_len(rows, cols, offset):
    """Number of elements on diagonal ``offset`` of a (rows, cols) plane."""
    if offset >= 0:
        return builtins.max(builtins.min(rows, cols - offset), 0)
    return builtins.max(builtins.min(rows + offset, cols), 0)


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    def fn(a):
        if a.ndim == 2:
            off = int(offset)
            if wrap and a.shape[0] > a.shape[1] and off == 0:
                # tall matrices: the diagonal restarts every cols+1 rows
                per = a.shape[1] + 1
                r = np.arange(a.shape[0])
                c = r % per
                keep = c < a.shape[1]
                r, c = r[keep], c[keep]
            else:
                n = _diag_len(a.shape[0], a.shape[1], off)
                ii = np.arange(n)
                r = ii + builtins.max(-off, 0)
                c = ii + builtins.max(off, 0)
            return a.at[r, c].set(jnp.asarray(value, a.dtype))
        # ndim > 2: paddle/torch fill the main HYPER-diagonal
        # x[i, i, ..., i] (all dims must be equal, offset 0)
        if int(offset) != 0:
            raise ValueError(
                "fill_diagonal_: offset must be 0 for ndim > 2")
        if builtins.len(set(a.shape)) != 1:
            raise ValueError(
                "fill_diagonal_: all dimensions must be equal for "
                f"ndim > 2, got {a.shape}")
        ii = jnp.arange(a.shape[0])
        return a.at[(ii,) * a.ndim].set(jnp.asarray(value, a.dtype))
    out = apply(fn, tape_alias(x), name="fill_diagonal_")
    return tape_rebind(x, out)


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    x, y = as_tensor(x), as_tensor(y)

    def fn(a, b):
        d1, d2 = int(dim1) % a.ndim, int(dim2) % a.ndim
        off = int(offset)
        moved = jnp.moveaxis(a, (d1, d2), (-2, -1))
        n = _diag_len(moved.shape[-2], moved.shape[-1], off)
        ii = jnp.arange(n)
        rows = ii + builtins.max(-off, 0)
        cols = ii + builtins.max(off, 0)
        moved = moved.at[..., rows, cols].set(b)   # b: [..., n]
        return jnp.moveaxis(moved, (-2, -1), (d1, d2))
    return apply(fn, x, y, name="fill_diagonal_tensor")


def fill_diagonal_tensor_(x, y, offset=0, dim1=0, dim2=1, name=None):
    return tape_rebind(x, fill_diagonal_tensor(tape_alias(x), y, offset,
                                               dim1, dim2))


def select_scatter(x, values, axis, index, name=None):
    x, v = as_tensor(x), as_tensor(values)
    axis_i, idx = int(axis), int(index)
    return apply(
        lambda a, b: a.at[(np.s_[:],) * (axis_i % a.ndim) + (idx,)].set(
            b.astype(a.dtype)),
        x, v, name="select_scatter")


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    x, v = as_tensor(x), as_tensor(value)

    def fn(a, b):
        sl = [np.s_[:]] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sl[int(ax)] = np.s_[int(s):int(e):int(st)]
        return a.at[tuple(sl)].set(b.astype(a.dtype))
    return apply(fn, x, v, name="slice_scatter")


def index_fill(x, index, axis, value, name=None):
    x = as_tensor(x)
    index = as_tensor(index)

    def fn(a, idx):
        moved = jnp.moveaxis(a, int(axis), 0)
        moved = moved.at[idx].set(jnp.asarray(value, a.dtype))
        return jnp.moveaxis(moved, 0, int(axis))
    return apply(fn, x, index, name="index_fill")


def index_fill_(x, index, axis, value, name=None):
    return tape_rebind(x, index_fill(tape_alias(x), index, axis, value))


def masked_fill_(x, mask, value, name=None):
    return tape_rebind(x, masked_fill(tape_alias(x), mask, value))


def masked_scatter_(x, mask, value, name=None):
    return tape_rebind(x, masked_scatter(tape_alias(x), mask, value))


# ---- combinatoric constructors --------------------------------------------

def block_diag(inputs, name=None):
    ts = [as_tensor(t) for t in inputs]

    def fn(*arrs):
        arrs = [jnp.atleast_2d(a) for a in arrs]
        rows = builtins.sum(a.shape[0] for a in arrs)
        cols = builtins.sum(a.shape[1] for a in arrs)
        out = jnp.zeros((rows, cols), arrs[0].dtype)
        r = c = 0
        for a in arrs:
            out = out.at[r:r + a.shape[0], c:c + a.shape[1]].set(a)
            r += a.shape[0]
            c += a.shape[1]
        return out
    return apply(fn, *ts, name="block_diag")


def cartesian_prod(x, name=None):
    ts = [as_tensor(t) for t in x]

    def fn(*arrs):
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    out = apply(fn, *ts, name="cartesian_prod")
    if len(ts) == 1:
        return reshape(out, [-1])
    return out


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools
    x = as_tensor(x)
    n = x.shape[0]
    gen = (itertools.combinations_with_replacement if with_replacement
           else itertools.combinations)
    idx = np.asarray(list(gen(range(n), int(r))), dtype=np.int64)
    if idx.size == 0:
        idx = idx.reshape(0, int(r))
    return apply(lambda a: jnp.take(a, jnp.asarray(idx), axis=0), x,
                 name="combinations")


def vander(x, n=None, increasing=False, name=None):
    x = as_tensor(x)
    num = x.shape[0] if n is None else int(n)
    return apply(lambda a: jnp.vander(a, num, increasing=increasing), x,
                 name="vander")


def take(x, index, mode="raise", name=None):
    """Flat-index gather with paddle's mode semantics ('raise', 'wrap',
    'clip'). mode='raise' validates eagerly when the index is concrete;
    under tracing (where raising is impossible) it clips like
    numpy-on-device."""
    x = as_tensor(x)
    index = as_tensor(index)
    if mode == "raise" and not isinstance(index._data, jax.core.Tracer):
        size = 1
        for s in x.shape:
            size *= int(s)
        idx_np = np.asarray(index._data)
        if idx_np.size and (int(idx_np.min()) < -size
                            or int(idx_np.max()) >= size):
            raise IndexError(
                f"paddle.take(mode='raise'): index out of range for "
                f"input with {size} elements "
                f"(min {int(idx_np.min())}, max {int(idx_np.max())})")

    def fn(a, idx):
        flat = a.reshape(-1)
        size = flat.shape[0]
        if mode == "wrap":
            idx = ((idx % size) + size) % size
        else:
            idx = jnp.where(idx < 0, idx + size, idx)
            idx = jnp.clip(idx, 0, size - 1)
        return jnp.take(flat, idx)
    return apply(fn, x, index, name="take")


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Write y onto the selected diagonal of x (paddle.diagonal_scatter)."""
    x, y = as_tensor(x), as_tensor(y)
    return fill_diagonal_tensor(x, y, offset=offset, dim1=axis1, dim2=axis2)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


__all__ += ["diagonal_scatter", "broadcast_shape"]


def shape(input, name=None):
    """paddle.shape — the runtime shape as an int32 tensor (static under
    XLA, so this is a constant in compiled programs)."""
    x = as_tensor(input)
    from .creation import to_tensor
    return to_tensor(np.asarray(x._data.shape, np.int32))


__all__ += ["shape"]
