"""Shared helpers for the op library."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import ObservedFloat, Tensor, apply, to_jax_dtype

__all__ = ["Tensor", "apply", "to_jax_dtype", "as_tensor", "unary", "binary"]


def as_tensor(x, dtype=None) -> Tensor:
    if isinstance(x, Tensor):
        return x
    if isinstance(x, ObservedFloat):
        x._misuse("tensor creation")
    return Tensor(jnp.asarray(x, dtype=to_jax_dtype(dtype)))


def unary(fn, name):
    """Build a paddle-style unary op ``op(x, name=None)``."""
    def op(x, name=None):
        return apply(fn, as_tensor(x), name=name or fn.__name__)
    op.__name__ = name
    return op


def binary(fn, name):
    """Build a paddle-style broadcasting binary op ``op(x, y, name=None)``."""
    def op(x, y, name=None):
        xt = x if isinstance(x, Tensor) else x
        yt = y if isinstance(y, Tensor) else y
        # keep python scalars as scalars (weak-typed in jax, matches paddle
        # scalar-op behavior); coerce lists/ndarrays to tensors
        if not isinstance(xt, Tensor) and not _is_scalar(xt):
            xt = as_tensor(xt)
        if not isinstance(yt, Tensor) and not _is_scalar(yt):
            yt = as_tensor(yt)
        return apply(fn, xt, yt, name=name or fn.__name__)
    op.__name__ = name
    return op


def _is_scalar(x) -> bool:
    return isinstance(x, (int, float, bool, complex))
