"""Paged attention for serving-time autoregressive decode.

Role of the reference inference engine's paged/ragged KV-cache attention
(Paddle Inference fused attention ops + PaddleNLP serving kernels,
UNVERIFIED — reference mount empty). The KV cache is stored as fixed-size
*pages* in a global pool; each sequence owns a list of pages (its block
table), so cache memory is allocated per-page instead of per-max-length —
the vLLM/TPU-serving design (see PAPERS.md ragged-paged-attention).

TPU-native: the fast path is the Pallas TPU paged-attention kernel that
ships with jax (``jax.experimental.pallas.ops.tpu.paged_attention``, a
scalar-prefetch kernel that streams only the pages named in the block
table through VMEM). The reference path below is pure jnp (gather +
masked softmax) — the numeric oracle and the CPU/debug fallback.

Layouts (decode step, one query token per sequence):
  q            [B, H, D]
  key_pages    [KVH, num_pages, page_size, D]
  value_pages  [KVH, num_pages, page_size, D]
  block_tables [B, pages_per_seq] int32 — page ids, row-padded with any
               valid id past the sequence's last page
  context_lens [B] int32 — tokens currently in cache per sequence
GQA/MQA: H a multiple of KVH; q head h attends kv head h // (H // KVH).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["paged_attention", "paged_attention_reference",
           "paged_decode_write", "pack_prompt_into_pages"]

_NEG_INF = -1e30


def paged_attention_reference(q, key_pages, value_pages, block_tables,
                              context_lens, scale=None):
    """Pure-jnp oracle: gather each sequence's pages, mask, soft-max."""
    b, h, d = q.shape
    kvh, _, page_size, _ = key_pages.shape
    rep = h // kvh
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    max_len = block_tables.shape[1] * page_size

    def one_seq(qi, table, ctx_len):
        # [KVH, pages_per_seq, page, D] -> [KVH, max_len, D]
        k = key_pages[:, table].reshape(kvh, max_len, d)
        v = value_pages[:, table].reshape(kvh, max_len, d)
        k = jnp.repeat(k, rep, axis=0)  # [H, max_len, D]
        v = jnp.repeat(v, rep, axis=0)
        logits = jnp.einsum("hd,hkd->hk", qi, k,
                            preferred_element_type=jnp.float32) * s
        mask = jnp.arange(max_len) < ctx_len
        logits = jnp.where(mask[None, :], logits, _NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
        return jnp.einsum("hk,hkd->hd", probs, v)

    return jax.vmap(one_seq)(q, block_tables, context_lens)


def paged_attention(q, key_pages, value_pages, block_tables, context_lens,
                    scale=None):
    """Decode-step paged attention; Pallas kernel on TPU, jnp oracle
    elsewhere (flag ``FLAGS_use_pallas_paged_attention`` forces the
    reference path off TPU too)."""
    from ..framework import flags
    platform = jax.devices()[0].platform
    use_kernel = (platform == "tpu"
                  and bool(int(flags.flag(
                      "FLAGS_use_pallas_paged_attention"))))
    if use_kernel:
        import warnings
        try:
            from jax.experimental.pallas.ops.tpu.paged_attention import (
                paged_attention as _kernel)
            s = scale if scale is not None else 1.0 / math.sqrt(
                q.shape[-1])
            pages_per_seq = block_tables.shape[1]
            ppcb = next(c for c in (8, 4, 2, 1)
                        if pages_per_seq % c == 0)
            # the kernel applies no softmax scale — fold it into q; it
            # also indexes with int32 internally, so int64 tables/lens
            # (the paddle default int dtype) must be cast AND the trace
            # must run with x64 promotion off (kernel-internal python
            # ints otherwise promote to i64 and its lax.div mixes
            # dtypes) — same contract as the other pallas kernels
            from .pallas._utils import no_x64
            with no_x64():
                return _kernel(q * jnp.asarray(s, q.dtype), key_pages,
                               value_pages,
                               context_lens.astype(jnp.int32),
                               block_tables.astype(jnp.int32),
                               pages_per_compute_block=ppcb)
        except Exception as e:
            warnings.warn(
                f"Pallas paged-attention kernel unavailable "
                f"({type(e).__name__}: {e}); using the jnp reference "
                f"path", RuntimeWarning)
    return paged_attention_reference(q, key_pages, value_pages,
                                     block_tables, context_lens, scale)


def paged_decode_write(kp, vp, k, v, block_tables, ctx, active=None):
    """Write one decode step's k/v into the page pools.

    k, v: [B, 1, KVH, D] (the step's projections, already rotated).
    ctx: [B] int32 — current cache length per slot; the new token lands at
    position ctx. Inactive slots (``active`` False) write to page 0 — the
    engine reserves it as a trash page so a freed/reassigned real page is
    never clobbered by a drained slot."""
    page = kp.shape[2]
    pid = jnp.take_along_axis(block_tables,
                              (ctx // page)[:, None], axis=1)[:, 0]
    if active is not None:
        pid = jnp.where(active, pid, 0)
    off = ctx % page
    kp = kp.at[:, pid, off, :].set(jnp.swapaxes(k[:, 0], 0, 1))
    vp = vp.at[:, pid, off, :].set(jnp.swapaxes(v[:, 0], 0, 1))
    return kp, vp


def pack_prompt_into_pages(kp, vp, k_dense, v_dense, slot_tables):
    """Scatter a prefilled dense cache into the slot's pages.

    k_dense, v_dense: [1, S, KVH, D] (positions 0..S-1 of one sequence);
    slot_tables: [pages_per_slot] int32 — must cover ceil(S/page) pages.
    Positions beyond the true prompt length may hold pad garbage; the
    per-slot context length masks them at attention time."""
    s = k_dense.shape[1]
    page = kp.shape[2]
    pid = jnp.take(slot_tables, jnp.arange(s) // page)
    off = jnp.arange(s) % page
    kp = kp.at[:, pid, off, :].set(jnp.swapaxes(k_dense[0], 0, 1))
    vp = vp.at[:, pid, off, :].set(jnp.swapaxes(v_dense[0], 0, 1))
    return kp, vp
