"""Paged attention for serving-time autoregressive decode.

Role of the reference inference engine's paged/ragged KV-cache attention
(Paddle Inference fused attention ops + PaddleNLP serving kernels,
UNVERIFIED — reference mount empty). The KV cache is stored as fixed-size
*pages* in a global pool; each sequence owns a list of pages (its block
table), so cache memory is allocated per-page instead of per-max-length —
the vLLM/TPU-serving design (see PAPERS.md ragged-paged-attention).

TPU-native: the fast path is the Pallas TPU paged-attention kernel that
ships with jax (``jax.experimental.pallas.ops.tpu.paged_attention``, a
scalar-prefetch kernel that streams only the pages named in the block
table through VMEM). The reference path below is pure jnp (gather +
masked softmax) — the numeric oracle and the CPU/debug fallback.

Layouts (decode step, one query token per sequence):
  q            [B, H, D]
  key_pages    [KVH, num_pages, page_size, D]
  value_pages  [KVH, num_pages, page_size, D]
  block_tables [B, pages_per_seq] int32 — page ids, row-padded with any
               valid id past the sequence's last page
  context_lens [B] int32 — tokens currently in cache per sequence
GQA/MQA: H a multiple of KVH; q head h attends kv head h // (H // KVH).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["paged_attention", "paged_attention_reference",
           "paged_prefill_attention", "paged_prefill_attention_reference",
           "ragged_paged_attention", "ragged_paged_attention_reference",
           "paged_decode_write", "paged_prefill_write",
           "paged_verify_write", "kv_quant_range", "quantize_kv",
           "dequantize_pages", "paged_prefill_write_quant",
           "paged_verify_write_quant"]

_NEG_INF = -1e30


def kv_quant_range(dtype):
    """Symmetric quantization range for a quantized-KV pool dtype: the
    largest magnitude a quantized code can carry, so ``scale = absmax /
    range``. The quant MODE is inferred from the pool dtype everywhere
    (no extra traced operand through the compiled batching step)."""
    dtype = jnp.dtype(dtype)
    if dtype == jnp.int8:
        return 127.0       # symmetric, the reference skips -128
    if "float8_e4m3" in dtype.name:
        return 448.0       # e4m3 finite max
    raise ValueError(f"not a quantized KV pool dtype: {dtype}")


def quantize_kv(x, dtype):
    """Per-vector absmax quantization of k/v projections: x [..., D]
    float -> (q [..., D] ``dtype``, scales [...] float32) with
    ``dequant = q.astype(f32) * scale``. One scale per (token, kv head)
    — written WITH the token, so decode appends into a partially filled
    page never requantize earlier tokens (write-once discipline)."""
    r = kv_quant_range(dtype)
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scales = jnp.where(amax > 0, amax, 1.0) / r
    y = xf / scales[..., None]
    if jnp.dtype(dtype) == jnp.int8:
        q = jnp.clip(jnp.round(y), -127.0, 127.0).astype(jnp.int8)
    else:
        q = y.astype(dtype)
    return q, scales


def dequantize_pages(pages, scales):
    """Quantized pool -> f32: pages [KVH, P, page, D] x scales
    [KVH, P, page] (the page-parallel scales pool) -> f32 pages."""
    return pages.astype(jnp.float32) * scales.astype(jnp.float32)[..., None]


def paged_attention_reference(q, key_pages, value_pages, block_tables,
                              context_lens, scale=None):
    """Pure-jnp oracle: gather each sequence's pages, mask, soft-max."""
    b, h, d = q.shape
    kvh, _, page_size, _ = key_pages.shape
    rep = h // kvh
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    max_len = block_tables.shape[1] * page_size

    def one_seq(qi, table, ctx_len):
        # [KVH, pages_per_seq, page, D] -> [KVH, max_len, D]
        k = key_pages[:, table].reshape(kvh, max_len, d)
        v = value_pages[:, table].reshape(kvh, max_len, d)
        k = jnp.repeat(k, rep, axis=0)  # [H, max_len, D]
        v = jnp.repeat(v, rep, axis=0)
        logits = jnp.einsum("hd,hkd->hk", qi, k,
                            preferred_element_type=jnp.float32) * s
        mask = jnp.arange(max_len) < ctx_len
        logits = jnp.where(mask[None, :], logits, _NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
        return jnp.einsum("hk,hkd->hd", probs, v)

    return jax.vmap(one_seq)(q, block_tables, context_lens)


def paged_attention(q, key_pages, value_pages, block_tables, context_lens,
                    scale=None):
    """Decode-step paged attention; Pallas kernel on TPU, jnp oracle
    elsewhere (flag ``FLAGS_use_pallas_paged_attention`` forces the
    reference path off TPU too)."""
    from ..framework import flags
    platform = jax.devices()[0].platform
    use_kernel = (platform == "tpu"
                  and bool(int(flags.flag(
                      "FLAGS_use_pallas_paged_attention"))))
    if use_kernel:
        import warnings
        try:
            from jax.experimental.pallas.ops.tpu.paged_attention import (
                paged_attention as _kernel)
            s = scale if scale is not None else 1.0 / math.sqrt(
                q.shape[-1])
            pages_per_seq = block_tables.shape[1]
            ppcb = next(c for c in (8, 4, 2, 1)
                        if pages_per_seq % c == 0)
            # the kernel applies no softmax scale — fold it into q; it
            # also indexes with int32 internally, so int64 tables/lens
            # (the paddle default int dtype) must be cast AND the trace
            # must run with x64 promotion off (kernel-internal python
            # ints otherwise promote to i64 and its lax.div mixes
            # dtypes) — same contract as the other pallas kernels
            from .pallas._utils import no_x64
            with no_x64():
                return _kernel(q * jnp.asarray(s, q.dtype), key_pages,
                               value_pages,
                               context_lens.astype(jnp.int32),
                               block_tables.astype(jnp.int32),
                               pages_per_compute_block=ppcb)
        except Exception as e:
            warnings.warn(
                f"Pallas paged-attention kernel unavailable "
                f"({type(e).__name__}: {e}); using the jnp reference "
                f"path", RuntimeWarning)
    return paged_attention_reference(q, key_pages, value_pages,
                                     block_tables, context_lens, scale)


def paged_prefill_attention_reference(q, key_pages, value_pages,
                                      block_tables, context_lens,
                                      scale=None, k_scales=None,
                                      v_scales=None):
    """Pure-jnp oracle for CHUNKED prefill over the page pool.

    q: [B, C, H, D] — C query tokens per sequence whose k/v have already
    been written into the pages at positions ``ctx .. ctx+C-1`` (see
    :func:`paged_prefill_write`). ``context_lens`` [B] is the cache
    length BEFORE the chunk; query token j attends every cache position
    ``<= ctx + j`` — full paged history behind it, causal within the
    chunk. With C == 1 this reduces exactly to the decode oracle called
    as ``paged_attention(q[:, 0], ..., ctx + 1)``.

    Per-query masking is over the SAME gathered [max_len] axis the
    decode oracle uses, so chunked and whole-prompt prefill reduce in
    the same order — the basis of the token-parity guarantee.

    ``k_scales``/``v_scales`` [KVH, num_pages, page_size] f32 mark the
    pools as quantized (int8/fp8): pages are dequantized to f32 right
    after the gather — the same block-table indirection, so trash-page
    routing and page sharing compose unchanged — and the output is cast
    back to q's dtype.
    """
    b, c, h, d = q.shape
    kvh, _, page_size, _ = key_pages.shape
    quantized = k_scales is not None
    if quantized:
        key_pages = dequantize_pages(key_pages, k_scales)
        value_pages = dequantize_pages(value_pages, v_scales)
    rep = h // kvh
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    max_len = block_tables.shape[1] * page_size

    def one_seq(qi, table, ctx_len):
        # [KVH, pages_per_seq, page, D] -> [KVH, max_len, D]
        k = key_pages[:, table].reshape(kvh, max_len, d)
        v = value_pages[:, table].reshape(kvh, max_len, d)
        k = jnp.repeat(k, rep, axis=0)  # [H, max_len, D]
        v = jnp.repeat(v, rep, axis=0)
        logits = jnp.einsum("chd,hkd->chk", qi, k,
                            preferred_element_type=jnp.float32) * s
        allow = (jnp.arange(max_len)[None, :]
                 <= (ctx_len + jnp.arange(c))[:, None])   # [C, max_len]
        logits = jnp.where(allow[:, None, :], logits, _NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
        return jnp.einsum("chk,hkd->chd", probs, v)

    out = jax.vmap(one_seq)(q, block_tables, context_lens)
    return out.astype(q.dtype) if quantized else out


def paged_prefill_attention(q, key_pages, value_pages, block_tables,
                            context_lens, scale=None):
    """Multi-token-query paged attention (chunked prefill) — every
    chunk token treated as valid. Kept as the whole-chunk entry point;
    the serving hot path goes through :func:`ragged_paged_attention`,
    which adds per-sequence valid counts (mixed prefill+decode+idle
    slots in one call) and the Pallas kernel dispatch."""
    b, c = q.shape[0], q.shape[1]
    lengths = jnp.full((b,), c, jnp.int32)
    return ragged_paged_attention(q, key_pages, value_pages,
                                  block_tables, context_lens, lengths,
                                  scale)


def ragged_paged_attention_reference(q, key_pages, value_pages,
                                     block_tables, ctx_lens, lengths,
                                     scale=None, k_scales=None,
                                     v_scales=None):
    """Pure-jnp oracle for the RAGGED mixed prefill+decode batching
    step: q [B, C, H, D] is the uniform-stride view of the flattened
    token stream (slot b's tokens are the ``[start=b*C, length=
    lengths[b]]`` window), ``ctx_lens`` the cache length BEFORE the
    chunk, ``lengths`` the per-slot valid token count — 0 (idle slot),
    1 (decode step) or >1 (prefill chunk) all flow through the same
    reduction. Rows past the valid count are zeroed.

    Reduces over the SAME gathered [max_len] axis as the prefill and
    decode oracles (it *is* the prefill oracle plus the validity mask),
    so with lengths == C it equals
    :func:`paged_prefill_attention_reference` exactly and with
    lengths == 1 it reduces exactly to the decode oracle at ctx+1 —
    the basis of the kernel parity tests."""
    c = q.shape[1]
    out = paged_prefill_attention_reference(
        q, key_pages, value_pages, block_tables, ctx_lens, scale,
        k_scales=k_scales, v_scales=v_scales)
    valid = jnp.arange(c)[None, :] < lengths[:, None]      # [B, C]
    return jnp.where(valid[:, :, None, None], out, 0).astype(out.dtype)


def ragged_paged_attention(q, key_pages, value_pages, block_tables,
                           ctx_lens, lengths, scale=None,
                           k_scales=None, v_scales=None):
    """Mixed prefill+decode paged attention — the serving engine's ONE
    attention entry point (PAPERS.md ragged-paged-attention). Pallas
    kernel on TPU (``FLAGS_use_pallas_ragged_attention``), jnp oracle
    elsewhere; the kernel module itself always runs (interpret mode)
    in the parity tests, the flash_attention discipline."""
    from ..framework import flags
    platform = jax.devices()[0].platform
    use_kernel = (platform == "tpu"
                  and bool(int(flags.flag(
                      "FLAGS_use_pallas_ragged_attention"))))
    if use_kernel:
        import warnings
        try:
            from .pallas.ragged_paged_attention import (
                ragged_paged_attention as _kernel)
            return _kernel(q, key_pages, value_pages, block_tables,
                           ctx_lens, lengths, scale,
                           k_scales=k_scales, v_scales=v_scales)
        except Exception as e:
            warnings.warn(
                f"Pallas ragged paged-attention kernel unavailable "
                f"({type(e).__name__}: {e}); using the jnp reference "
                f"path", RuntimeWarning)
    return ragged_paged_attention_reference(
        q, key_pages, value_pages, block_tables, ctx_lens, lengths,
        scale, k_scales=k_scales, v_scales=v_scales)


def paged_decode_write(kp, vp, k, v, block_tables, ctx, active=None):
    """Write one decode step's k/v into the page pools.

    k, v: [B, 1, KVH, D] (the step's projections, already rotated).
    ctx: [B] int32 — current cache length per slot; the new token lands at
    position ctx. Inactive slots (``active`` False) write to page 0 — the
    engine reserves it as a trash page so a freed/reassigned real page is
    never clobbered by a drained slot."""
    page = kp.shape[2]
    pid = jnp.take_along_axis(block_tables,
                              (ctx // page)[:, None], axis=1)[:, 0]
    if active is not None:
        pid = jnp.where(active, pid, 0)
    off = ctx % page
    kp = kp.at[:, pid, off, :].set(jnp.swapaxes(k[:, 0], 0, 1))
    vp = vp.at[:, pid, off, :].set(jnp.swapaxes(v[:, 0], 0, 1))
    return kp, vp


def paged_prefill_write(kp, vp, k, v, block_tables, ctx, valid):
    """Write one prefill chunk's k/v into the page pools.

    k, v: [B, C, KVH, D] (the chunk's projections, already rotated).
    Token j of sequence b lands at global position ``ctx[b] + j`` in its
    block-table row; tokens with ``j >= valid[b]`` (chunk padding, or a
    slot not in this prefill wave) are routed to the reserved trash page
    0 so a real page is never clobbered."""
    c = k.shape[1]
    page = kp.shape[2]
    pos = ctx[:, None] + jnp.arange(c, dtype=ctx.dtype)[None, :]  # [B, C]
    # padded positions can run past the table row — clamp the page index
    # (the write is trash-routed anyway) so the gather stays in bounds
    pidx = jnp.minimum(pos // page, block_tables.shape[1] - 1)
    pid = jnp.take_along_axis(block_tables, pidx, axis=1)         # [B, C]
    ok = jnp.arange(c)[None, :] < valid[:, None]
    pid = jnp.where(ok, pid, 0)
    off = pos % page
    kp = kp.at[:, pid, off, :].set(jnp.transpose(k, (2, 0, 1, 3)))
    vp = vp.at[:, pid, off, :].set(jnp.transpose(v, (2, 0, 1, 3)))
    return kp, vp


def paged_verify_write(kp, vp, k, v, block_tables, ctx, valid):
    """Multi-token speculative VERIFY write (ISSUE 18): write a
    ``1 + K``-token verification chunk's k/v — the pending token plus
    ``K`` draft tokens — into positions ``ctx .. ctx + K`` of each
    slot's block-table row, BEFORE knowing how many drafts the target
    will accept.

    Rollback-safe page commit, by construction rather than by an undo
    log:

    - **Reads are fenced by ctx.** Every attention entry point masks
      cache reads to positions ``<= ctx + j`` for query token ``j``,
      and the engine only ever advances its committed ``ctx`` mirror by
      the ACCEPTED length. KV written past the accepted position is
      therefore unreachable — no future query can attend it.
    - **Writes overwrite in place.** The next chunk for the slot starts
      at the committed ``ctx`` and re-writes those same page offsets,
      so rejected-draft garbage has the lifetime of one scheduler turn.
    - **Sharing is prompt-only.** The prefix cache publishes full pages
      of PROMPT tokens at prefill completion; decode/verify positions
      live past ``len(prompt)`` in COW-private pages, so a rejected
      draft can never leak into a page another sequence attaches.

    Accepting tokens is thus a pure bookkeeping commit (advance ctx);
    rejecting is a no-op. The write routing itself is identical to a
    short prefill chunk — token ``j >= valid`` is trash-routed to page
    0 and out-of-row positions are clamped — because a verification
    chunk IS a short prefill chunk to the page pool."""
    return paged_prefill_write(kp, vp, k, v, block_tables, ctx, valid)


def paged_prefill_write_quant(kp, vp, ks, vs, k, v, block_tables, ctx,
                              valid):
    """Quantize-at-write prefill chunk write for quantized KV pools.

    kp, vp: [KVH, num_pages, page_size, D] int8 (or fp8) data pools;
    ks, vs: [KVH, num_pages, page_size] f32 page-parallel scales pools.
    k, v: [B, C, KVH, D] float projections (already rotated). The quant
    mode rides the pool dtype (:func:`kv_quant_range`) and each token's
    per-kv-head scale is written at the SAME (page, offset) its data
    lands at, so the scales ride the block-table indirection unchanged:
    trash-routed padding writes its scale to trash page 0, COW forks
    copy the scale page with the data page, and preemption replay
    rewrites both."""
    c = k.shape[1]
    page = kp.shape[2]
    qk, sk = quantize_kv(k, kp.dtype)       # [B, C, KVH, D] / [B, C, KVH]
    qv, sv = quantize_kv(v, vp.dtype)
    pos = ctx[:, None] + jnp.arange(c, dtype=ctx.dtype)[None, :]  # [B, C]
    pidx = jnp.minimum(pos // page, block_tables.shape[1] - 1)
    pid = jnp.take_along_axis(block_tables, pidx, axis=1)         # [B, C]
    ok = jnp.arange(c)[None, :] < valid[:, None]
    pid = jnp.where(ok, pid, 0)
    off = pos % page
    kp = kp.at[:, pid, off, :].set(jnp.transpose(qk, (2, 0, 1, 3)))
    vp = vp.at[:, pid, off, :].set(jnp.transpose(qv, (2, 0, 1, 3)))
    ks = ks.at[:, pid, off].set(jnp.transpose(sk, (2, 0, 1))
                                .astype(ks.dtype))
    vs = vs.at[:, pid, off].set(jnp.transpose(sv, (2, 0, 1))
                                .astype(vs.dtype))
    return kp, vp, ks, vs


def paged_verify_write_quant(kp, vp, ks, vs, k, v, block_tables, ctx,
                             valid):
    """Speculative verify write into quantized pools — the same
    rollback-safety argument as :func:`paged_verify_write` (reads are
    fenced by ctx, writes overwrite in place, sharing is prompt-only)
    holds per-token for the scales too, since a scale is only ever read
    together with the data it was written with."""
    return paged_prefill_write_quant(kp, vp, ks, vs, k, v, block_tables,
                                     ctx, valid)
